"""IndexService: one index = N shards + mapping + routing + search fan-out.

Role model: ``IndexService`` (core/.../index/IndexService.java) for shard
ownership, ``OperationRouting`` (cluster/routing/OperationRouting.java:232)
for doc->shard routing, and ``TransportSearchAction`` +
``SearchPhaseController`` for the scatter-gather + merge. In the
single-node path the "network boundary" between coordinator and shards is
a method call; the distributed path (parallel/) replaces the per-shard
loop with a shard_map over a device mesh.
"""

from __future__ import annotations

import copy
import os
import time
from typing import Dict, List, Optional

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.common.errors import (
    DocumentMissingException,
    IllegalArgumentException,
    SearchPhaseExecutionException,
    TaskCancelledException,
)
from elasticsearch_tpu.common.settings import (
    INDEX_NUMBER_OF_REPLICAS,
    INDEX_NUMBER_OF_SHARDS,
    INDEX_TRANSLOG_DURABILITY,
    Settings,
)
from elasticsearch_tpu.common.integrity import integrity_service
from elasticsearch_tpu.index.shard import IndexShard
from elasticsearch_tpu.index.store import CorruptIndexException
from elasticsearch_tpu.mapper.mapping import MapperService
from elasticsearch_tpu.search.aggregations import parse_aggs, run_aggregations
from elasticsearch_tpu.search.service import fetch_hits, merge_refs, normalize_sort
from elasticsearch_tpu.utils.murmur3 import shard_id_for


class IndexService:
    def __init__(self, name: str, settings: Settings = Settings.EMPTY,
                 mapping: Optional[dict] = None, data_path: Optional[str] = None):
        self.name = name
        # 6.x single-type name (custom names deprecated, echoed in
        # document/search/mapping responses; _doc canonical)
        self.doc_type = "_doc"
        self.settings = settings
        self.creation_date = int(time.time() * 1000)
        self.uuid = f"{name}-{self.creation_date:x}"
        self.num_shards = INDEX_NUMBER_OF_SHARDS.get(settings)
        self.num_replicas = INDEX_NUMBER_OF_REPLICAS.get(settings)
        self.analyzers = AnalysisRegistry(settings)
        from elasticsearch_tpu.index.similarity import SimilarityService
        self.mapper_service = MapperService(
            self.analyzers, mapping,
            similarity_service=SimilarityService(settings),
            dense_vector_max_dims=settings.get_int(
                "index.mapping.dense_vector.max_dims", 1024))
        self.data_path = data_path
        from elasticsearch_tpu.index.index_sort import parse_index_sort
        self.index_sort = parse_index_sort(settings, self.mapper_service)
        durability = INDEX_TRANSLOG_DURABILITY.get(settings)
        slowlog_warn = settings.get_time("index.search.slowlog.threshold.query.warn")
        slowlog_info = settings.get_time("index.search.slowlog.threshold.query.info")
        # index-level search slowlog thresholds for mesh-plane-served
        # queries (no ShardSearcher runs there); negative = disabled
        self._slowlog_warn_s = (slowlog_warn if slowlog_warn is not None
                                and slowlog_warn >= 0 else None)
        self._slowlog_info_s = (slowlog_info if slowlog_info is not None
                                and slowlog_info >= 0 else None)
        idx_slow_warn = settings.get_time(
            "index.indexing.slowlog.threshold.index.warn")
        idx_slow_info = settings.get_time(
            "index.indexing.slowlog.threshold.index.info")
        idx_slow_source = settings.get_int("index.indexing.slowlog.source", 1000)
        gc_deletes = settings.get_time("index.gc_deletes")
        self.shards: Dict[int, IndexShard] = {}
        for sid in range(self.num_shards):
            shard_path = os.path.join(data_path, str(sid)) if data_path else None
            shard = IndexShard(name, sid, self.mapper_service, shard_path,
                               durability=durability,
                               slowlog_warn_s=slowlog_warn,
                               slowlog_info_s=slowlog_info,
                               index_sort=self.index_sort,
                               indexing_slowlog_warn_s=idx_slow_warn,
                               indexing_slowlog_info_s=idx_slow_info,
                               indexing_slowlog_source_chars=idx_slow_source)
            if gc_deletes is not None:
                shard.engine.gc_deletes = gc_deletes
            # postings codec preference for the tile-kernel staging
            # (index.search.pallas.postings_codec; docs/PRUNING.md):
            # "default" follows the node-wide ES_TPU_PALLAS_CODEC export
            shard.engine.postings_codec = settings.get_str(
                "index.search.pallas.postings_codec", "default")
            # slice resolution is shard-count-aware (SliceBuilder)
            shard.searcher.num_shards = self.num_shards
            shard.searcher.max_slices = settings.get_int(
                "index.max_slices_per_scroll", 1024)
            self.shards[sid] = shard
            try:
                if shard_path and shard.engine.store.read_commit() is not None:
                    shard.recover_from_store()
                elif shard_path and os.path.exists(
                    os.path.join(shard_path, "translog", "translog.ckp")
                ):
                    shard.recover_from_store()
                else:
                    shard.start_fresh()
            except CorruptIndexException as e:
                # boot over corrupt/marked bytes (ISSUE 16): quarantine
                # the copy instead of crashing index open — the shard
                # stays allocated but every query against it fails into
                # failures[] (never silent empty hits), and a healthy
                # copy elsewhere (replica / snapshot) is the way back
                self._quarantine_shard(sid, e, site="load")
        # periodic NRT refresh (index.refresh_interval, default 1s; -1
        # disables — IndexService#getRefreshInterval + refresh scheduling)
        # mesh-executed query phase (parallel/plan_exec.IndexMeshSearch):
        # lazy — staged on the first eligible search; the setting gates it
        # (index.search.mesh: true default; false = host merge only)
        self._mesh_search = None
        self._mesh_enabled = settings.get_bool("index.search.mesh", True)
        # cross-query micro-batching (search/batching.py; docs/BATCHING.md):
        # concurrent compatible searches share one batched kernel launch on
        # the mesh_pallas / host-pallas rungs. A query with no concurrency
        # takes the unbatched path with zero added latency.
        from elasticsearch_tpu.search.batching import BatchStats, MicroBatcher

        self.batch_stats = BatchStats()
        self._batcher = MicroBatcher(
            window_s=settings.get_float("search.batch.window_ms", 0.2)
            / 1000.0,
            max_queries=settings.get_int("search.batch.max_queries", 16),
            enabled=settings.get_bool("search.batch.enabled", True),
            stats=self.batch_stats)
        # phase-attributed query telemetry (search/telemetry.py,
        # docs/OBSERVABILITY.md): always-on span tracing drained into
        # per-plane × per-phase histograms; search.telemetry.enabled is
        # the dynamic kill switch
        from elasticsearch_tpu.search.telemetry import SearchTelemetry

        self.telemetry = SearchTelemetry()
        # multi-tenant overload control (search/admission.py, ISSUE 12,
        # docs/OVERLOAD.md): bounded admission queue + per-tenant DRR +
        # the brownout ladder, consulted at dispatch before any
        # staging/launch work; also sizes the batcher's ADAPTIVE window
        from elasticsearch_tpu.search.admission import (
            SearchAdmissionController,
        )

        self.admission = SearchAdmissionController(name, settings)
        self._batcher.window_fn = (
            lambda: self.admission.effective_batch_window_s(
                self._batcher.window_s))
        # device-memory budget (search.memory.hbm_budget_bytes, ISSUE 9):
        # the accountant is a process resource — an explicitly-set value
        # here (node-file seed / direct-service tests) configures it, the
        # same way node startup and PUT _cluster/settings do
        if settings.get("search.memory.hbm_budget_bytes") is not None:
            from elasticsearch_tpu.common.memory import memory_accountant

            memory_accountant().set_budget(
                settings.get_bytes("search.memory.hbm_budget_bytes", 0))
        # batch items are (body, deadline, tracer): stamp window-wait +
        # batch shape onto each member's tracer at dispatch time
        self._batcher.annotate = self._annotate_batch_member
        import threading as _threading

        self._stats_lock = _threading.Lock()
        # shard request cache (IndicesRequestCache.java:64): size==0
        # (agg/count) responses cached against the shards' visibility
        # epochs; index.requests.cache.enable gates it (default on)
        from elasticsearch_tpu.index.request_cache import RequestCache

        self._request_cache_enabled = settings.get_bool(
            "index.requests.cache.enable", True)
        # stats counters (IndexingStats/GetStats/RefreshStats/FlushStats)
        self._get_total = 0
        self._refresh_total = 0
        self._host_query_total = 0
        # legacy _parent metadata field values (ParentFieldMapper):
        # doc_id -> parent id, surfaced via stored_fields [_parent].
        # Values persist with the document (translog/store record
        # alongside routing) and are rebuilt here after recovery.
        self.parents: Dict[str, str] = {}
        self._rebuild_parents()
        self._flush_total = 0
        cache_bytes = settings.get_int(
            "index.requests.cache.size_in_bytes", 8 * 1024 * 1024)
        self.request_cache = RequestCache(max_bytes=cache_bytes)
        iv = settings.get_time("index.refresh_interval")
        self.refresh_interval = 1.0 if iv is None else iv
        self._refresh_stop = None
        if self.refresh_interval and self.refresh_interval > 0:
            import threading

            self._refresh_stop = threading.Event()

            import logging

            logger = logging.getLogger("elasticsearch_tpu.index.refresh")

            def _refresh_loop():
                while not self._refresh_stop.wait(self.refresh_interval):
                    for s in list(self.shards.values()):
                        try:
                            s.refresh()
                        except Exception:
                            # a closing shard can race the timer; anything
                            # else must be visible to the operator
                            logger.warning(
                                "[%s][%s] scheduled refresh failed",
                                name, s.shard_id, exc_info=True)

            threading.Thread(target=_refresh_loop, daemon=True,
                             name=f"refresh[{name}]").start()
        # background store/device scrubber (ISSUE 16, docs/RESILIENCE.md
        # "Data integrity"): index.scrub.interval, off by default. The
        # thread always runs (cheap idle poll) so turning the knob on
        # dynamically — via _settings or the cluster-level override —
        # needs no thread lifecycle management; each wake re-reads the
        # effective interval.
        import threading as _scrub_threading

        self.scrub_interval_override: Optional[float] = None
        self._scrub_stop = _scrub_threading.Event()
        _scrub_threading.Thread(target=self._scrub_loop, daemon=True,
                                name=f"scrub[{name}]").start()
        # background slot compaction (ISSUE 20): no polling loop — the
        # mesh plane nudges maybe_compact_async() after a delta commit;
        # the lock makes the pass single-flight (a second trigger while
        # one runs is a no-op, never a queue)
        self.staging_delta_enabled_override: Optional[bool] = None
        self.staging_compact_threshold_override: Optional[float] = None
        self._compact_lock = _scrub_threading.Lock()
        self._closing = False

    def _rebuild_parents(self) -> None:
        """Re-derive the _parent registry from recovered shard state: the
        sealed segments' per-doc parent column and the (translog-replayed)
        buffer — so stored_fields [_parent] survives restart/restore
        (round-5 advisor finding: the registry was memory-only)."""
        for shard in self.shards.values():
            eng = shard.engine
            for seg in eng.segments:
                parents = getattr(seg, "parents", None)
                if not parents:
                    continue
                for local, doc_id in enumerate(seg.doc_ids):
                    p = parents[local] if local < len(parents) else None
                    if p is not None and seg.live[local]:
                        self.parents[str(doc_id)] = str(p)
            buf = eng.buffer
            for local, p in enumerate(getattr(buf, "parents", []) or []):
                if p is not None and local not in eng._buffer_deletes:
                    self.parents[str(buf.doc_ids[local])] = str(p)

    # ------------------------------------------------------------------
    # Corruption quarantine + the background scrubber (ISSUE 16)
    # ------------------------------------------------------------------

    def _quarantine_shard(self, sid: int, exc: Exception,
                          site: str = "query") -> None:
        """Quarantine a corrupt shard copy (Store.markStoreCorrupted +
        IndexShard#failShard parity): write the ``corrupted_*`` marker
        (once — first cause wins), record the detection, flag the shard
        so the query path fails it into failures[] per the PR-4 partial
        contract, and release the copy's device staging through the
        PR-9 accountant — a quarantined copy must not pin HBM, and the
        ledger must return to baseline exactly (no leak)."""
        shard = self.shards.get(sid)
        if shard is None:
            return
        store = shard.engine.store
        integ = integrity_service()
        integ.record_corruption(self.name, sid, site, str(exc))
        already = store.is_corrupted()
        marker = store.mark_corrupted(str(exc), site=site)
        if not already:
            integ.record_marker(self.name, sid, marker, action="marked")
        shard.store_corrupted = True
        for seg in list(shard.engine.segments):
            try:
                seg.release_device_staging()
            except Exception:  # noqa: BLE001 — release is best-effort
                pass  # the index-level release_index backstop covers it

    def unquarantine_shard(self, sid: int) -> None:
        """A successful re-recovery installed a verified byte set over
        the quarantined copy: clear the markers + flag (the ONLY legal
        transition out of quarantine — never called on load)."""
        shard = self.shards.get(sid)
        if shard is None:
            return
        store = shard.engine.store
        for marker in store.corruption_markers():
            integrity_service().record_marker(
                self.name, sid, marker, action="cleared")
        store.clear_corruption_markers()
        shard.store_corrupted = False

    def _scrub_effective_interval(self) -> Optional[float]:
        """Cluster-level override wins when an operator committed one
        (explicitness contract, mirroring the other dynamic knobs);
        otherwise the index setting. None/<=0 disables."""
        if self.scrub_interval_override is not None:
            return self.scrub_interval_override
        return self.settings.get_time("index.scrub.interval")

    def _scrub_loop(self) -> None:
        import logging

        logger = logging.getLogger("elasticsearch_tpu.index.scrub")
        while True:
            iv = self._scrub_effective_interval()
            wait = iv if iv is not None and iv > 0 else 5.0
            if self._scrub_stop.wait(wait):
                return
            iv = self._scrub_effective_interval()
            if iv is None or iv <= 0:
                continue  # disabled (or disabled mid-wait): idle poll
            try:
                self.scrub_now()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.warning("[%s] scrub pass failed", self.name,
                               exc_info=True)

    def scrub_now(self) -> dict:
        """One synchronous scrubber pass (the loop body; tests call it
        directly for determinism). Two checks per shard:

        - **disk**: re-verify every committed segment's checksums
          recursively (sealed files are immutable — any mismatch is
          at-rest corruption) → quarantine with site=``scrub``;
        - **device drift**: digest device-staged base tables
          (block_docs / block_tfs / norms) against host truth cast to
          the staged dtype — drift invalidates the staging (restage
          classifies with the ``scrub`` lifecycle reason) and counts,
          never serves.
        """
        import hashlib

        import numpy as np

        bytes_verified = 0
        checksum_failures = 0
        drift_count = 0
        for sid, shard in list(self.shards.items()):
            store = shard.engine.store
            if getattr(shard, "store_corrupted", False) \
                    or store.is_corrupted():
                continue  # already quarantined — heal, don't re-verify
            commit = store.read_commit() or {}
            for seg_name in commit.get("segments", []):
                try:
                    bytes_verified += store.verify_segment(seg_name)
                except CorruptIndexException as e:
                    checksum_failures += 1
                    self._quarantine_shard(sid, e, site="scrub")
                    break
                except OSError:
                    continue  # raced a concurrent merge/commit GC
            if getattr(shard, "store_corrupted", False):
                continue
            for seg in list(shard.engine.segments):
                dev = getattr(seg, "_device", None)
                if not dev:
                    continue
                for key, host in (("block_docs", seg.block_docs),
                                  ("block_tfs", seg.block_tfs),
                                  ("norms", seg.norms)):
                    staged = dev.get(key)
                    if staged is None:
                        continue
                    dev_np = np.asarray(staged)
                    bytes_verified += int(dev_np.nbytes)
                    # host truth cast to the staged dtype: staging used
                    # the same conversion, so a clean table matches
                    # bit-for-bit and x64 downcasts never false-positive
                    host_np = np.asarray(host).astype(dev_np.dtype,
                                                      copy=False)
                    if (hashlib.sha256(dev_np.tobytes()).digest()
                            != hashlib.sha256(host_np.tobytes()).digest()):
                        drift_count += 1
                        integrity_service().record_scrub_drift(
                            self.name, sid, seg.name, key)
                        # invalidate: the restage re-adopts host truth
                        # and classifies as `scrub` in the ledger ring
                        seg.stage_reason_initial = "scrub"
                        seg.release_device_staging()
                        break
        integrity_service().record_scrub_run(bytes_verified)
        return {"bytes_verified": bytes_verified,
                "checksum_failures": checksum_failures,
                "drift": drift_count}

    # ------------------------------------------------------------------
    # Background slot compaction (ISSUE 20)
    # ------------------------------------------------------------------

    def _compact_threshold(self) -> float:
        """index.staging.compact.threshold with the explicitness-aware
        cluster override on top; <= 0 disables compaction."""
        if self.staging_compact_threshold_override is not None:
            return float(self.staging_compact_threshold_override)
        return float(self.settings.get_float(
            "index.staging.compact.threshold", 0.25))

    def _compaction_due(self) -> bool:
        """Tombstone density or slot fragmentation crossed the
        threshold on the live staged generation (cheap: host-side
        counters only, no device work)."""
        threshold = self._compact_threshold()
        if threshold <= 0:
            return False
        ms = self._mesh_search
        stats = (ms.staging_slot_stats() if ms is not None else None)
        if not stats or not stats["slots"]:
            return False
        if any(s["tombstone_density"] >= threshold
               for s in stats["slots"]):
            return True
        # fragmentation: occupied slots beyond what the live docs need —
        # sparse slots (delete-heavy or many tiny appended segments)
        # waste HBM rows and merge-loop work; when the occupied count
        # exceeds the post-merge slot need by more than the threshold
        # fraction, a compaction pass would shrink the generation
        occupied = len(stats["slots"])
        needed = max(1, -(-sum(s["live"] for s in stats["slots"])
                          // max(max(s["docs"] for s in stats["slots"]),
                                 1)))
        return occupied > needed and (
            (occupied - needed) / occupied >= threshold)

    def maybe_compact_async(self) -> bool:
        """Delta-commit hook (called by the mesh plane, possibly under
        its stage lock): decide cheaply, then run the pass on a
        background thread — compaction never runs on the query path.
        Returns True when a pass was kicked off."""
        if (self._closing or self.admission.draining
                or not self._compaction_due()):
            return False
        if self._compact_lock.locked():
            return False  # single-flight: a pass is already running
        import threading as _t

        _t.Thread(target=self.compact_now, daemon=True,
                  name=f"compact[{self.name}]").start()
        return True

    def compact_now(self) -> dict:
        """One synchronous compaction pass (the background thread body;
        tests call it directly for determinism). Force-merges the
        tombstone-dense shards (expunging deletes), then restages a
        FRESH generation with fresh slot headroom and releases the old
        one — ledger-exact through the transactional staging path.
        Single-flight via ``_compact_lock``; interruptible by drain
        (docs/RESILIENCE.md): a drain beginning mid-pass aborts between
        shards, leaving a consistent (merely uncompacted) staging."""
        if not self._compact_lock.acquire(blocking=False):
            return {"ran": False, "reason": "already_running"}
        try:
            if self._closing:
                return {"ran": False, "reason": "closing"}
            if self.admission.draining:
                return {"ran": False, "reason": "draining"}
            threshold = self._compact_threshold()
            merged_shards = []
            for sid, shard in list(self.shards.items()):
                if self._closing:
                    return {"ran": False, "reason": "closing",
                            "merged_shards": merged_shards}
                if self.admission.draining:
                    return {"ran": False, "reason": "draining",
                            "merged_shards": merged_shards}
                eng = shard.engine
                total = sum(int(s.num_docs) for s in eng.segments)
                live = sum(int(s.live_doc_count) for s in eng.segments)
                dense = (total > 0 and threshold > 0
                         and (total - live) / total >= threshold)
                frag = len(eng.segments) > 1
                if dense or frag:
                    eng.force_merge(stage_reason="compaction")
                    merged_shards.append(sid)
            if self._closing:
                return {"ran": False, "reason": "closing",
                        "merged_shards": merged_shards}
            ms = self._mesh_search
            restaged = (ms.restage_for_compaction()
                        if ms is not None else False)
            if ms is not None:
                ms.note_compaction_run()
            return {"ran": True, "merged_shards": merged_shards,
                    "restaged": bool(restaged)}
        finally:
            self._compact_lock.release()

    # ------------------------------------------------------------------
    # Routing + document ops
    # ------------------------------------------------------------------

    def _route(self, doc_id: str, routing: Optional[str] = None) -> int:
        return shard_id_for(routing if routing is not None else doc_id,
                            self.num_shards)

    def index_doc(self, doc_id: str, source: dict, routing: Optional[str] = None,
                  parent: Optional[str] = None, **kw) -> dict:
        routing = self._check_join_routing(doc_id, source, routing)
        shard = self.shards[self._route(doc_id, routing)]
        r = shard.index_doc(doc_id, source, routing, parent=parent, **kw)
        if parent is not None:
            # the registry serves stored_fields [_parent]; the value also
            # rides the engine record (translog + segment) so it survives
            # restart/restore — rebuilt in _rebuild_parents()
            self.parents[str(doc_id)] = str(parent)
        return r

    def _check_join_routing(self, doc_id: str, source: dict,
                            routing: Optional[str]) -> Optional[str]:
        """Child docs of a join field MUST be colocated with their parent
        (modules/parent-join: RoutingMissingException when a child is
        indexed without routing). On multi-shard indices a missing routing
        is an error; we follow the reference and additionally default the
        routing to the parent id, which is always correct."""
        from elasticsearch_tpu.mapper.field_types import join_field_of

        jf = join_field_of(self.mapper_service)
        if jf is None:
            return routing
        value = source.get(jf.name)
        if not isinstance(value, (str, dict)):
            return routing
        try:
            name, parent = jf.parse_join(value)
        except Exception:
            return routing  # parse errors surface in the mapper with context
        if parent is None:
            return routing
        if routing is None:
            if self.num_shards > 1:
                raise IllegalArgumentException(
                    f"[routing] is missing for join field [{jf.name}]: child "
                    f"document [{doc_id}] must be routed to its parent's shard"
                )
            routing = parent
        return routing

    def get_doc(self, doc_id: str, routing: Optional[str] = None,
                realtime: bool = True):
        with self._stats_lock:
            self._get_total += 1
        shard = self.shards[self._route(doc_id, routing)]
        return shard.get_doc(doc_id, realtime=realtime)

    def delete_doc(self, doc_id: str, routing: Optional[str] = None, **kw) -> dict:
        shard = self.shards[self._route(doc_id, routing)]
        return shard.delete_doc(doc_id, **kw)

    def update_doc(self, doc_id: str, body: dict, routing: Optional[str] = None,
                   version: Optional[int] = None) -> dict:
        """Update API (action/update/TransportUpdateAction): partial doc
        merge, upsert, doc_as_upsert; scripted updates run painless over
        ctx._source with ctx.op semantics (UpdateHelper.executeScripts).
        ``version``: internal optimistic-concurrency check against the
        CURRENT doc version (UpdateRequest versioning)."""
        shard = self.shards[self._route(doc_id, routing)]
        existing = shard.get_doc(doc_id)
        if version is not None and existing.found \
                and existing.version != version:
            from elasticsearch_tpu.common.errors import (
                VersionConflictEngineException,
            )

            raise VersionConflictEngineException(
                doc_id, existing.version, version)
        if not existing.found:
            # upserts go through index_doc so join-routing checks apply
            if body.get("doc_as_upsert") and "doc" in body:
                return self.index_doc(doc_id, body["doc"], routing)
            if "upsert" in body:
                if "script" in body and body.get("scripted_upsert"):
                    return self._scripted_update(
                        doc_id, body, dict(body["upsert"]), routing,
                        version=0)
                return self.index_doc(doc_id, body["upsert"], routing)
            raise DocumentMissingException(self.name, doc_id)
        if "script" in body:
            # deep copy: engine.get returns the live buffer/segment source,
            # and a script may mutate nested objects then set ctx.op='none' —
            # a shallow copy would corrupt the stored doc in place, bypassing
            # versioning and the translog (same hazard _apply_byquery_script
            # guards against in index/reindex.py)
            return self._scripted_update(
                doc_id, body, copy.deepcopy(existing.source), routing,
                version=existing.version)
        if "doc" in body:
            merged = _deep_merge(dict(existing.source), body["doc"])
            if merged == existing.source and body.get("detect_noop", True):
                return {
                    "_index": self.name, "_id": doc_id,
                    "_version": existing.version, "result": "noop",
                }
            return self.index_doc(doc_id, merged, routing)
        raise DocumentMissingException(self.name, doc_id)

    def _scripted_update(self, doc_id: str, body: dict, source: dict,
                         routing: Optional[str], version: int) -> dict:
        from elasticsearch_tpu.common.errors import IllegalArgumentException
        from elasticsearch_tpu.script.expression import compile_script
        from elasticsearch_tpu.script.painless import execute_update_script

        spec = body["script"]
        script = compile_script(spec)
        if not hasattr(script, "run"):
            raise IllegalArgumentException(
                "update scripts must be painless (the numeric expression "
                "engine has no ctx mutation surface)")
        params = (spec.get("params") if isinstance(spec, dict) else None) or {}
        new_source, op = execute_update_script(
            script, source, params,
            doc_meta={"_index": self.name, "_id": doc_id,
                      "_version": version})
        if op == "none":
            return {"_index": self.name, "_id": doc_id,
                    "_version": version, "result": "noop"}
        if op == "delete":
            return self.delete_doc(doc_id, routing=routing)
        return self.index_doc(doc_id, new_source, routing)

    def refresh(self) -> None:
        with self._stats_lock:
            self._refresh_total += 1
        for shard in self.shards.values():
            shard.refresh()

    def flush(self) -> None:
        with self._stats_lock:
            self._flush_total += 1
        for shard in self.shards.values():
            shard.flush()

    def synced_flush(self) -> Dict[int, str]:
        """Flush + synced-flush marker per shard (ISSUE 14 graceful
        drain; the reference's _flush/synced): after it a warm restart
        over the same data path recovers ops-free. Returns
        {shard_id: sync_id}."""
        with self._stats_lock:
            self._flush_total += 1
        return {sid: shard.synced_flush()
                for sid, shard in self.shards.items()}

    def force_merge(self) -> None:
        for shard in self.shards.values():
            shard.force_merge()

    # ------------------------------------------------------------------
    # Compiled program-variant lattice (ISSUE 14, docs/RESILIENCE.md
    # "Rollout & drain"): record the query shapes the mesh plane served,
    # so a restart can warm their compiled variants off the query path.
    # ------------------------------------------------------------------

    def _record_warm_variant(self, kind: str, bodies: List[dict],
                             plane: str) -> None:
        if plane not in ("mesh_pallas", "mesh") or not bodies:
            return
        from elasticsearch_tpu.common import compile_cache as cc

        if cc.in_warming():
            return  # a warm replay must not re-record itself
        import json as _json

        try:
            # dedup BEFORE any copying/serialization: on the steady
            # state every query's variant is already recorded and this
            # is one skeleton hash + one dict probe
            key = (kind + "|" + str(min(len(bodies), 16)) + "|"
                   + "|".join(sorted({cc.body_skeleton(b)
                                      for b in bodies[:16]})))
            registry = cc.variant_registry()
            if registry.has_warm(self.name, key):
                return
            clean = [{k: v for k, v in (b or {}).items()
                      if k not in ("profile", "preference")}
                     for b in bodies[:16]]
            _json.dumps(clean)  # only JSON-serializable bodies persist
            registry.record_warm(self.name, key,
                                 {"kind": kind, "bodies": clean})
        except (TypeError, ValueError):
            pass  # unserializable body: this variant just isn't warmable

    def warm_compile_variants(self) -> int:
        """Replay this index's recorded program-variant lattice under
        the warming context — first compiles (or persistent-cache
        deserializations) land in ``programs_warmed_total``, never on
        the query path. Called in the background on node start / index
        open; returns how many warm specs replayed cleanly."""
        from elasticsearch_tpu.common import compile_cache as cc

        warmed = 0
        for spec in cc.variant_registry().warm_entries(self.name):
            try:
                with cc.warming():
                    bodies = [dict(b) for b in spec.get("bodies") or []]
                    if not bodies:
                        continue
                    if spec.get("kind") == "search_batch":
                        self.search_batch(bodies)
                    else:
                        for body in bodies:
                            self._search_uncached(body)
                warmed += 1
            except Exception:  # noqa: BLE001 — warming must never fail
                # the node; a stale spec (deleted field, changed
                # mapping) just warms nothing
                continue
        return warmed

    # ------------------------------------------------------------------
    # Search (scatter -> merge -> fetch; §3.2 of SURVEY.md)
    # ------------------------------------------------------------------

    def _telemetry_enabled(self) -> bool:
        """search.telemetry.enabled — the dynamic kill switch for the
        always-on phase tracer (docs/OBSERVABILITY.md). A cluster-level
        PUT wins while explicitly set (same explicitness contract as
        search.pallas.pruning.* — synced in put_cluster_settings)."""
        override = getattr(self, "telemetry_enabled_override", None)
        if override is not None:
            return bool(override)
        return self.settings.get_bool("search.telemetry.enabled", True)

    def _tracer(self):
        """One QueryTracer per request (NULL_TRACER when the kill switch
        is off), stamped with the request's X-Opaque-Id so the id
        survives the batch leader's thread hop."""
        from elasticsearch_tpu.search.telemetry import get_opaque_id

        tracer = self.telemetry.tracer(self._telemetry_enabled())
        oid = get_opaque_id()
        if oid:
            tracer.annotate("opaque_id", oid)
        return tracer

    @staticmethod
    def _annotate_batch_member(item, wait_s: float, batch_size: int,
                               member_index: int) -> None:
        """MicroBatcher telemetry hook: items are (body, deadline,
        tracer, opaque_id) — stamp the collection-window wait onto the
        member's tracer before the leader dispatches. The LAUNCH sites
        own batch_size/batch_member_index: only members that actually
        share a launch report a batch shape, a member that falls to
        serial execution must not claim one (docs/OBSERVABILITY.md)."""
        tracer = item[2] if len(item) > 2 else None
        if tracer is not None and getattr(tracer, "enabled", False):
            tracer.annotate("batch_window_wait_ms",
                            round(wait_s * 1000.0, 3))

    def _maybe_search_slowlog(self, took_s: float, body: dict,
                              plane: str, tracer) -> None:
        """Search slowlog for mesh-plane-served queries (the host path's
        per-shard ShardSearcher slowlog never runs there): same logger,
        same thresholds, enriched with plane + top-3 phase spans + the
        request's X-Opaque-Id (docs/OBSERVABILITY.md)."""
        from elasticsearch_tpu.search.service import emit_search_slowlog

        emit_search_slowlog(self._slowlog_warn_s, self._slowlog_info_s,
                            took_s, "index", self.name, plane, tracer,
                            body)

    def _finish_query_response(self, resp: dict, body: dict, tracer,
                               plane: str, took_s: float) -> dict:
        """One choke point for per-query observability: drain the
        tracer into the phase histograms, attach the plane-truthful
        profile section, and emit the (mesh-plane) slowlog line."""
        self.telemetry.record_query(plane, tracer)
        # program-variant warm spec (ISSUE 14, docs/RESILIENCE.md): a
        # mesh-served query shape joins the index's recorded lattice so
        # the next restart can warm its compiled variant off the query
        # path (deduped by structure — one record per variant)
        self._record_warm_variant("search", [body], plane)
        if body.get("profile"):
            prof = resp.setdefault("profile", {"shards": []})
            prof["plane"] = plane
            prof["phases"] = tracer.spans()
            prof["annotations"] = tracer.annotations()
        if plane != "host":
            self._maybe_search_slowlog(took_s, body, plane, tracer)
        return resp

    def _try_mesh_search(self, body: dict, k: int,
                         deadline=None, tracer=None) -> Optional[dict]:
        """Mesh query phase + host fetch phase. None = ineligible."""
        import time as _time

        from elasticsearch_tpu.search.service import fetch_hits
        from elasticsearch_tpu.search.telemetry import NULL_TRACER

        t0 = _time.monotonic()
        if tracer is None:
            tracer = NULL_TRACER
        if self._mesh_search is None:
            from elasticsearch_tpu.parallel.plan_exec import IndexMeshSearch

            self._mesh_search = IndexMeshSearch(self)
        out = self._mesh_search.query(body, max(k, 1), deadline=deadline,
                                      tracer=tracer)
        if out is None:
            return None
        from_ = int(body.get("from", 0) or 0)
        size = int(body.get("size")) if body.get("size") is not None else 10
        refs = out["refs"]
        refs_window = refs[from_: from_ + size] if size >= 0 else refs[from_:]
        t_fetch = tracer.start("fetch")
        hits = fetch_hits(refs_window, self.shards, body, self.name)
        tracer.stop("fetch", t_fetch)
        resp = {
            "took": int((_time.monotonic() - t0) * 1000),
            "timed_out": False,
            # which data plane served the query phase (execution-plane
            # observability; mirrored as counters in _stats):
            # "mesh_pallas" = the tile kernel scored inside the mesh
            # program (the unified fast plane), "mesh" = scatter mesh
            "_plane": out.get("plane", "mesh"),
            "_shards": {"total": len(self.shards),
                        "successful": len(self.shards),
                        "skipped": 0, "failed": 0},
            "hits": {"total": out["total"], "max_score": out["max_score"],
                     "hits": hits},
        }
        if out.get("terminated_early") is not None:
            resp["terminated_early"] = bool(out["terminated_early"])
        if out.get("pruned") is not None:
            # block-max pruned scoring served the query phase: surface
            # the tile economy (and the gte-total semantics marker) next
            # to _plane so bench/tests can assert pruning actually fired
            resp["_pruned"] = out["pruned"]
        if out["aggregations"] is not None:
            resp["aggregations"] = out["aggregations"]
        if body.get("suggest"):
            # suggest is its own phase beside the query program
            # (SuggestPhase) — same host code as the fallback path
            from elasticsearch_tpu.search.suggest import run_suggest

            resp["suggest"] = run_suggest(
                body["suggest"], self.shards, self.mapper_service)
        return self._finish_query_response(
            resp, body, tracer, resp["_plane"],
            _time.monotonic() - t0)

    def _try_mesh_knn(self, body: dict, spec: dict, k: int,
                      deadline=None, tracer=None) -> Optional[dict]:
        """kNN query phase on the mesh_pallas MXU plane + host fetch
        phase. None = ineligible (callers run the host plan-node rung —
        the same ladder shape as _try_mesh_search). Response assembly is
        shared with the batched form (_mesh_batch_response) so the
        serial and batched kNN shapes can never diverge."""
        if self._mesh_search is None:
            from elasticsearch_tpu.parallel.plan_exec import IndexMeshSearch

            self._mesh_search = IndexMeshSearch(self)
        out = self._mesh_search.query_knn(spec, max(k, 1),
                                          deadline=deadline,
                                          stats=body.get("stats"),
                                          tracer=tracer)
        if out is None:
            return None
        return self._mesh_batch_response(body, out, tracer=tracer)

    def _search_hybrid(self, body: dict, deadline=None) -> dict:
        """Hybrid ranking: the lexical ``query`` and the ``knn`` section
        each retrieve a top-``window`` candidate list through their own
        full plane ladder (mesh_pallas → host, deadlines/cancellation/
        partial results intact), then fuse:

        - ``rank: {rrf: {...}}`` — reciprocal rank fusion,
          score = Σ_sides 1 / (rank_constant + rank)  (the reference's
          RRF retriever);
        - default — convex score fusion, score = lexical score +
          knn_boost * knn score (the reference's additive knn+query
          combination; per-side ``boost`` weights the blend).

        The fused total is a LOWER BOUND (the union's exact count is
        not computed) — surfaced via the response's ``_total_relation``
        marker, which the REST layer renders as the
        track_total_hits-style ``{"value", "relation": "gte"}`` object.
        """
        import time as _time

        t0 = _time.monotonic()
        spec = body["knn"]
        if not isinstance(spec, dict) or "field" not in spec \
                or "query_vector" not in spec:
            raise IllegalArgumentException(
                "[knn] must be an object with [field] and [query_vector]")
        rank = body.get("rank")
        rrf = None
        if rank is not None:
            if not isinstance(rank, dict) or set(rank) != {"rrf"}:
                raise IllegalArgumentException(
                    "[rank] supports exactly one method: [rrf]")
            rrf = dict(rank.get("rrf") or {})
            unknown = set(rrf) - {"rank_constant", "window_size",
                                  "rank_window_size"}
            if unknown:
                # strict parsing, same contract as the knn clause: a
                # misspelled tuning knob must 400, never silently
                # fall back to defaults
                raise IllegalArgumentException(
                    f"[rrf] unknown parameter(s) {sorted(unknown)}")
            if "window_size" not in rrf and "rank_window_size" in rrf:
                # the reference's 8.x name for the same knob
                rrf["window_size"] = rrf["rank_window_size"]
            if int(rrf.get("rank_constant", 60)) < 1:
                raise IllegalArgumentException(
                    "[rank_constant] must be >= 1")
            if int(rrf.get("window_size", 1)) < 1:
                raise IllegalArgumentException(
                    "[window_size] must be >= 1")
        from_ = int(body.get("from", 0) or 0)
        size = int(body.get("size")) if body.get("size") is not None else 10
        k = max(from_ + size, 1)
        knn_k = int(spec.get("k", 10) or 10)
        window = max(k, knn_k)
        if rrf is not None:
            window = max(window, int(rrf.get("window_size", window)))
        rank_constant = int(rrf.get("rank_constant", 60)) if rrf else 60
        knn_boost = float(spec.get("boost", 1.0))

        # the knn side must fetch hits with the SAME source filtering /
        # fetch options as the lexical side: a hit found only by the
        # vector ranking would otherwise leak fields the request's
        # _source spec withheld
        passthrough = ("timeout", "allow_partial_search_results", "stats",
                       "_source", "docvalue_fields", "stored_fields",
                       "script_fields", "highlight", "version")
        lex_body = {key: v for key, v in body.items()
                    if key not in ("knn", "rank", "from", "size")}
        lex_body["size"] = window
        knn_body = {"query": {"knn": {key: v for key, v in spec.items()
                                      if key != "boost"}},
                    "size": window}
        for key in passthrough:
            if key in body:
                knn_body[key] = body[key]
        lex_resp = self._search_uncached(lex_body, deadline=deadline)
        knn_resp = self._search_uncached(knn_body, deadline=deadline)

        def ranked(resp):
            return {h["_id"]: (i + 1, h)
                    for i, h in enumerate(resp["hits"]["hits"])}

        lex_hits, knn_hits = ranked(lex_resp), ranked(knn_resp)
        if rrf is None:
            # convex (additive) fusion follows the reference's knn+query
            # semantics: only the k GLOBAL nearest neighbors contribute
            # a vector score — a doc ranked past k by similarity gets 0
            # from the knn side even though the window fetched more
            knn_hits = {doc_id: (r, h) for doc_id, (r, h)
                        in knn_hits.items() if r <= knn_k}
        fused = []
        for doc_id in set(lex_hits) | set(knn_hits):
            lex_rank, lex_hit = lex_hits.get(doc_id, (None, None))
            knn_rank, knn_hit = knn_hits.get(doc_id, (None, None))
            if rrf is not None:
                score = sum(1.0 / (rank_constant + r)
                            for r in (lex_rank, knn_rank) if r is not None)
            else:
                score = ((lex_hit["_score"] or 0.0)
                         if lex_hit is not None else 0.0) \
                    + knn_boost * ((knn_hit["_score"] or 0.0)
                                   if knn_hit is not None else 0.0)
            hit = dict(lex_hit if lex_hit is not None else knn_hit)
            hit["_score"] = float(score)
            hit.pop("sort", None)
            fused.append(hit)
        fused.sort(key=lambda h: (-h["_score"], h["_id"]))
        page = fused[from_: from_ + size] if size >= 0 else fused[from_:]

        # shard header: both sides query the SAME shards, so merge the
        # failure sets dedup'd by shard id — failed == len(failures) and
        # successful + failed == total stay internally consistent even
        # when a shard failed on both sides
        shards = dict(lex_resp["_shards"])
        seen = set()
        failures = []
        for f in (list(lex_resp["_shards"].get("failures") or [])
                  + list(knn_resp["_shards"].get("failures") or [])):
            key = (f.get("index"), f.get("shard"))
            if key not in seen:
                seen.add(key)
                failures.append(f)
        shards["failed"] = len(failures)
        shards["successful"] = max(
            int(shards.get("total", len(self.shards))) - len(failures), 0)
        shards.pop("failures", None)
        if failures:
            shards["failures"] = failures
        total = max(int(lex_resp["hits"]["total"]),
                    int(knn_resp["hits"]["total"]))
        resp = {
            "took": int((_time.monotonic() - t0) * 1000),
            "timed_out": bool(lex_resp.get("timed_out")
                              or knn_resp.get("timed_out")),
            "_plane": knn_resp.get("_plane", "host"),
            # per-side execution-plane observability + fusion mode
            "_hybrid": {"lexical_plane": lex_resp.get("_plane", "host"),
                        "knn_plane": knn_resp.get("_plane", "host"),
                        "fusion": "rrf" if rrf is not None else "convex"},
            # union count not computed: the fused total is a documented
            # lower bound (REST renders {"value", "relation": "gte"})
            "_total_relation": "gte",
            "_shards": shards,
            "hits": {"total": total,
                     "max_score": (page[0]["_score"] if page else None),
                     "hits": page},
        }
        # aggregations/suggest are request-level features orthogonal to
        # the ranking fusion: they are computed by the LEXICAL side
        # (whose window query saw the full matched set) and ride the
        # fused response unchanged — docs/VECTOR.md
        for key in ("aggregations", "suggest"):
            if key in lex_resp:
                resp[key] = lex_resp[key]
        return resp

    def search(self, body: Optional[dict] = None,
               preference_shards: Optional[List[int]] = None,
               pinned_segments: Optional[Dict[int, list]] = None,
               deadline=None) -> dict:
        """pinned_segments: {shard_id: [PinnedSegmentView]} from an open
        scroll context — bypasses the request cache, can_match, and the
        mesh plane (all keyed to the LIVE segment set).
        deadline: SearchDeadline threaded from the coordinator — expiry
        degrades to partial results (timed_out: true), cancellation
        raises TaskCancelledException at the next checkpoint."""
        from elasticsearch_tpu.index.request_cache import (
            RequestCache,
            cacheable,
            shard_epoch,
        )

        t0 = time.monotonic()
        body = body or {}
        if deadline is None and body.get("timeout") is not None:
            # direct IndexService.search callers get the same timeout
            # contract as the coordinator path
            from elasticsearch_tpu.search.cancellation import (
                SearchDeadline,
                parse_search_timeout,
            )

            deadline = SearchDeadline(parse_search_timeout(body))
        cache_key = None
        # (a cached COMPLETE response is always valid under a deadline;
        # only the put below filters — partial/timed-out responses must
        # not poison the cache)
        if (self._request_cache_enabled and preference_shards is None
                and pinned_segments is None and cacheable(body)):
            epochs = [shard_epoch(self.shards[sid])
                      for sid in sorted(self.shards)]
            cache_key = RequestCache.key_for(body, epochs)
            if cache_key is not None:
                cached = self.request_cache.get(cache_key)
                if cached is not None:
                    cached["took"] = int((time.monotonic() - t0) * 1000)
                    return cached
        resp = self._search_dispatch(body, preference_shards,
                                     pinned_segments, deadline=deadline)
        if (cache_key is not None and not resp.get("timed_out")
                and not resp["_shards"].get("failed")
                and not resp.get("_degraded")):
            # browned-out responses (shed aggs/rescore, forced pruning)
            # must not poison the cache: once pressure drains the same
            # body must serve full-precision, full-feature again
            self.request_cache.put(cache_key, resp)
        return resp

    def _search_dispatch(self, body: dict,
                         preference_shards: Optional[List[int]] = None,
                         pinned_segments: Optional[Dict[int, list]] = None,
                         deadline=None) -> dict:
        """Overload-control choke point (search/admission.py, ISSUE 12):
        every top-level search acquires an admission slot here BEFORE
        any staging/launch work. Overflow raises the 429 rejection; a
        deadline that expired while queued is shed pre-execution and
        serves its partial timed-out response; admitted queries execute
        shaped by the brownout ladder (forced pruning eligibility /
        shed rescore / shed aggs+suggest, marked ``_degraded``)."""
        from elasticsearch_tpu.search.service import expired_queue_response

        token = self.admission.acquire(deadline=deadline)
        if token.shed_expired:
            if deadline is not None:
                deadline.timed_out = True
            return expired_queue_response(self.name, len(self.shards),
                                          body)
        try:
            shaped, degraded = self.admission.apply_brownout(body, token)
            resp = self._admitted_dispatch(shaped, preference_shards,
                                           pinned_segments,
                                           deadline=deadline)
            if degraded and isinstance(resp, dict):
                # the degradation marker ALSO keeps the response out of
                # the request cache (IndexService.search): a browned-out
                # response must never be replayed after pressure drains
                resp["_degraded"] = degraded
            return resp
        finally:
            self.admission.release(token)

    def _admitted_dispatch(self, body: dict,
                           preference_shards: Optional[List[int]] = None,
                           pinned_segments: Optional[Dict[int, list]]
                           = None, deadline=None) -> dict:
        """Route the query phase through the cross-query micro-batcher
        when eligible (search/batching.py): a concurrent burst of
        compatible queries shares one batched kernel launch; a lone query
        takes the unbatched path with zero added latency."""
        from elasticsearch_tpu.search.batching import batchable_body
        from elasticsearch_tpu.search.telemetry import get_opaque_id

        tracer = self._tracer()
        if (not self._batcher.enabled or preference_shards is not None
                or pinned_segments is not None or body.get("scroll")
                or not batchable_body(body)):
            return self._search_uncached(body, preference_shards,
                                         pinned_segments, deadline=deadline,
                                         tracer=tracer)
        # the member's X-Opaque-Id rides the ITEM: the batch executes on
        # the leader's thread, whose own request context must not stamp
        # other members' slowlog lines (NULL_TRACER under the kill
        # switch carries no annotation to correct it)
        return self._batcher.run(
            self.name, (body, deadline, tracer, get_opaque_id()),
            single_fn=lambda it: self._search_uncached(
                it[0], deadline=it[1], tracer=it[2]),
            batch_fn=lambda items: self.search_batch(
                [it[0] for it in items], [it[1] for it in items],
                [it[2] for it in items], [it[3] for it in items]))

    def _search_uncached(self, body: dict,
                         preference_shards: Optional[List[int]] = None,
                         pinned_segments: Optional[Dict[int, list]] = None,
                         deadline=None, score_caches: Optional[dict] = None,
                         skip_mesh: bool = False, tracer=None) -> dict:
        """score_caches: {(shard_id, segment_name): (scores, matched)}
        from a cross-query batched kernel launch (search_batch) — cached
        segments skip plan execution inside ShardSearcher.query.
        skip_mesh: the query already went through the batch's plane
        ladder; don't re-probe the mesh plane per member.
        tracer: this request's QueryTracer (created here when absent);
        spans attribute to whichever plane ends up serving."""
        from elasticsearch_tpu.search.cancellation import (
            TimeExceededException,
        )
        from elasticsearch_tpu.search.service import (
            allow_partial_results,
            shard_failure_entry,
        )

        if tracer is None:
            tracer = self._tracer()
        body = body or {}
        # device-plane fault injection consult point (ISSUE 10): the
        # EvictionStormScheme forces the accountant's LRU evictor here,
        # under real query load
        from elasticsearch_tpu.testing.disruption import on_query_begin

        on_query_begin(self.name)
        if body.get("knn") is not None:
            # top-level ``knn`` section (the reference's knn search
            # surface): alone it is a pure vector search — normalize to
            # the ``knn`` query clause so the whole pipeline (plane
            # ladder, deadlines, partial results, fetch) serves it;
            # combined with ``query`` it is HYBRID ranking (RRF or
            # convex fusion) — see docs/VECTOR.md
            if not isinstance(body["knn"], dict):
                raise IllegalArgumentException(
                    "[knn] must be an object with [field] and "
                    "[query_vector]")
            if body.get("query") is not None:
                return self._search_hybrid(body, deadline=deadline)
            body = dict(body)
            spec = body.pop("knn")
            if body.pop("rank", None) is not None:
                raise IllegalArgumentException(
                    "[rank] requires both [query] and [knn] sections")
            body["query"] = {"knn": spec}
            if body.get("size") is None and spec.get("k") is not None:
                body["size"] = int(spec["k"])

        t0 = time.monotonic()
        from_ = int(body.get("from", 0) or 0)
        size = int(body.get("size")) if body.get("size") is not None else 10
        k = from_ + size
        shard_ids = preference_shards or sorted(self.shards)
        sort_spec = normalize_sort(body.get("sort"))
        allow_partial = allow_partial_results(body)
        timed_out = False

        # mesh data plane: eligible searches over all shards run as ONE
        # multi-device program (query + DFS-free scoring + global top-k
        # merge in-XLA); fallback is the per-shard host merge below.
        # Pinned (scroll) searches stay on the host path: the mesh stages
        # the LIVE segment set.
        if (self._mesh_enabled and not skip_mesh
                and preference_shards is None
                and pinned_segments is None and not body.get("scroll")
                # a quarantined copy must FAIL, not serve (ISSUE 16):
                # the mesh plane executes all shards as one program and
                # cannot report a per-shard failure, so any corrupt-
                # flagged shard forces the host path below where the
                # flag becomes a failures[] entry
                and not any(getattr(s, "store_corrupted", False)
                            for s in self.shards.values())):
            try:
                knn_clause = _pure_knn_mesh_clause(body)
                if knn_clause is not None:
                    mesh_resp = self._try_mesh_knn(body, knn_clause, k,
                                                   deadline=deadline,
                                                   tracer=tracer)
                else:
                    mesh_resp = self._try_mesh_search(body, k,
                                                      deadline=deadline,
                                                      tracer=tracer)
            except TimeExceededException:
                # deadline expired inside the mesh plane: the host loop
                # below breaks at its first checkpoint and reports the
                # accumulated (empty) partial result
                mesh_resp = None
                timed_out = True
            if mesh_resp is not None:
                return mesh_resp
        with self._stats_lock:
            self._host_query_total += 1

        shard_results = []
        failures = []
        # can_match prefilter (SearchService.canMatch /
        # TransportSearchAction pre-filtering): shards whose doc-value
        # bounds cannot satisfy a pure range query are skipped without
        # executing the query phase
        skipped = 0
        active_ids = []
        for sid in shard_ids:
            if (preference_shards is None and pinned_segments is None
                    and not _can_match(self.shards[sid], body)):
                # (pinned searches bypass can_match: its bounds come from
                # the live segment set, not the pinned view)
                skipped += 1
                continue
            active_ids.append(sid)
        if not active_ids and shard_ids:
            # keep at least one shard so the response shape (empty hits,
            # empty agg frames) is produced by a real query phase
            active_ids = [shard_ids[0]]
            skipped -= 1
        for sid in active_ids:
            if timed_out or (deadline is not None and deadline.expired):
                # accumulated shard results stand; the fan-out stops
                timed_out = True
                if deadline is not None:
                    deadline.timed_out = True
                break
            try:
                if getattr(self.shards[sid], "store_corrupted", False):
                    # quarantined copy (ISSUE 16): fail the shard into
                    # failures[] — never silent empty hits, never a
                    # re-read of the marked bytes
                    raise CorruptIndexException(
                        f"shard [{self.name}][{sid}] store is marked "
                        f"corrupted — awaiting re-recovery from a "
                        f"healthy copy")
                shard_cache = None
                if score_caches:
                    shard_cache = {
                        name: pair for (s, name), pair
                        in score_caches.items() if s == sid}
                shard_results.append(
                    self.shards[sid].searcher.query(
                        body, size_hint=max(k, 1),
                        segments=(pinned_segments.get(sid, [])
                                  if pinned_segments is not None else None),
                        deadline=deadline, score_cache=shard_cache,
                        tracer=tracer)
                )
            except TaskCancelledException:
                raise  # _tasks/_cancel: a clean request-level error
            except TimeExceededException:
                timed_out = True
                break
            except Exception as e:  # noqa: BLE001 — per-shard isolation
                if _is_request_error(e):
                    # request-level validation (parse/mapping/argument):
                    # deterministic on every shard — surface it with its
                    # own 4xx status instead of masking it as failures
                    raise
                if (isinstance(e, CorruptIndexException)
                        and not getattr(self.shards[sid],
                                        "store_corrupted", False)):
                    # first detection on the query path: quarantine the
                    # copy (marker + staging release) — subsequent
                    # queries fail fast on the flag without recounting
                    self._quarantine_shard(sid, e, site="query")
                # one bad shard (corrupt segment, injected fault, compile
                # error) becomes a failures[] entry + _shards.failed, not
                # a 500 (AbstractSearchAsyncAction.onShardFailure)
                failures.append(shard_failure_entry(self.name, sid, e))
        timed_out = timed_out or any(r.timed_out for r in shard_results)
        if failures and not shard_results and not timed_out:
            # every shard failed: no results to degrade to
            # (SearchPhaseExecutionException "all shards failed")
            raise SearchPhaseExecutionException(
                "query", "all shards failed", failures)
        if not allow_partial and (failures or timed_out):
            raise SearchPhaseExecutionException(
                "query",
                "Partial shards failure"
                + (" (request timed out)" if timed_out else ""),
                failures)
        total = sum(r.total_hits for r in shard_results)
        max_score = None
        for r in shard_results:
            if r.max_score is not None:
                max_score = r.max_score if max_score is None else max(max_score, r.max_score)
        collapse_body = body.get("collapse") or {}
        collapse_field = collapse_body.get("field")
        merge_k = max(k, 0)
        if collapse_field:
            merge_k = 0  # keep all candidates; collapsing shrinks the list
        t_merge = tracer.start("merge")
        all_refs = [ref for r in shard_results for ref in r.refs]
        refs = merge_refs(all_refs, sort_spec, merge_k or len(all_refs))
        if collapse_field:
            from elasticsearch_tpu.search.service import collapse_refs

            refs = collapse_refs(refs, collapse_field, self.shards)[: max(k, 0)]
        refs_window = refs[from_: from_ + size] if size >= 0 else refs[from_:]
        tracer.stop("merge", t_merge)

        aggregations = None
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        if agg_specs:
            # host-path agg execution gets its own phase span (ISSUE 13:
            # the `aggregate` taxonomy entry) so phase_attribution_p50_ms
            # can show what the fused plane removes
            t_agg = tracer.start("aggregate")
            views = [v for r in shard_results for v in r.agg_views]
            aggregations = run_aggregations(agg_specs, views)
            tracer.stop("aggregate", t_agg)

        t_fetch = tracer.start("fetch")
        hits = fetch_hits(refs_window, self.shards, body, self.name,
                          pinned_segments=pinned_segments)
        tracer.stop("fetch", t_fetch)
        if collapse_field:
            from elasticsearch_tpu.search.service import expand_collapsed_hits

            expand_collapsed_hits(
                hits, refs_window, collapse_body, body,
                lambda sub: self.search(sub, deadline=deadline))
        took = int((time.monotonic() - t0) * 1000)
        resp = {
            "took": took,
            "timed_out": timed_out,
            "_plane": "host",
            "_shards": {
                # shards the deadline cut before they ran count successful
                # (they did not fail — the reference reports responded +
                # unreached alike against the timeout flag)
                "total": len(shard_ids),
                "successful": len(shard_ids) - len(failures),
                "skipped": skipped,
                "failed": len(failures),
            },
            "hits": {
                "total": total,
                "max_score": max_score,
                "hits": hits,
            },
        }
        if failures:
            resp["_shards"]["failures"] = failures
        if any(r.terminated_early is not None for r in shard_results):
            resp["terminated_early"] = any(
                bool(r.terminated_early) for r in shard_results
            )
        if aggregations is not None:
            resp["aggregations"] = aggregations
        if body.get("profile"):
            resp["profile"] = {"shards": [
                s for r in shard_results for s in (r.profile or [])
            ]}
        if body.get("suggest"):
            from elasticsearch_tpu.search.suggest import run_suggest

            resp["suggest"] = run_suggest(
                body["suggest"], self.shards, self.mapper_service
            )
        return self._finish_query_response(resp, body, tracer, "host",
                                           took / 1000.0)

    # ------------------------------------------------------------------
    # Cross-query micro-batching (search/batching.py; docs/BATCHING.md)
    # ------------------------------------------------------------------

    def search_batch(self, bodies: List[dict],
                     deadlines: Optional[list] = None,
                     tracers: Optional[list] = None,
                     oids: Optional[list] = None) -> list:
        """Execute Q concurrent search requests as one micro-batch.

        Returns one entry per member: the response dict, or the
        exception that member alone should raise (cancellation, request
        error) — peers are never failed by one member's fate.

        Plane ladder, mirroring the serial path:
        1. an expired member is served its partial (timed_out) result
           individually and a cancelled member gets its
           TaskCancelledException — both are DROPPED from the batch;
        2. mesh_pallas rung: eligible batches run as ONE batched kernel
           launch inside the mesh program (IndexMeshSearch.query_batch);
           a batch-wide plane fault feeds the PlaneHealth quarantine
           ONCE and the batch falls to the next rung;
        3. host-pallas rung: one batched launch per segment feeds each
           member's normal per-query pipeline via score caches;
        4. members ineligible for any shared launch execute serially.
        """
        from elasticsearch_tpu.search.batching import batchable_body
        from elasticsearch_tpu.search.cancellation import (
            TimeExceededException,
        )
        from elasticsearch_tpu.search.telemetry import (
            get_opaque_id,
            set_opaque_id,
        )

        n = len(bodies)
        deadlines = list(deadlines) if deadlines else [None] * n
        # direct callers (tests, dryrun) pass no tracers: create per-
        # member ones so batched profile/phase attribution still works
        tracers = (list(tracers) if tracers
                   else [self._tracer() for _ in bodies])
        # every member executes on THIS (the leader's) thread: its own
        # X-Opaque-Id must be the contextvar while its result is built,
        # or its slowlog line logs the leader's client id; the leader's
        # context is restored before returning
        leader_oid = get_opaque_id()
        oids = list(oids) if oids else [leader_oid] * n
        results: list = [None] * n
        live: List[int] = []
        for i, body in enumerate(bodies):
            dl = deadlines[i]
            if dl is not None:
                try:
                    dl.checkpoint()
                except TaskCancelledException as e:
                    # _tasks/_cancel of one member: its own clean error,
                    # the batch proceeds without it
                    results[i] = e
                    continue
                except TimeExceededException:
                    # expired before dispatch: serve its accumulated
                    # (empty) partial result — the serial path hits the
                    # same checkpoint immediately and reports timed_out
                    set_opaque_id(oids[i])
                    results[i] = self._batch_member_single(body, dl,
                                                           tracer=tracers[i])
                    continue
            if not batchable_body(body):
                set_opaque_id(oids[i])
                results[i] = self._batch_member_single(body, dl,
                                                       tracer=tracers[i])
                continue
            live.append(i)

        # pure-kNN members split off onto the kNN MXU plane: the batched
        # dense-matmul launch streams the embedding matrix once for the
        # whole vector burst (IndexMeshSearch.query_knn_batch); members
        # it can't serve fall back to their serial pipeline one by one
        from elasticsearch_tpu.search.batching import knn_batch_spec

        knn_live = [i for i in live if knn_batch_spec(bodies[i])]
        if knn_live:
            live = [i for i in live if i not in set(knn_live)]
            self._dispatch_knn_batch(bodies, deadlines, knn_live, results,
                                     tracers, oids=oids)

        if len(live) < 2:
            for i in live:
                set_opaque_id(oids[i])
                results[i] = self._batch_member_single(bodies[i],
                                                       deadlines[i],
                                                       tracer=tracers[i])
            set_opaque_id(leader_oid)
            return results

        live_bodies = [bodies[i] for i in live]
        # rung 1: batched mesh_pallas launch (one program, Q queries).
        # A plane fault inside quarantines mesh_pallas exactly once.
        mesh_out = None
        if (self._mesh_enabled and len(self.shards) >= 2
                # quarantined copies fail per-shard on the host path
                # (ISSUE 16) — same gate as the serial mesh dispatch
                and not any(getattr(s, "store_corrupted", False)
                            for s in self.shards.values())):
            if self._mesh_search is None:
                from elasticsearch_tpu.parallel.plan_exec import (
                    IndexMeshSearch,
                )

                self._mesh_search = IndexMeshSearch(self)
            mesh_out = self._mesh_search.query_batch(
                live_bodies, tracers=[tracers[i] for i in live])
        if mesh_out is not None:
            for j, i in enumerate(live):
                set_opaque_id(oids[i])
                try:
                    results[i] = self._mesh_batch_response(
                        bodies[i], mesh_out[j], tracer=tracers[i])
                except Exception as e:  # noqa: BLE001 — per-member fetch
                    results[i] = e
            self.batch_stats.note_batch(len(live))
            # batched program-variant warm spec (ISSUE 14): record the
            # burst's shape so restart warming replays a same-shaped
            # batch through query_batch (the batched q_pad/kk variants
            # are distinct compiled programs from the serial ones)
            self._record_warm_variant("search_batch", live_bodies,
                                      "mesh_pallas")
            set_opaque_id(leader_oid)
            return results

        # rung 2: host-pallas batched scoring, then each member's normal
        # per-query pipeline on top of its cached score vectors
        caches, launches = self._host_batch_scores(live_bodies)
        # count only the members that actually shared a launch — kernel-
        # ineligible members executed fully serially and must not inflate
        # the batching-coverage telemetry (same rule for the batch-shape
        # annotations below)
        shared = sum(1 for c in caches if c)
        member_idx = 0
        for j, i in enumerate(live):
            set_opaque_id(oids[i])
            if caches[j]:
                tr = tracers[i]
                if tr is not None and getattr(tr, "enabled", False):
                    tr.annotate("batch_size", shared)
                    tr.annotate("batch_member_index", member_idx)
                member_idx += 1
            results[i] = self._batch_member_single(
                bodies[i], deadlines[i], score_caches=caches[j] or None,
                skip_mesh=bool(caches[j]), tracer=tracers[i])
        if launches and shared:
            self.batch_stats.note_batch(shared)
        set_opaque_id(leader_oid)
        return results

    @staticmethod
    def _knn_member_body(body) -> dict:
        """The serial path's top-level-knn size normalization (size
        defaults to the spec's k), applied to a batch member so a
        request returns the SAME hit count whether or not it happened
        to share a batch window."""
        body = dict(body or {})
        spec = body.get("knn")
        if (isinstance(spec, dict) and body.get("query") is None
                and body.get("size") is None
                and spec.get("k") is not None):
            body["size"] = int(spec["k"])
        return body

    def _dispatch_knn_batch(self, bodies, deadlines, knn_live, results,
                            tracers=None, oids=None):
        """Serve a burst of pure-kNN members: one batched MXU launch
        when they target the same field and the mesh plane is up, else
        per-member serial execution (which still rides the serial kNN
        ladder). Fills ``results`` in place."""
        from elasticsearch_tpu.search.batching import knn_batch_spec

        from elasticsearch_tpu.search.telemetry import scoped_opaque_id

        if tracers is None:
            tracers = [None] * len(bodies)
        if oids is None:
            oids = [None] * len(bodies)

        specs = [knn_batch_spec(bodies[i]) for i in knn_live]
        norm_bodies = {i: self._knn_member_body(bodies[i])
                       for i in knn_live}
        ks = []
        for i in knn_live:
            body = norm_bodies[i]
            from_ = int(body.get("from", 0) or 0)
            size = (int(body.get("size"))
                    if body.get("size") is not None else 10)
            ks.append(max(from_ + size, 1))
        mesh_out = None
        if (self._mesh_enabled and len(self.shards) >= 2
                and len(knn_live) >= 2
                and len({str(s.get("field")) for s in specs}) == 1):
            if self._mesh_search is None:
                from elasticsearch_tpu.parallel.plan_exec import (
                    IndexMeshSearch,
                )

                self._mesh_search = IndexMeshSearch(self)
            mesh_out = self._mesh_search.query_knn_batch(
                specs, ks,
                stats=[norm_bodies[i].get("stats") for i in knn_live],
                tracers=[tracers[i] for i in knn_live])
        # scoped stamps (PR-15 contract-lint fix): the bare set_opaque_id
        # shape left the LAST member's id in the leader's context on both
        # exit paths, mis-attributing its later slowlog/profile lines
        if mesh_out is not None:
            for j, i in enumerate(knn_live):
                with scoped_opaque_id(oids[i]):
                    try:
                        results[i] = self._mesh_batch_response(
                            norm_bodies[i], mesh_out[j],
                            tracer=tracers[i])
                    except Exception as e:  # noqa: BLE001 — per-member
                        results[i] = e  # fetch isolation
            self.batch_stats.note_batch(len(knn_live))
            return
        for i in knn_live:
            with scoped_opaque_id(oids[i]):
                results[i] = self._batch_member_single(
                    bodies[i], deadlines[i], tracer=tracers[i])

    def _batch_member_single(self, body, deadline, score_caches=None,
                             skip_mesh=False, tracer=None):
        """One member's serial execution inside a batch: exceptions are
        captured as that member's result instead of failing its peers."""
        try:
            return self._search_uncached(
                body, deadline=deadline, score_caches=score_caches,
                skip_mesh=skip_mesh, tracer=tracer)
        except Exception as e:  # noqa: BLE001 — per-member isolation
            return e

    def _host_batch_scores(self, bodies: List[dict]):
        """Per-segment batched kernel launches for the host rung.

        Returns ([per-member {(shard_id, seg_name): (scores, matched)}],
        n_launches). A member whose plan on a segment isn't a pure
        kernel-scored disjunction simply gets no cache entry there and
        executes that segment serially — per-query semantics are owned
        by the normal pipeline either way."""
        from elasticsearch_tpu.search.batching import (
            batched_segment_scores,
            counts_safe_for_union,
        )
        from elasticsearch_tpu.search.plan import PallasScoreTermsNode
        from elasticsearch_tpu.search.query_dsl import parse_query

        caches: List[dict] = [dict() for _ in bodies]
        launches = 0
        qbs = []
        for body in bodies:
            try:
                qbs.append(parse_query(body.get("query")))
            except Exception:  # noqa: BLE001 — parse errors surface with
                # their proper status when the member executes serially
                qbs.append(None)
        for sid in sorted(self.shards):
            shard = self.shards[sid]
            ctx = shard.searcher.ctx
            for seg in shard.engine.searchable_segments():
                if seg.num_docs == 0:
                    continue
                plans = []
                for qb in qbs:
                    node = None
                    if qb is not None:
                        try:
                            p = qb.to_plan(ctx, seg)
                            if (isinstance(p, PallasScoreTermsNode)
                                    and getattr(p, "_host_lanes", None)
                                    and counts_safe_for_union(p)):
                                node = p
                        except Exception:  # noqa: BLE001 — serial path
                            # owns this member's error shape
                            node = None
                    plans.append(node)
                idxs = [i for i, p in enumerate(plans) if p is not None]
                if len(idxs) < 2:
                    continue  # nothing to amortize on this segment
                try:
                    outs = batched_segment_scores(
                        seg, [plans[i] for i in idxs])
                except Exception:  # noqa: BLE001 — batched launch fault:
                    # every member still serves serially (and a kernel
                    # fault on the serial path feeds its own quarantine)
                    outs = None
                if outs is None:
                    continue
                launches += 1
                for j, i in enumerate(idxs):
                    caches[i][(sid, seg.name)] = outs[j]
        return caches, launches

    def _mesh_batch_response(self, body: dict, out: dict,
                             tracer=None) -> dict:
        """Assemble one member's full response from its slice of a
        batched mesh launch (same shape as _try_mesh_search)."""
        import time as _time

        from elasticsearch_tpu.search.service import fetch_hits
        from elasticsearch_tpu.search.telemetry import NULL_TRACER

        if tracer is None:
            tracer = NULL_TRACER
        t0 = _time.monotonic()
        t_demux = tracer.start("batch_demux")
        from_ = int(body.get("from", 0) or 0)
        size = int(body.get("size")) if body.get("size") is not None else 10
        refs = out["refs"]
        refs_window = (refs[from_: from_ + size] if size >= 0
                       else refs[from_:])
        tracer.stop("batch_demux", t_demux)
        t_fetch = tracer.start("fetch")
        hits = fetch_hits(refs_window, self.shards, body, self.name)
        tracer.stop("fetch", t_fetch)
        resp = {
            "took": int((_time.monotonic() - t0) * 1000),
            "timed_out": False,
            # per-query truth: every member of the batch was scored by
            # the batched mesh_pallas launch
            "_plane": out.get("plane", "mesh_pallas"),
            "_shards": {"total": len(self.shards),
                        "successful": len(self.shards),
                        "skipped": 0, "failed": 0},
            "hits": {"total": out["total"], "max_score": out["max_score"],
                     "hits": hits},
        }
        if out.get("aggregations") is not None:
            # fused on-device aggregations computed inside the batched
            # launch (ISSUE 13, docs/AGGS.md)
            resp["aggregations"] = out["aggregations"]
        if out.get("pruned") is not None:
            resp["_pruned"] = out["pruned"]
        return self._finish_query_response(
            resp, body, tracer, resp["_plane"], _time.monotonic() - t0)

    def count(self, body: Optional[dict] = None) -> dict:
        body = dict(body or {})
        body["size"] = 0
        r = self.search(body)
        return {"count": r["hits"]["total"], "_shards": r["_shards"]}

    # ------------------------------------------------------------------

    @property
    def num_docs(self) -> int:
        return sum(s.num_docs for s in self.shards.values())

    def search_stats(self, shard_stats: Optional[dict] = None) -> dict:
        """The ``search`` stats block alone (SearchStats + the TPU-plane
        extensions) — reused verbatim by ``stats()`` and aggregated
        across indices into the ``_nodes/stats`` search section
        (docs/OBSERVABILITY.md)."""
        if shard_stats is None:
            shard_stats = {sid: s.stats() for sid, s in self.shards.items()}
        groups: Dict[str, dict] = {}
        for s in shard_stats.values():
            for g, gs in (s["search"].get("groups") or {}).items():
                agg = groups.setdefault(g, {k: 0 for k in gs})
                for k, v in gs.items():
                    agg[k] += v
        search = {
            "open_contexts": 0,
            "query_total": sum(s["search"]["query_total"]
                               for s in shard_stats.values()),
            "query_time_in_millis": sum(s["search"]["query_time_in_millis"]
                                        for s in shard_stats.values()),
            "fetch_total": sum(s["search"].get("fetch_total", 0)
                               for s in shard_stats.values()),
            # execution-plane counters (VERDICT r4 weak 3): on a TPU
            # deployment "did we use the chip?" must be observable —
            # which data plane served each query (mesh program vs host
            # scatter-merge) and which engine scored each segment
            "planes": {
                "mesh_query_total": (self._mesh_search.query_total
                                     if self._mesh_search is not None
                                     else 0),
                "mesh_pallas_query_total": (
                    self._mesh_search.pallas_query_total
                    if self._mesh_search is not None else 0),
                "host_query_total": self._host_query_total,
                "pallas_segments_total": sum(
                    s["search"]["planes"]["pallas_segments_total"]
                    for s in shard_stats.values()),
                "scatter_segments_total": sum(
                    s["search"]["planes"]["scatter_segments_total"]
                    for s in shard_stats.values()),
                # plane-health quarantine (docs/RESILIENCE.md): per-plane
                # fault counters + which planes are currently benched
                **(self._mesh_search.plane_health.stats()
                   if self._mesh_search is not None else
                   {"plane_failures_total": {"mesh_pallas": 0, "mesh": 0},
                    "plane_failures_by_reason": {},
                    "plane_probes_total": 0,
                    "plane_quarantined": [], "quarantine_events": []}),
                # block-max pruned scoring + postings codec observability
                # (docs/PRUNING.md): queries served pruned, the tile
                # economy, and what representation the postings stream as
                # dense-vector retrieval (docs/VECTOR.md): kNN queries
                # served by the mesh MXU program
                "knn_query_total": (
                    self._mesh_search.knn_query_total
                    if self._mesh_search is not None else 0),
                # fused on-device aggregations (ISSUE 13, docs/AGGS.md):
                # agg'd queries whose whole agg set reduced inside the
                # mesh program vs those that fell back to the host
                # reduce, per documented reason
                "agg_fused_query_total": (
                    self._mesh_search.agg_fused_query_total
                    if self._mesh_search is not None else 0),
                "agg_host_fallback_total": (
                    self._mesh_search.agg_host_fallback_total
                    if self._mesh_search is not None else 0),
                "agg_host_fallback_by_reason": (
                    dict(self._mesh_search.agg_host_fallback_by_reason)
                    if self._mesh_search is not None else {}),
                "pruned_query_total": (
                    self._mesh_search.pruned_query_total
                    if self._mesh_search is not None else 0),
                # delta device staging (ISSUE 20): incremental appends
                # served without a geometry rebuild, in-place tombstone
                # mask updates, and background compaction passes
                "delta_restage_total": (
                    self._mesh_search.delta_restage_total
                    if self._mesh_search is not None else 0),
                "tombstone_update_total": (
                    self._mesh_search.tombstone_update_total
                    if self._mesh_search is not None else 0),
                "compaction_runs_total": (
                    self._mesh_search.compaction_runs_total
                    if self._mesh_search is not None else 0),
                "tiles_scored_total": (
                    self._mesh_search.tiles_scored_total
                    if self._mesh_search is not None else 0),
                "tiles_pruned_total": (
                    self._mesh_search.tiles_pruned_total
                    if self._mesh_search is not None else 0),
                "postings_codec": (
                    self._mesh_search._executor.postings_codec
                    if self._mesh_search is not None
                    and self._mesh_search._executor is not None
                    else None),
                # staged posting bytes: the mesh-plane staging plus every
                # shard segment's host-plane kernel staging (raw stages
                # 8 B/posting, packed 4 B — the restage cost ROADMAP
                # item 3 tracks shrinks with it)
                "postings_bytes_staged": (
                    (self._mesh_search._executor.postings_bytes_staged
                     if self._mesh_search is not None
                     and self._mesh_search._executor is not None else 0)
                    + sum(int(getattr(seg, "kernel_postings_bytes", 0))
                          for sh in self.shards.values()
                          for seg in sh.engine.searchable_segments())),
            },
            # cross-query micro-batching (docs/BATCHING.md): how much of
            # the traffic shared batched kernel launches, the dispatched
            # batch-size distribution, and how often a leader paid the
            # collection window
            "batch": self.batch_stats.as_dict(),
            # multi-tenant overload control (ISSUE 12, docs/OVERLOAD.md):
            # admission queue occupancy, admitted/rejected/expired
            # counters, brownout ladder state + per-step shed counts,
            # the computed Retry-After, and per-tenant accounting
            "admission": self.admission.stats_dict(),
            # phase-attributed telemetry (ISSUE 8, docs/OBSERVABILITY.md):
            # per-plane × per-phase log2 latency histograms, byte/tile
            # counters, and plane-ladder decision counters with reasons
            "phases": self.telemetry.phases_dict(),
            # device-memory ledger (ISSUE 9, docs/OBSERVABILITY.md):
            # per-kind staged bytes (sum EXACTLY to staged_bytes_total),
            # staging/eviction lifecycle event rings, and the
            # restage-amplification metric ROADMAP item 3 drives down
            "memory": _memory_stats(self.name),
            # compile plane (ISSUE 14, docs/OBSERVABILITY.md): the
            # persistent-cache hit/miss counters, warmed-program count,
            # query-path first compiles, and the first-compile-stall
            # histogram — a PROCESS resource like the memory ledger
            # (_nodes/stats re-exports the same node-wide block)
            "compile": _compile_stats(),
            # data integrity (ISSUE 16, docs/OBSERVABILITY.md): detected
            # corruptions by site, corrupted_* marker lifecycle events,
            # and the background scrubber's verified-bytes/drift counters
            # — counters node-global, marker_events filtered per index
            "integrity": integrity_service().stats(self.name),
        }
        if groups:
            search["groups"] = groups
        return search

    def stats(self) -> dict:
        """Full CommonStats section set (action/admin/indices/stats) —
        every section present so metric filtering can subset; untracked
        counters report zero rather than omitting the section."""
        shard_stats = {sid: s.stats() for sid, s in self.shards.items()}
        index_total = sum(s["indexing"]["index_total"]
                          for s in shard_stats.values())
        delete_total = sum(s["indexing"]["delete_total"]
                           for s in shard_stats.values())
        mem_bytes = sum(s["segments"]["memory_in_bytes"]
                        for s in shard_stats.values())
        fielddata_bytes = sum(
            sum(seg.breaker_charges.values())
            for sh in self.shards.values()
            for seg in sh.engine.searchable_segments())
        search = self.search_stats(shard_stats)
        totals = {
            "docs": {"count": self.num_docs, "deleted": 0},
            "store": {"size_in_bytes": mem_bytes,
                      "throttle_time_in_millis": 0},
            "indexing": {
                "index_total": index_total,
                "index_time_in_millis": 0,
                "delete_total": delete_total,
                "index_failed": 0,
                "types": {self.doc_type or "_doc": {
                    "index_total": index_total,
                    "index_time_in_millis": 0,
                    "delete_total": delete_total,
                }},
            },
            "get": {"total": self._get_total, "time_in_millis": 0,
                    "exists_total": 0, "missing_total": 0, "current": 0},
            "search": search,
            "merges": {"current": 0, "current_docs": 0, "total": 0,
                       "total_time_in_millis": 0, "total_docs": 0},
            "refresh": {"total": self._refresh_total,
                        "total_time_in_millis": 0, "listeners": 0},
            "flush": {"total": self._flush_total,
                      "total_time_in_millis": 0},
            "warmer": {"current": 0, "total": 0, "total_time_in_millis": 0},
            "query_cache": {"memory_size_in_bytes": 0, "total_count": 0,
                            "hit_count": 0, "miss_count": 0,
                            "cache_count": 0, "evictions": 0},
            "fielddata": {"memory_size_in_bytes": fielddata_bytes,
                          "evictions": 0},
            "completion": {"size_in_bytes": 0},
            "segments": {
                "count": sum(s["segments"]["count"]
                             for s in shard_stats.values()),
                "memory_in_bytes": mem_bytes,
            },
            "translog": {
                "operations": sum(s["translog"]["operations"]
                                  for s in shard_stats.values()),
                "size_in_bytes": sum(
                    s["translog"].get("size_in_bytes", 0)
                    for s in shard_stats.values()),
            },
            "recovery": {"current_as_source": 0, "current_as_target": 0,
                         "throttle_time_in_millis": 0},
            "request_cache": self.request_cache.stats(),
        }
        return {"primaries": totals, "total": totals, "shards": shard_stats}

    def mapping_dict(self) -> dict:
        return self.mapper_service.mapping_dict()

    def put_mapping(self, mapping: dict) -> None:
        self.mapper_service.merge(mapping)

    def close(self) -> None:
        self._closing = True
        # wait out an in-flight background compaction pass: its restage
        # must not re-stage bytes after the releases below (the
        # leak-check contract) — new passes see _closing and bail
        with self._compact_lock:
            pass
        if self._refresh_stop is not None:
            self._refresh_stop.set()
        self._scrub_stop.set()
        # wake queued admission waiters with a clean rejection so no
        # caller hangs on a closing index
        self.admission.shutdown()
        # structured device-memory releases first (mesh plane, then every
        # shard's segments via engine.close), then the index-level ledger
        # backstop — close/delete must return the ledger to baseline
        # exactly (the leak-check contract, docs/OBSERVABILITY.md)
        if self._mesh_search is not None:
            self._mesh_search._drop_staging()
        for shard in self.shards.values():
            shard.close()
        from elasticsearch_tpu.common.memory import memory_accountant

        memory_accountant().release_index(self.name)


def _memory_stats(index: Optional[str]) -> dict:
    from elasticsearch_tpu.common.memory import memory_accountant

    return memory_accountant().stats(index)


def _compile_stats() -> dict:
    from elasticsearch_tpu.common.compile_cache import compile_stats

    return compile_stats().stats()


def _pure_knn_mesh_clause(body: dict) -> Optional[dict]:
    """The knn spec when this request is a plain top-k vector search the
    mesh kNN program can serve whole, else None. The eligibility rules
    (sole knn clause, simple body keys, default boost — a non-default
    boost stays on the host rung for byte-parity) are SHARED with the
    batched dispatcher so the serial and batched paths can never drift
    (search/batching.knn_batch_spec)."""
    from elasticsearch_tpu.search.batching import knn_batch_spec

    q = body.get("query")
    if not (isinstance(q, dict) and set(q) == {"knn"}):
        return None  # here only the already-normalized clause form runs
    return knn_batch_spec(body)


def _is_request_error(exc: Exception) -> bool:
    """True for 4xx engine exceptions — request-level validation errors
    (malformed query, unmapped field, bad argument) that every shard
    would raise identically; the reference rejects these on the
    coordinator before the fan-out, so they keep their own status."""
    from elasticsearch_tpu.common.errors import ElasticsearchTpuException

    return (isinstance(exc, ElasticsearchTpuException)
            and exc.status_code < 500)


def _deep_merge(base: dict, patch: dict) -> dict:
    for key, value in patch.items():
        if isinstance(value, dict) and isinstance(base.get(key), dict):
            base[key] = _deep_merge(dict(base[key]), value)
        else:
            base[key] = value
    return base


def _can_match(shard, body: dict) -> bool:
    """Shard-level rewrite of a PURE range query against the shard's
    doc-value bounds (the reference's canMatch phase rewrites the query
    against min/max points). Conservative: anything but a bare range
    query matches."""
    query = (body or {}).get("query")
    if not isinstance(query, dict) or set(query) != {"range"}:
        return True
    (field, cond), = query["range"].items()
    if not isinstance(cond, dict):
        return True
    lo = cond.get("gte", cond.get("gt"))
    hi = cond.get("lte", cond.get("lt"))
    if not all(isinstance(v, (int, float)) or v is None for v in (lo, hi)):
        return True  # dates/strings need parsing context; don't prefilter
    any_col = False
    for seg in shard.engine.searchable_segments():
        col = seg.numeric_columns.get(field)
        if col is None or col.count == 0:
            continue
        any_col = True
        seg_min = float(col.min_value[seg.live[: seg.nd_pad]].min()) \
            if seg.live[: seg.num_docs].any() else float("inf")
        seg_max = float(col.max_value[seg.live[: seg.nd_pad]].max()) \
            if seg.live[: seg.num_docs].any() else float("-inf")
        if (lo is None or seg_max >= lo) and (hi is None or seg_min <= hi):
            return True
    if not any_col:
        # no doc values for the field on this shard: only unrefreshed
        # buffer docs could match, and the query phase reads sealed
        # segments only — but match the conservative default
        return True
    return False
