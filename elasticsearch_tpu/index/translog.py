"""Per-shard write-ahead log with generations.

Role model: ``Translog`` (core/.../index/translog/Translog.java:94, add:488)
— a sequential op log with monotonically increasing sequence numbers,
generation files rolled at flush, fsync policies (``request`` fsyncs every
write, ``async`` batches), and replay snapshots for recovery
(index/engine/InternalEngine recoverFromTranslog).

Format: one JSON line per operation + a small checkpoint file recording
(generation, max_seqno, last-committed seqno) — the analog of Translog's
``translog.ckp``. JSON-lines keeps ops human-debuggable; the op volume is
host-side and never touches the TPU path.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional


class TranslogOp:
    INDEX = "index"
    DELETE = "delete"
    NO_OP = "no_op"

    def __init__(self, op_type: str, seqno: int, doc_id: Optional[str] = None,
                 source: Optional[dict] = None, routing: Optional[str] = None,
                 version: int = 1, primary_term: int = 1,
                 parent: Optional[str] = None):
        self.op_type = op_type
        self.seqno = seqno
        self.doc_id = doc_id
        self.source = source
        self.routing = routing
        self.version = version
        self.primary_term = primary_term
        # legacy _parent metadata value — persisted alongside routing so
        # the registry survives restart (ParentFieldMapper stores it)
        self.parent = parent

    def to_dict(self) -> dict:
        d = {"op": self.op_type, "seq_no": self.seqno, "primary_term": self.primary_term,
             "version": self.version}
        if self.doc_id is not None:
            d["id"] = self.doc_id
        if self.source is not None:
            d["source"] = self.source
        if self.routing is not None:
            d["routing"] = self.routing
        if self.parent is not None:
            d["parent"] = self.parent
        return d

    @staticmethod
    def from_dict(d: dict) -> "TranslogOp":
        return TranslogOp(
            d["op"], d["seq_no"], d.get("id"), d.get("source"), d.get("routing"),
            d.get("version", 1), d.get("primary_term", 1),
            parent=d.get("parent"),
        )


class Translog:
    DURABILITY_REQUEST = "request"
    DURABILITY_ASYNC = "async"

    def __init__(self, directory: str, durability: str = DURABILITY_REQUEST):
        self.directory = directory
        self.durability = durability
        os.makedirs(directory, exist_ok=True)
        ckp = self._read_checkpoint()
        self.generation: int = ckp.get("generation", 1)
        self.max_seqno: int = ckp.get("max_seqno", -1)
        # ops at or below this seqno are in a committed segment set
        self.committed_seqno: int = ckp.get("committed_seqno", -1)
        self._writer = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self._ops_since_sync = 0

    # ------------------------------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"translog-{gen}.log")

    def _ckp_path(self) -> str:
        return os.path.join(self.directory, "translog.ckp")

    def _read_checkpoint(self) -> dict:
        try:
            with open(self._ckp_path(), encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write_checkpoint(self) -> None:
        tmp = self._ckp_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "generation": self.generation,
                    "max_seqno": self.max_seqno,
                    "committed_seqno": self.committed_seqno,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckp_path())  # atomic, like MetaDataStateFormat

    # ------------------------------------------------------------------

    def add(self, op: TranslogOp) -> None:
        """Append one op; fsync per the durability policy (Translog.add:488)."""
        self._writer.write(json.dumps(op.to_dict(), separators=(",", ":")) + "\n")
        self.max_seqno = max(self.max_seqno, op.seqno)
        if self.durability == self.DURABILITY_REQUEST:
            self.sync()
        else:
            self._ops_since_sync += 1

    def sync(self) -> None:
        self._writer.flush()
        os.fsync(self._writer.fileno())
        self._ops_since_sync = 0
        self._write_checkpoint()

    def roll_generation(self) -> None:
        """Start a new generation file (rolled at flush)."""
        self.sync()
        self._writer.close()
        self.generation += 1
        self._writer = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self._write_checkpoint()

    def mark_committed(self, seqno: int) -> None:
        """Engine flushed a commit covering ops <= seqno; trim old generations
        whose ops are all committed (CombinedDeletionPolicy analog)."""
        self.committed_seqno = max(self.committed_seqno, seqno)
        self.sync()
        # trim: delete generations strictly older than current whose max op
        # seqno <= committed_seqno
        for gen in range(1, self.generation):
            path = self._gen_path(gen)
            if not os.path.exists(path):
                continue
            try:
                ops = list(self._read_gen(gen))
            except (OSError, json.JSONDecodeError):
                continue
            if not ops or all(op.seqno <= self.committed_seqno for op in ops):
                os.remove(path)

    def _read_gen(self, gen: int) -> Iterator[TranslogOp]:
        with open(self._gen_path(gen), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield TranslogOp.from_dict(json.loads(line))

    def snapshot(self, from_seqno: int = 0) -> List[TranslogOp]:
        """All retained ops with seqno >= from_seqno, in log order.
        (Translog.newSnapshot — used by recovery phase2 and resync.)"""
        self._writer.flush()
        out: List[TranslogOp] = []
        for gen in range(1, self.generation + 1):
            if not os.path.exists(self._gen_path(gen)):
                continue
            for op in self._read_gen(gen):
                if op.seqno >= from_seqno:
                    out.append(op)
        return out

    def uncommitted_ops(self) -> List[TranslogOp]:
        return self.snapshot(self.committed_seqno + 1)

    def stats(self) -> dict:
        n_ops = len(self.snapshot(0))
        size = sum(
            os.path.getsize(self._gen_path(g))
            for g in range(1, self.generation + 1)
            if os.path.exists(self._gen_path(g))
        )
        return {
            "operations": n_ops,
            "size_in_bytes": size,
            "uncommitted_operations": len(self.uncommitted_ops()),
            "generation": self.generation,
        }

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._writer.close()
