"""Per-shard write-ahead log with generations.

Role model: ``Translog`` (core/.../index/translog/Translog.java:94, add:488)
— a sequential op log with monotonically increasing sequence numbers,
generation files rolled at flush, fsync policies (``request`` fsyncs every
write, ``async`` batches), and replay snapshots for recovery
(index/engine/InternalEngine recoverFromTranslog).

Format: one JSON line per operation + a small checkpoint file recording
(generation, max_seqno, last-committed seqno) — the analog of Translog's
``translog.ckp``. JSON-lines keeps ops human-debuggable; the op volume is
host-side and never touches the TPU path.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Iterator, List, Optional

from elasticsearch_tpu.common.errors import TranslogCorruptedException

logger = logging.getLogger("elasticsearch_tpu.index.translog")


class TranslogOp:
    INDEX = "index"
    DELETE = "delete"
    NO_OP = "no_op"

    def __init__(self, op_type: str, seqno: int, doc_id: Optional[str] = None,
                 source: Optional[dict] = None, routing: Optional[str] = None,
                 version: int = 1, primary_term: int = 1,
                 parent: Optional[str] = None):
        self.op_type = op_type
        self.seqno = seqno
        self.doc_id = doc_id
        self.source = source
        self.routing = routing
        self.version = version
        self.primary_term = primary_term
        # legacy _parent metadata value — persisted alongside routing so
        # the registry survives restart (ParentFieldMapper stores it)
        self.parent = parent

    def to_dict(self) -> dict:
        d = {"op": self.op_type, "seq_no": self.seqno, "primary_term": self.primary_term,
             "version": self.version}
        if self.doc_id is not None:
            d["id"] = self.doc_id
        if self.source is not None:
            d["source"] = self.source
        if self.routing is not None:
            d["routing"] = self.routing
        if self.parent is not None:
            d["parent"] = self.parent
        return d

    @staticmethod
    def from_dict(d: dict) -> "TranslogOp":
        return TranslogOp(
            d["op"], d["seq_no"], d.get("id"), d.get("source"), d.get("routing"),
            d.get("version", 1), d.get("primary_term", 1),
            parent=d.get("parent"),
        )


class Translog:
    DURABILITY_REQUEST = "request"
    DURABILITY_ASYNC = "async"

    def __init__(self, directory: str, durability: str = DURABILITY_REQUEST):
        self.directory = directory
        self.durability = durability
        os.makedirs(directory, exist_ok=True)
        ckp = self._read_checkpoint()
        self.generation: int = ckp.get("generation", 1)
        self.max_seqno: int = ckp.get("max_seqno", -1)
        # ops at or below this seqno are in a committed segment set
        self.committed_seqno: int = ckp.get("committed_seqno", -1)
        # generations found unreadable below their tail (see _read_gen):
        # surfaced in stats(), retained until fully committed
        self.corrupt_generations: set = set()
        self._trim_torn_tail()
        self._writer = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self._ops_since_sync = 0

    # ------------------------------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"translog-{gen}.log")

    def _ckp_path(self) -> str:
        return os.path.join(self.directory, "translog.ckp")

    def _read_checkpoint(self) -> dict:
        try:
            with open(self._ckp_path(), encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write_checkpoint(self) -> None:
        tmp = self._ckp_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "generation": self.generation,
                    "max_seqno": self.max_seqno,
                    "committed_seqno": self.committed_seqno,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckp_path())  # atomic, like MetaDataStateFormat

    # ------------------------------------------------------------------

    def add(self, op: TranslogOp) -> None:
        """Append one op; fsync per the durability policy (Translog.add:488)."""
        self._writer.write(json.dumps(op.to_dict(), separators=(",", ":")) + "\n")
        self.max_seqno = max(self.max_seqno, op.seqno)
        if self.durability == self.DURABILITY_REQUEST:
            self.sync()
        else:
            self._ops_since_sync += 1

    def sync(self) -> None:
        self._writer.flush()
        os.fsync(self._writer.fileno())
        self._ops_since_sync = 0
        self._write_checkpoint()

    def roll_generation(self) -> None:
        """Start a new generation file (rolled at flush)."""
        self.sync()
        self._writer.close()
        self.generation += 1
        self._writer = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self._write_checkpoint()

    def mark_committed(self, seqno: int) -> None:
        """Engine flushed a commit covering ops <= seqno; trim old generations
        whose ops are all committed (CombinedDeletionPolicy analog).

        A generation that cannot be READ is never silently skipped (the
        old behavior retained it forever, masking the corruption): it is
        recorded in ``corrupt_generations`` / stats() with a warning, and
        deleted only once EVERYTHING ever logged is committed — an
        unreadable file can hide ops, so the conservative bound is the
        checkpoint's own max_seqno."""
        self.committed_seqno = max(self.committed_seqno, seqno)
        self.sync()
        # trim: delete generations strictly older than current whose max op
        # seqno <= committed_seqno
        for gen in range(1, self.generation):
            path = self._gen_path(gen)
            if not os.path.exists(path):
                continue
            try:
                ops = list(self._read_gen(gen))
            except OSError:
                continue
            except TranslogCorruptedException:
                if gen not in self.corrupt_generations:
                    self.corrupt_generations.add(gen)
                    logger.warning(
                        "[%s] translog generation [%d] is corrupt; "
                        "retained until its seqno range is fully committed",
                        self.directory, gen)
                if self.committed_seqno >= self.max_seqno:
                    os.remove(path)
                    self.corrupt_generations.discard(gen)
                continue
            if not ops or all(op.seqno <= self.committed_seqno for op in ops):
                os.remove(path)
                self.corrupt_generations.discard(gen)

    def _trim_torn_tail(self) -> None:
        """Cut a benign torn final line off the newest generation BEFORE
        reopening it for append: the writer opens in append mode, so a
        crash-cut fragment left in place would have the next acked op
        CONCATENATED onto it — one unparseable merged line that silently
        swallows the new op (or, once buried mid-file, fails recovery of
        everything). Only the case _read_gen would tolerate is trimmed;
        a tear that could hide checkpointed ops, or any unreadable line
        before the tail, is left intact so recovery raises
        TranslogCorruptedException instead of destroying the evidence."""
        path = self._gen_path(self.generation)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        if not data or data.endswith(b"\n"):
            return
        head, _sep, tail = data.rpartition(b"\n")
        try:
            json.loads(tail.decode("utf-8"))
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass
        else:
            # a COMPLETE op missing only its newline (crash between the
            # json write and the terminator): finish the line instead of
            # dropping a durable op
            with open(path, "ab") as f:
                f.write(b"\n")
                f.flush()
                os.fsync(f.fileno())
            return
        last_seqno = -1
        any_read = False
        intact = True
        for line in head.split(b"\n"):
            if not line.strip():
                continue
            try:
                d = json.loads(line.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                intact = False  # damage before the tail: don't touch
                break
            last_seqno = d.get("seq_no", -1)
            any_read = True
        if not (intact and self._benign_torn_tail(self.generation,
                                                  last_seqno, any_read)):
            return
        with open(path, "ab") as f:
            f.truncate(len(head) + len(_sep))
            f.flush()
            os.fsync(f.fileno())
        logger.warning(
            "[%s] translog generation [%d] had a truncated final line "
            "(crash mid-append); trimmed, replay resumes at seqno [%d]",
            self.directory, self.generation, last_seqno)

    def _benign_torn_tail(self, gen: int, last_seqno: int,
                          any_read: bool) -> bool:
        """THE safety invariant shared by trim-at-open and replay: a torn
        final line is benign only when nothing checkpointed can sit
        beyond the tear — every op at or below the committed seqno was
        already read from this generation, or the generation holds no
        readable op at all (a rolled file whose only append was the torn,
        never-acked one)."""
        return (last_seqno >= self.committed_seqno
                or (not any_read and gen > 1))

    def _read_gen(self, gen: int,
                  tolerate_tail: bool = False) -> Iterator[TranslogOp]:
        """Ops of one generation file, in log order.

        ``tolerate_tail`` (the NEWEST generation during recovery): a
        crash mid-append leaves a partial final JSON line — replay stops
        there with a warning, because the torn op was never acked. Any
        OTHER unreadable line — mid-file, an older generation, or a tail
        whose loss would swallow ops at or below the checkpointed
        committed seqno — raises ``TranslogCorruptedException``: acked
        data is gone and recovery must not pretend otherwise."""
        with open(self._gen_path(gen), encoding="utf-8") as f:
            lines = f.read().split("\n")
        last_seqno = -1
        any_read = False
        for i, raw in enumerate(lines):
            line = raw.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                is_tail = all(not rest.strip() for rest in lines[i + 1:])
                if tolerate_tail and is_tail and self._benign_torn_tail(
                        gen, last_seqno, any_read):
                    logger.warning(
                        "[%s] translog generation [%d] has a truncated "
                        "final line (crash mid-append); replay stops at "
                        "seqno [%d]", self.directory, gen, last_seqno)
                    return
                raise TranslogCorruptedException(
                    f"translog generation [{gen}] unreadable at line "
                    f"[{i + 1}]"
                    + ("" if is_tail else " (mid-file)")
                    + (f"; ops at or below the checkpointed seqno "
                       f"[{self.committed_seqno}] may be lost"
                       if last_seqno < self.committed_seqno else ""))
            op = TranslogOp.from_dict(d)
            last_seqno = op.seqno
            any_read = True
            yield op

    def snapshot(self, from_seqno: int = 0,
                 on_corruption: str = "raise") -> List[TranslogOp]:
        """All retained ops with seqno >= from_seqno, in log order.
        (Translog.newSnapshot — used by recovery phase2 and resync.)
        ``on_corruption``: "raise" (recovery must fail loudly) or "skip"
        (observability paths keep serving the readable generations)."""
        self._writer.flush()
        out: List[TranslogOp] = []
        for gen in range(1, self.generation + 1):
            if not os.path.exists(self._gen_path(gen)):
                continue
            try:
                for op in self._read_gen(
                        gen, tolerate_tail=gen == self.generation):
                    if op.seqno >= from_seqno:
                        out.append(op)
            except TranslogCorruptedException:
                self.corrupt_generations.add(gen)
                if on_corruption == "raise":
                    raise
        return out

    def uncommitted_ops(self) -> List[TranslogOp]:
        return self.snapshot(self.committed_seqno + 1)

    def stats(self) -> dict:
        ops = self.snapshot(0, on_corruption="skip")
        size = sum(
            os.path.getsize(self._gen_path(g))
            for g in range(1, self.generation + 1)
            if os.path.exists(self._gen_path(g))
        )
        retained = [g for g in range(1, self.generation + 1)
                    if os.path.exists(self._gen_path(g))]
        return {
            "operations": len(ops),
            "size_in_bytes": size,
            "uncommitted_operations": len(
                [op for op in ops if op.seqno > self.committed_seqno]),
            "generation": self.generation,
            # retention observability: a corrupt old generation must be
            # VISIBLE, not silently pinned (mark_committed docstring)
            "earliest_retained_generation": min(retained,
                                                default=self.generation),
            "corrupt_generations": sorted(self.corrupt_generations),
        }

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._writer.close()
