"""Index sorting: segments store documents pre-sorted by configured keys.

Role model: ``IndexSortConfig`` (reference:
core/src/main/java/org/elasticsearch/index/IndexSortConfig.java) — the
``index.sort.field/order/missing/mode`` settings validated at index
creation, plus the sorted-index early-termination hook in
``QueryPhase.execute`` (search/query/QueryPhase.java:107): when a query
sorts by a prefix of the index sort, collection stops after k hits.

TPU mapping: the sort permutation is applied once at segment seal (host
side), so doc order *is* sort order in every packed array. The query path
then selects the first k matching docs in doc order — no sort-key
orientation or top-k pass — and reports ``terminated_early`` like the
reference. Unlike the reference (which stops counting), the exhaustive
dense-mask execution gets the exact total for free, so totals stay
accurate.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentException

# (field, order, missing, mode)
SortSpec = List[Tuple[str, str, str, str]]

_SORTABLE_TYPES = {
    "long", "integer", "short", "byte", "double", "float", "half_float",
    "scaled_float", "date", "boolean", "keyword", "ip",
}


def parse_index_sort(settings, mapper_service) -> Optional[SortSpec]:
    """Parse + validate ``index.sort.*`` settings against the mapping.

    Raises IllegalArgumentException for unknown fields or unsortable field
    types (IndexSortConfig.java: "unknown index sort field" /
    "docvalues not found for index sort field").
    """
    fields = settings.get_list("index.sort.field")
    if not fields:
        return None
    orders = settings.get_list("index.sort.order") or []
    missings = settings.get_list("index.sort.missing") or []
    modes = settings.get_list("index.sort.mode") or []

    def nth(lst, i, default):
        # option arrays must match the field array length exactly
        # (IndexSortConfig: a single-element list is NOT broadcast over
        # multiple sort fields)
        if not lst:
            return default
        if len(lst) != len(fields):
            raise IllegalArgumentException(
                f"index.sort option lists must match index.sort.field length "
                f"({len(fields)})")
        return lst[i]

    spec: SortSpec = []
    for i, field in enumerate(fields):
        order = str(nth(orders, i, "asc")).lower()
        if order not in ("asc", "desc"):
            raise IllegalArgumentException(f"Illegal sort order: {order}")
        missing = str(nth(missings, i, "_last"))
        if missing not in ("_last", "_first"):
            raise IllegalArgumentException(
                f"Illegal missing value: {missing}, must be one of [_last, _first]")
        mode = str(nth(modes, i, "min" if order == "asc" else "max")).lower()
        if mode not in ("min", "max"):
            raise IllegalArgumentException(
                f"Illegal sort mode: {mode}, must be one of [min, max]")
        ft = mapper_service.field_type(field)
        if ft is None:
            raise IllegalArgumentException(f"unknown index sort field:[{field}]")
        nested_paths = getattr(mapper_service.mapper, "nested_paths", {})
        if any(field == p or field.startswith(p + ".") for p in nested_paths):
            raise IllegalArgumentException(
                "index sorting on a field inside a nested object is not "
                f"supported: [{field}]")
        if ft.type_name not in _SORTABLE_TYPES:
            raise IllegalArgumentException(
                f"invalid index sort field:[{field}] of type [{ft.type_name}] "
                "(index sorting requires doc values)")
        if not getattr(ft, "doc_values", True):
            raise IllegalArgumentException(
                f"docvalues not found for index sort field:[{field}]")
        spec.append((field, order, missing, mode))
    return spec


_NUMERIC_SORT_TYPES = _SORTABLE_TYPES - {"keyword", "ip"}


def _query_key_mode(mapper_service, field: str, order: str) -> str:
    """The multi-value reduction the *query* sort path applies
    (service.py _sort_keys): numeric fields use min for asc / max for
    desc; ordinal (keyword/ip) keys always use the first (min) ordinal."""
    ft = mapper_service.field_type(field) if mapper_service else None
    if ft is not None and ft.type_name in _NUMERIC_SORT_TYPES:
        return "min" if order == "asc" else "max"
    return "min"


def query_sort_matches_index_sort(query_sort, index_sort: Optional[SortSpec],
                                  mapper_service=None) -> bool:
    """True when the query's sort is a prefix of the index sort — the
    early-termination eligibility check (QueryPhase.java:107
    canEarlyTerminate, which requires full SortField equality).

    Field + order must match; the query's missing placement must agree
    with the index sort's (custom numeric missing values disqualify); and
    the index sort's multi-value mode must equal the reduction the query
    sort path applies, else segment doc order can disagree with the
    cross-segment merge keys on multi-valued docs.
    """
    if not index_sort or not query_sort:
        return False
    if len(query_sort) > len(index_sort):
        return False
    for (qf, qorder, qmissing), (sf, sorder, smissing, smode) in zip(
            query_sort, index_sort):
        if qf != sf or qorder != sorder:
            return False
        q_missing = qmissing if qmissing is not None else "_last"
        if q_missing != smissing:
            return False
        if smode != _query_key_mode(mapper_service, sf, sorder):
            return False
    return True


def index_sort_permutation(builder, spec: SortSpec) -> Optional[np.ndarray]:
    """Compute the doc permutation (new order -> old doc) for a sealed
    builder. Stable: equal keys keep insertion (seqno) order."""
    n = builder.num_docs
    if n <= 1:
        return None
    lex_keys = []
    for field, order, missing, mode in reversed(spec):  # lexsort: last = primary
        fill = np.inf if missing == "_last" else -np.inf
        vals = np.full(n, np.nan, np.float64)
        have = np.zeros(n, bool)
        numeric = builder.numeric_values.get(field)
        if numeric is not None:
            for doc, v in numeric:
                v = float(v)
                if not have[doc]:
                    vals[doc] = v
                    have[doc] = True
                else:
                    vals[doc] = min(vals[doc], v) if mode == "min" else max(vals[doc], v)
        else:
            strings = builder.string_values.get(field) or []
            # rank strings so the float lexsort key preserves their order
            per_doc: dict = {}
            for doc, s in strings:
                cur = per_doc.get(doc)
                if cur is None:
                    per_doc[doc] = s
                else:
                    per_doc[doc] = min(cur, s) if mode == "min" else max(cur, s)
            rank = {s: i for i, s in enumerate(sorted(set(per_doc.values())))}
            for doc, s in per_doc.items():
                vals[doc] = float(rank[s])
                have[doc] = True
        oriented = np.where(have, -vals if order == "desc" else vals, fill)
        lex_keys.append(oriented)
    return np.lexsort(lex_keys)
