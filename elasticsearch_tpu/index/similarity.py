"""Pluggable per-field similarities (scoring models).

Role model: ``SimilarityService`` (reference:
core/src/main/java/org/elasticsearch/index/similarity/SimilarityService.java)
with BM25 as the default and classic TF-IDF, boolean, DFR, IB,
LM-Dirichlet and LM-Jelinek-Mercer as configurable alternatives
(``index/similarity/*Provider.java``). Custom similarities are declared in
index settings (``index.similarity.<name>.type`` + model params) and bound
to fields via the mapping's ``"similarity"`` parameter.

TPU-first inversion: the reference's ``Similarity`` produces a per-segment
``SimScorer`` object invoked doc-at-a-time inside Lucene's BulkScorer.
Here a similarity is split into
  * host-side per-term constant folding (``lane_params``): everything that
    depends only on corpus statistics (df, ttf, N, sum_ttf, avgdl) is
    precomputed into <= 3 scalars per posting-block lane, and
  * a vectorized contribution formula over ``(tf, doc_len)`` traced into
    the query program (see ``emit_contrib``), selected statically by the
    similarity *kind* string so XLA compiles only the formulas a query
    actually uses.

Formulas follow Lucene 7 (``BM25Similarity``, ``ClassicSimilarity``,
``BooleanSimilarity``, ``SimilarityBase`` subclasses: ``DFRSimilarity``,
``IBSimilarity``, ``LMDirichletSimilarity``, ``LMJelinekMercerSimilarity``).
Like Lucene's ``SimilarityBase``, LM scores are clamped at zero so that a
matching doc never scores negative (keeps "matched => score >= 0").
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from elasticsearch_tpu.common.errors import IllegalArgumentException

LOG2 = math.log(2.0)


def _log2(x: float) -> float:
    return math.log(x) / LOG2


class Similarity:
    """Base: a similarity folds per-term stats into lane constants.

    ``lane_params(stats)`` -> (kind, weight, p1, p2, p3) where stats is a
    dict with df, ttf, doc_count (N), sum_ttf (T), avgdl, boost.
    ``kind`` is a static string keying the traced formula.
    """

    name = "base"
    # whether lane_params reads stats["ttf"] — computing total term
    # frequency costs an O(postings) host pass, skipped when unused
    needs_ttf = False

    def lane_params(self, stats: dict) -> Tuple[str, float, float, float, float]:
        raise NotImplementedError


class BM25Similarity(Similarity):
    name = "BM25"

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = float(k1)
        self.b = float(b)

    def idf(self, df: int, n: int) -> float:
        return math.log(1.0 + (n - df + 0.5) / (df + 0.5))

    def lane_params(self, stats):
        w = stats["boost"] * self.idf(stats["df"], stats["doc_count"])
        return ("bm25", w, self.k1, self.b, 0.0)


class ClassicSimilarity(Similarity):
    """Lucene ClassicSimilarity: sqrt(tf) * idf^2 * 1/sqrt(dl)."""

    name = "classic"

    def lane_params(self, stats):
        idf = 1.0 + math.log((stats["doc_count"] + 1.0) / (stats["df"] + 1.0))
        return ("classic", stats["boost"] * idf * idf, 0.0, 0.0, 0.0)


class BooleanSimilarity(Similarity):
    name = "boolean"

    def lane_params(self, stats):
        return ("boolean", stats["boost"], 0.0, 0.0, 0.0)


class LMDirichletSimilarity(Similarity):
    name = "LMDirichlet"
    needs_ttf = True

    def __init__(self, mu: float = 2000.0):
        self.mu = float(mu)

    def lane_params(self, stats):
        # DefaultCollectionModel: p(t|C) = (F + 1) / (T + 1)
        pc = (stats["ttf"] + 1.0) / (stats["sum_ttf"] + 1.0)
        return ("lm_dirichlet", stats["boost"], self.mu, pc, 0.0)


class LMJelinekMercerSimilarity(Similarity):
    name = "LMJelinekMercer"
    needs_ttf = True

    def __init__(self, lam: float = 0.1):
        if not 0.0 < lam <= 1.0:
            raise IllegalArgumentException("lambda must be in (0, 1]")
        self.lam = float(lam)

    def lane_params(self, stats):
        pc = (stats["ttf"] + 1.0) / (stats["sum_ttf"] + 1.0)
        return ("lm_jm", stats["boost"], self.lam, pc, 0.0)


class DFRSimilarity(Similarity):
    """Divergence-from-randomness: basic_model x after_effect x
    normalization (reference: DFRSimilarityProvider.java)."""

    name = "DFR"
    needs_ttf = True
    BASIC_MODELS = ("g", "if", "in", "ine")
    AFTER_EFFECTS = ("no", "b", "l")
    NORMALIZATIONS = ("no", "h1", "h2", "z")

    def __init__(self, basic_model: str = "g", after_effect: str = "l",
                 normalization: str = "h2", c: float = 1.0, z: float = 0.30):
        basic_model = basic_model.lower()
        after_effect = after_effect.lower()
        normalization = normalization.lower()
        if basic_model not in self.BASIC_MODELS:
            raise IllegalArgumentException(
                f"Unsupported BasicModel [{basic_model}]")
        if after_effect not in self.AFTER_EFFECTS:
            raise IllegalArgumentException(
                f"Unsupported AfterEffect [{after_effect}]")
        if normalization not in self.NORMALIZATIONS:
            raise IllegalArgumentException(
                f"Unsupported Normalization [{normalization}]")
        self.basic_model = basic_model
        self.after_effect = after_effect
        self.normalization = normalization
        self.c = float(c)
        self.z = float(z)

    def lane_params(self, stats):
        n, df, f = stats["doc_count"], stats["df"], stats["ttf"]
        # fold the per-term basic-model constants host-side
        if self.basic_model == "g":
            lam = f / (n + f) if (n + f) > 0 else 0.5
            p2 = _log2(1.0 + lam)              # additive part
            p3 = _log2((1.0 + lam) / max(lam, 1e-12))  # per-tfn slope
        elif self.basic_model == "if":
            # BasicModelIF: tfn * log2(1 + (N+1)/(F+0.5))
            p2 = 0.0
            p3 = _log2(1.0 + (n + 1.0) / (f + 0.5))
        else:
            if self.basic_model == "in":
                x = df
            else:  # ine — BasicModelIne: ne = N*(1 - ((N-1)/N)^F)
                x = n * (1.0 - math.pow((n - 1.0) / n, f)) if n > 0 else df
            p2 = 0.0
            p3 = _log2((n + 1.0) / (x + 0.5))
        if self.after_effect == "b":
            ae_const = (f + 1.0) / max(df, 1)
        else:
            ae_const = 1.0  # "l" divides by (tfn+1); "no" is identity
        kind = f"dfr:{self.basic_model}:{self.after_effect}:{self.normalization}"
        # p1 carries the normalization parameter (c for h1/h2, z for z)
        p1 = self.z if self.normalization == "z" else self.c
        return (kind, stats["boost"] * ae_const, p1, p2, p3)


class IBSimilarity(Similarity):
    """Information-based: distribution x lambda x normalization
    (reference: IBSimilarityProvider.java)."""

    name = "IB"
    needs_ttf = True
    DISTRIBUTIONS = ("ll", "spl")
    LAMBDAS = ("df", "ttf")
    NORMALIZATIONS = ("no", "h1", "h2", "z")

    def __init__(self, distribution: str = "ll", lam: str = "df",
                 normalization: str = "h2", c: float = 1.0, z: float = 0.30):
        distribution = distribution.lower()
        lam = lam.lower()
        normalization = normalization.lower()
        if distribution not in self.DISTRIBUTIONS:
            raise IllegalArgumentException(
                f"Unsupported Distribution [{distribution}]")
        if lam not in self.LAMBDAS:
            raise IllegalArgumentException(f"Unsupported Lambda [{lam}]")
        if normalization not in self.NORMALIZATIONS:
            raise IllegalArgumentException(
                f"Unsupported Normalization [{normalization}]")
        self.distribution = distribution
        self.lam = lam
        self.normalization = normalization
        self.c = float(c)
        self.z = float(z)

    def lane_params(self, stats):
        n = stats["doc_count"]
        if self.lam == "df":
            lam = (stats["df"] + 1.0) / (n + 1.0)
        else:
            lam = (stats["ttf"] + 1.0) / (n + 1.0)
        kind = f"ib:{self.distribution}:{self.normalization}"
        p1 = self.z if self.normalization == "z" else self.c
        return (kind, stats["boost"], p1, lam, 0.0)


# ---------------------------------------------------------------------------
# Traced contribution formulas (device side)
# ---------------------------------------------------------------------------


def _tfn(norm: str, tf, dl, avgdl, p1):
    """DFR/IB term-frequency normalization (Lucene NormalizationH1/H2/Z)."""
    if norm == "no":
        return tf
    if norm == "h1":
        return p1 * tf * avgdl / dl  # NormalizationH1: c * tf * avgdl/len
    if norm == "h2":
        return tf * jnp.log2(1.0 + p1 * avgdl / dl)
    if norm == "z":
        return tf * jnp.power(avgdl / dl, p1)
    raise IllegalArgumentException(f"unknown normalization [{norm}]")


def emit_contrib(kind: str, tf, dl, w, avgdl, p1, p2, p3):
    """Per-lane score contribution for one static similarity kind.

    All args are [QB, BLOCK]-broadcastable jnp arrays except ``kind``.
    Returns contributions (>= 0) for matching postings; callers mask
    non-matching (tf == 0) lanes out.
    """
    if kind == "bm25":
        # p1 = k1, p2 = b
        return w * tf * (p1 + 1.0) / (tf + p1 * (1.0 - p2 + p2 * dl / avgdl))
    if kind == "classic":
        return w * jnp.sqrt(tf) / jnp.sqrt(jnp.maximum(dl, 1.0))
    if kind == "boolean":
        return w * (tf > 0.0)
    if kind == "lm_dirichlet":
        # p1 = mu, p2 = p(t|C)
        s = jnp.log2(1.0 + tf / (p1 * p2)) + jnp.log2(p1 / (dl + p1))
        return jnp.maximum(w * s, 0.0)
    if kind == "lm_jm":
        # p1 = lambda, p2 = p(t|C)
        s = jnp.log2(1.0 + ((1.0 - p1) * tf / jnp.maximum(dl, 1.0)) / (p1 * p2))
        return jnp.maximum(w * s, 0.0)
    if kind.startswith("dfr:"):
        _, bm, ae, norm = kind.split(":")
        tfn = _tfn(norm, tf, dl, avgdl, p1)
        if bm == "g":
            basic = p2 + tfn * p3  # log2(1+lam) + tfn*log2((1+lam)/lam)
        else:
            basic = tfn * p3  # tfn * log2((N+1)/(x+0.5))
        if ae in ("b", "l"):
            basic = basic / (tfn + 1.0)  # B's (F+1)/df constant is folded in w
        return jnp.maximum(w * basic, 0.0)
    if kind.startswith("ib:"):
        _, dist, norm = kind.split(":")
        tfn = _tfn(norm, tf, dl, avgdl, p1)
        lam = p2
        if dist == "ll":
            s = -jnp.log2(lam / (tfn + lam))
        else:  # spl
            num = jnp.power(lam, tfn / (tfn + 1.0)) - lam
            s = -jnp.log2(jnp.maximum(num, 1e-12) / (1.0 - lam))
        return jnp.maximum(w * s, 0.0)
    raise IllegalArgumentException(f"unknown similarity kind [{kind}]")


# kinds whose contributions are strictly positive for tf > 0 and w > 0 —
# eligible for the single-scatter "score > 0 == matched" fast path
STRICTLY_POSITIVE_KINDS = {"bm25", "classic", "boolean"}


# ---------------------------------------------------------------------------
# SimilarityService
# ---------------------------------------------------------------------------


def _build(type_name: str, cfg: dict) -> Similarity:
    t = type_name
    if t == "BM25":
        return BM25Similarity(k1=float(cfg.get("k1", 1.2)),
                              b=float(cfg.get("b", 0.75)))
    if t == "classic":
        return ClassicSimilarity()
    if t == "boolean":
        return BooleanSimilarity()
    if t == "LMDirichlet":
        return LMDirichletSimilarity(mu=float(cfg.get("mu", 2000.0)))
    if t == "LMJelinekMercer":
        return LMJelinekMercerSimilarity(lam=float(cfg.get("lambda", 0.1)))
    if t in ("DFR", "IB"):
        # the c parameter comes from the key matching the *configured*
        # normalization (normalization.h1.c for h1, .h2.c for h2, ...);
        # a stray key for a different normalization is ignored
        norm = str(cfg.get("normalization", "h2"))
        c = float(cfg.get(f"normalization.{norm}.c", 1.0))
        z = float(cfg.get("normalization.z.z", 0.30))
        if t == "DFR":
            return DFRSimilarity(
                basic_model=str(cfg.get("basic_model", "g")),
                after_effect=str(cfg.get("after_effect", "l")),
                normalization=norm, c=c, z=z,
            )
        return IBSimilarity(
            distribution=str(cfg.get("distribution", "ll")),
            lam=str(cfg.get("lambda", "df")),
            normalization=norm, c=c, z=z,
        )
    raise IllegalArgumentException(f"Unknown Similarity type [{t}]")


class SimilarityService:
    """Resolves similarity names for an index.

    Built-ins: BM25 (default), classic, boolean. Custom similarities come
    from ``index.similarity.<name>.type`` (+ params) in the index settings;
    ``index.similarity.default.type`` overrides the index default
    (reference: SimilarityService.java:45-75).
    """

    def __init__(self, settings=None):
        self._sims: Dict[str, Similarity] = {
            "BM25": BM25Similarity(),
            "classic": ClassicSimilarity(),
            "boolean": BooleanSimilarity(),
        }
        if settings is not None:
            groups: Dict[str, dict] = {}
            for key in settings.keys():
                if not key.startswith("index.similarity."):
                    continue
                rest = key[len("index.similarity."):]
                name, _, param = rest.partition(".")
                if name and param:
                    groups.setdefault(name, {})[param] = settings.get(key)
            for name, cfg in groups.items():
                if "type" not in cfg:
                    raise IllegalArgumentException(
                        f"similarity [{name}] must declare a type")
                self._sims[name] = _build(str(cfg["type"]), cfg)
        self.default: Similarity = self._sims.get("default", self._sims["BM25"])

    def get(self, name: Optional[str]) -> Similarity:
        if name is None:
            return self.default
        sim = self._sims.get(name)
        if sim is None:
            raise IllegalArgumentException(f"Unknown Similarity [{name}]")
        return sim
