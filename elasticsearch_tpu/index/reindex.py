"""Reindex / update-by-query / delete-by-query.

Role model: ``modules/reindex`` (TransportReindexAction:87,
AbstractAsyncBulkByScrollAction) — scroll+bulk loops with per-batch
progress recorded on a BulkByScrollTask. The scan uses sliced _doc-ordered
scroll pages, exactly the reference's machinery.
"""

from __future__ import annotations

import time
from typing import Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException

DEFAULT_BATCH = 1000


def _scan_batches(node, index_expr: str, query: Optional[dict], batch_size: int):
    """Yield batches of hits by walking shards/segments directly — the
    exact-cursor equivalent of the reference's _doc-ordered scroll (a
    Lucene doc id is only unique within a segment, so the cursor is
    (shard, segment, local_doc), not a sort value)."""
    import numpy as np

    from elasticsearch_tpu.search import plan as P
    from elasticsearch_tpu.search.query_dsl import ShardQueryContext, parse_query

    qb = parse_query(query or {"match_all": {}})
    batch = []
    for svc in node.resolve_search_indices(index_expr):
        ctx = ShardQueryContext(svc.mapper_service)
        for sid in sorted(svc.shards):
            shard = svc.shards[sid]
            for seg in shard.engine.searchable_segments():
                _, matched = P.execute(seg.device_arrays(), qb.to_plan(ctx, seg))
                matched = np.asarray(matched)[: seg.num_docs] & seg.live[: seg.num_docs]
                for local in np.nonzero(matched)[0]:
                    batch.append({
                        "_index": svc.name,
                        "_id": seg.doc_ids[local],
                        "_source": seg.sources[local],
                    })
                    if len(batch) >= batch_size:
                        yield batch
                        batch = []
    if batch:
        yield batch


def reindex(node, body: dict) -> dict:
    t0 = time.monotonic()
    source = body.get("source") or {}
    dest = body.get("dest") or {}
    src_index = source.get("index")
    dst_index = dest.get("index")
    if not src_index or not dst_index:
        raise IllegalArgumentException("reindex requires source.index and dest.index")
    batch_size = int(source.get("size", DEFAULT_BATCH))
    max_docs = body.get("max_docs") or body.get("size")
    op_type = dest.get("op_type", "index")
    pipeline = dest.get("pipeline")
    task = node.tasks.register("indices:data/write/reindex",
                               f"reindex from [{src_index}] to [{dst_index}]")
    created = updated = total = 0
    failures = []
    try:
        for hits in _scan_batches(node, src_index, source.get("query"), batch_size):
            task.ensure_not_cancelled()
            ops = []
            for h in hits:
                if max_docs is not None and total >= int(max_docs):
                    break
                total += 1
                ops.append((
                    "create" if op_type == "create" else "index",
                    {"_index": dst_index, "_id": h["_id"], "pipeline": pipeline},
                    h["_source"],
                ))
            if not ops:
                break
            resp = node.bulk(ops)
            for item in resp["items"]:
                r = next(iter(item.values()))
                if "error" in r:
                    failures.append(r["error"])
                elif r.get("result") == "created":
                    created += 1
                else:
                    updated += 1
            task.status = {"total": total, "created": created, "updated": updated}
            if max_docs is not None and total >= int(max_docs):
                break
    finally:
        node.tasks.unregister(task)
    if dst_index in node.indices:
        node.indices[dst_index].refresh()
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "total": total,
        "created": created,
        "updated": updated,
        "deleted": 0,
        "batches": -(-total // batch_size) if total else 0,
        "version_conflicts": 0,
        "noops": 0,
        "retries": {"bulk": 0, "search": 0},
        "failures": failures,
    }


def update_by_query(node, index_expr: str, body: Optional[dict]) -> dict:
    """Re-indexes matching docs in place (no script support yet: the
    reference's script hook maps to ingest-style mutations via `script`
    param in later rounds; a bare update_by_query refreshes mappings)."""
    t0 = time.monotonic()
    body = body or {}
    updated = total = 0
    task = node.tasks.register("indices:data/write/update/byquery",
                               f"update-by-query [{index_expr}]")
    try:
        for hits in _scan_batches(node, index_expr, body.get("query"), DEFAULT_BATCH):
            task.ensure_not_cancelled()
            ops = [("index", {"_index": h["_index"], "_id": h["_id"]}, h["_source"])
                   for h in hits]
            total += len(ops)
            resp = node.bulk(ops)
            updated += sum(1 for i in resp["items"] if "error" not in next(iter(i.values())))
            task.status = {"total": total, "updated": updated}
    finally:
        node.tasks.unregister(task)
    for name in node.cluster_service.state.resolve_index_names(index_expr):
        node.indices[name].refresh()
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "total": total,
        "updated": updated,
        "deleted": 0,
        "version_conflicts": 0,
        "noops": 0,
        "failures": [],
    }


def delete_by_query(node, index_expr: str, body: Optional[dict]) -> dict:
    t0 = time.monotonic()
    body = body or {}
    if "query" not in body:
        raise IllegalArgumentException("delete_by_query requires a query in the request body")
    deleted = total = 0
    task = node.tasks.register("indices:data/write/delete/byquery",
                               f"delete-by-query [{index_expr}]")
    try:
        for hits in _scan_batches(node, index_expr, body.get("query"), DEFAULT_BATCH):
            task.ensure_not_cancelled()
            total += len(hits)
            for h in hits:
                r = node.delete_doc(h["_index"], h["_id"])
                if r.get("found"):
                    deleted += 1
            task.status = {"total": total, "deleted": deleted}
    finally:
        node.tasks.unregister(task)
    for name in node.cluster_service.state.resolve_index_names(index_expr):
        node.indices[name].refresh()
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "total": total,
        "deleted": deleted,
        "version_conflicts": 0,
        "noops": 0,
        "failures": [],
    }
