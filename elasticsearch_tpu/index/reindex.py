"""Reindex / update-by-query / delete-by-query.

Role model: ``modules/reindex`` (TransportReindexAction:87,
AbstractAsyncBulkByScrollAction) — scroll+bulk loops with per-batch
progress recorded on a BulkByScrollTask. The scan uses sliced _doc-ordered
scroll pages, exactly the reference's machinery.
"""

from __future__ import annotations

import time
from typing import Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException

DEFAULT_BATCH = 1000


def _compile_byquery_script(body: dict):
    """The reference's script hook on reindex/update_by_query
    (AbstractAsyncBulkByScrollAction.buildScriptApplier): a painless
    script mutating ctx._source, with ctx.op controlling per-doc fate
    (index | noop | delete). Returns None when no script is given."""
    spec = body.get("script")
    if spec is None:
        return None
    from elasticsearch_tpu.script.expression import compile_script

    script = compile_script(spec)
    if not hasattr(script, "run"):
        raise IllegalArgumentException(
            "by-query scripts must be painless (ctx mutation)")
    params = (spec.get("params") if isinstance(spec, dict) else None) or {}
    return script, params


def _apply_byquery_script(compiled, hit) -> str:
    """Run the script against one hit; returns the resulting op.

    The hit's _source is DEEP-copied first: _scan_batches hands out the
    segment's live stored-source dicts, and a script mutating a nested
    object (then nooping) must never alter data that was never written
    back through the engine. ctx._id/_index rewrites (reindex routing
    scripts) propagate to the hit."""
    import copy

    from elasticsearch_tpu.script.painless import ScriptException

    script, params = compiled
    ctx = {"_source": copy.deepcopy(hit["_source"]),
           "_index": hit["_index"], "_id": hit["_id"], "op": "index"}
    script.run({"ctx": ctx, "params": dict(params)})
    op = ctx.get("op", "index")
    if op not in ("index", "none", "noop", "delete", "create"):
        raise ScriptException(f"Operation type [{op}] not allowed")
    hit["_source"] = ctx["_source"]
    hit["_index"] = ctx.get("_index", hit["_index"])
    hit["_id"] = str(ctx.get("_id", hit["_id"]))
    return "none" if op == "noop" else op


def _scan_batches(node, index_expr: str, query: Optional[dict], batch_size: int):
    """Yield batches of hits by walking a POINT-IN-TIME snapshot of every
    shard's segments — the reference's sliced-scroll source reader
    (AbstractAsyncBulkByScrollAction over a pinned ScrollContext). The
    whole segment set + live masks are pinned up front, so writes issued
    while the reindex/update-by-query consumer drains batches can never
    skip, duplicate, or half-apply to the scanned docs. The cursor is
    (shard, segment, local_doc) — a Lucene doc id is only unique within
    a segment, so it cannot be a sort value."""
    import numpy as np

    from elasticsearch_tpu.index.segment import PinnedSegmentView
    from elasticsearch_tpu.search import plan as P
    from elasticsearch_tpu.search.query_dsl import ShardQueryContext, parse_query

    qb = parse_query(query or {"match_all": {}})
    snapshot = []  # (svc, ctx, [views]) pinned BEFORE any batch yields
    for svc in node.resolve_search_indices(index_expr):
        ctx = ShardQueryContext(svc.mapper_service)
        for sid in sorted(svc.shards):
            shard = svc.shards[sid]
            snapshot.append((svc, ctx, [
                PinnedSegmentView(s)
                for s in shard.engine.searchable_segments()]))
    batch = []
    for svc, ctx, views in snapshot:
        for seg in views:
            _, matched = P.execute(seg.device_arrays(), qb.to_plan(ctx, seg))
            matched = np.asarray(matched)[: seg.num_docs] & seg.live[: seg.num_docs]
            for local in np.nonzero(matched)[0]:
                batch.append({
                    "_index": svc.name,
                    "_id": seg.doc_ids[local],
                    "_source": seg.sources[local],
                })
                if len(batch) >= batch_size:
                    yield batch
                    batch = []
    if batch:
        yield batch


def reindex(node, body: dict) -> dict:
    t0 = time.monotonic()
    source = body.get("source") or {}
    dest = body.get("dest") or {}
    src_index = source.get("index")
    dst_index = dest.get("index")
    if not src_index or not dst_index:
        raise IllegalArgumentException("reindex requires source.index and dest.index")
    batch_size = int(source.get("size", DEFAULT_BATCH))
    max_docs = body.get("max_docs") or body.get("size")
    op_type = dest.get("op_type", "index")
    pipeline = dest.get("pipeline")
    compiled = _compile_byquery_script(body)
    task = node.tasks.register("indices:data/write/reindex",
                               f"reindex from [{src_index}] to [{dst_index}]")
    created = updated = total = noops = deleted = 0
    failures = []
    try:
        for hits in _scan_batches(node, src_index, source.get("query"), batch_size):
            task.ensure_not_cancelled()
            ops = []
            reached_max = False
            for h in hits:
                if max_docs is not None and total >= int(max_docs):
                    reached_max = True
                    break
                total += 1
                dest_for_doc = dst_index
                doc_action = "create" if op_type == "create" else "index"
                if compiled is not None:
                    op = _apply_byquery_script(compiled, h)
                    if op == "create":
                        # per-doc ctx.op='create' wins over dest.op_type:
                        # existing dest docs become version conflicts
                        # (AbstractAsyncBulkByScrollAction honors the
                        # script-returned op when building the bulk item)
                        doc_action = "create"
                    if op == "none":
                        noops += 1
                        continue
                    if op == "delete":
                        # ctx.op = 'delete' removes the doc from the DEST
                        # index (the reference's reindex delete semantics)
                        try:
                            r = node.delete_doc(dst_index, h["_id"])
                            if r.get("found", True):
                                deleted += 1
                        except Exception:  # noqa: BLE001 — absent in dest
                            pass
                        continue
                    # scripts may rewrite ctx._index for per-doc routing
                    if h["_index"] != src_index:
                        dest_for_doc = h["_index"]
                ops.append((
                    doc_action,
                    {"_index": dest_for_doc, "_id": h["_id"],
                     "pipeline": pipeline},
                    h["_source"],
                ))
            if ops:
                resp = node.bulk(ops)
                for item in resp["items"]:
                    r = next(iter(item.values()))
                    if "error" in r:
                        failures.append(r["error"])
                    elif r.get("result") == "created":
                        created += 1
                    else:
                        updated += 1
            task.status = {"total": total, "created": created,
                           "updated": updated, "noops": noops,
                           "deleted": deleted}
            if reached_max:
                break
    finally:
        node.tasks.unregister(task)
    if dst_index in node.indices:
        node.indices[dst_index].refresh()
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "total": total,
        "created": created,
        "updated": updated,
        "deleted": deleted,
        "batches": -(-total // batch_size) if total else 0,
        "version_conflicts": 0,
        "noops": noops,
        "retries": {"bulk": 0, "search": 0},
        "failures": failures,
    }


def update_by_query(node, index_expr: str, body: Optional[dict]) -> dict:
    """Re-indexes matching docs in place; with a painless ``script`` each
    doc's ctx._source is transformed and ctx.op may turn the update into
    a noop or a delete (UpdateByQueryRequest + buildScriptApplier)."""
    t0 = time.monotonic()
    body = body or {}
    compiled = _compile_byquery_script(body)
    updated = total = noops = deleted = 0
    task = node.tasks.register("indices:data/write/update/byquery",
                               f"update-by-query [{index_expr}]")
    try:
        for hits in _scan_batches(node, index_expr, body.get("query"), DEFAULT_BATCH):
            task.ensure_not_cancelled()
            ops = []
            for h in hits:
                total += 1
                if compiled is not None:
                    op = _apply_byquery_script(compiled, h)
                    if op == "none":
                        noops += 1
                        continue
                    if op == "delete":
                        r = node.delete_doc(h["_index"], h["_id"])
                        if r.get("found", True):
                            deleted += 1
                        continue
                ops.append(("index", {"_index": h["_index"], "_id": h["_id"]},
                            h["_source"]))
            if ops:
                resp = node.bulk(ops)
                updated += sum(1 for i in resp["items"]
                               if "error" not in next(iter(i.values())))
            task.status = {"total": total, "updated": updated,
                           "noops": noops, "deleted": deleted}
    finally:
        node.tasks.unregister(task)
    for name in node.cluster_service.state.resolve_index_names(index_expr):
        node.indices[name].refresh()
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "total": total,
        "updated": updated,
        "deleted": deleted,
        "version_conflicts": 0,
        "noops": noops,
        "failures": [],
    }


def delete_by_query(node, index_expr: str, body: Optional[dict]) -> dict:
    t0 = time.monotonic()
    body = body or {}
    if "query" not in body:
        raise IllegalArgumentException("delete_by_query requires a query in the request body")
    deleted = total = 0
    task = node.tasks.register("indices:data/write/delete/byquery",
                               f"delete-by-query [{index_expr}]")
    try:
        for hits in _scan_batches(node, index_expr, body.get("query"), DEFAULT_BATCH):
            task.ensure_not_cancelled()
            total += len(hits)
            for h in hits:
                r = node.delete_doc(h["_index"], h["_id"])
                if r.get("found"):
                    deleted += 1
            task.status = {"total": total, "deleted": deleted}
    finally:
        node.tasks.unregister(task)
    for name in node.cluster_service.state.resolve_index_names(index_expr):
        node.indices[name].refresh()
    return {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "total": total,
        "deleted": deleted,
        "version_conflicts": 0,
        "noops": 0,
        "failures": [],
    }
