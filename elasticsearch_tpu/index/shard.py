"""IndexShard: one shard's lifecycle, write entry points, search entry.

Role model: ``IndexShard`` (core/.../index/shard/IndexShard.java, 2401 LoC)
— the shard state machine (CREATED → RECOVERING → POST_RECOVERY → STARTED →
CLOSED), primary-term fencing for writes, searcher acquisition, and
refresh/flush scheduling. The TPU build keeps the same state names; the
"searcher" is the ShardSearcher over sealed segments.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.index.engine import Engine, VersionEntry
from elasticsearch_tpu.index.store import Store
from elasticsearch_tpu.index.translog import Translog, TranslogOp
from elasticsearch_tpu.search.service import ShardSearcher

import logging

_indexing_slow_logger = logging.getLogger(
    "elasticsearch_tpu.index.indexing.slowlog")


class ShardState:
    CREATED = "CREATED"
    RECOVERING = "RECOVERING"
    POST_RECOVERY = "POST_RECOVERY"
    STARTED = "STARTED"
    CLOSED = "CLOSED"


class ShardNotPrimaryException(IllegalArgumentException):
    """The copy is not (any longer) the primary for the operation."""


class OperationPermits:
    """IndexShardOperationPermits analog (reference
    index/shard/IndexShardOperationPermits.java, acquired at
    IndexShard.java:2089): counted operation permits with a blocking
    drain. Writers hold a permit across the engine op; a primary-term
    bump or relocation handoff calls ``block_and_drain`` — new
    acquisitions park, in-flight ones finish — and runs its critical
    section against a quiesced shard."""

    def __init__(self):
        self._cond = threading.Condition()
        self._active = 0
        self._blocked = False
        # reentrancy: a thread already holding a permit (e.g. the
        # replication layer wrapping shard.index_doc, which acquires its
        # own) must not park behind a drain it would itself block
        self._local = threading.local()

    @property
    def active(self) -> int:
        return self._active

    @contextmanager
    def acquire(self, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        depth = getattr(self._local, "depth", 0)
        with self._cond:
            while self._blocked and depth == 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise IllegalArgumentException(
                        "timed out waiting for operation permit "
                        "(shard is draining)")
                self._cond.wait(remaining)
            self._active += 1
        self._local.depth = depth + 1
        try:
            yield
        finally:
            self._local.depth = depth
            with self._cond:
                self._active -= 1
                self._cond.notify_all()

    @contextmanager
    def block_and_drain(self, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._blocked:  # one drain at a time
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise IllegalArgumentException(
                        "timed out waiting for a concurrent drain")
                self._cond.wait(remaining)
            self._blocked = True
            try:
                while self._active > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise IllegalArgumentException(
                            "timed out draining in-flight operations")
                    self._cond.wait(remaining)
            except BaseException:
                self._blocked = False
                self._cond.notify_all()
                raise
        try:
            yield
        finally:
            with self._cond:
                self._blocked = False
                self._cond.notify_all()


class IndexShard:
    def __init__(self, index_name: str, shard_id: int, mapper_service,
                 data_path: Optional[str] = None, primary: bool = True,
                 durability: str = Translog.DURABILITY_REQUEST,
                 slowlog_warn_s=None, slowlog_info_s=None, index_sort=None,
                 indexing_slowlog_warn_s=None, indexing_slowlog_info_s=None,
                 indexing_slowlog_source_chars: int = 1000):
        self.index_name = index_name
        self.shard_id = shard_id
        self.mapper_service = mapper_service
        self.primary = primary
        self.primary_term = 1
        self.state = ShardState.CREATED
        # operation permits: writers hold one across the engine op;
        # promotion/handoff drains (IndexShardOperationPermits)
        self.permits = OperationPermits()
        # primary-side GlobalCheckpointTracker (set by the replication
        # layer when replicas exist; None = single copy)
        self.checkpoints = None
        # indexing slow log (IndexingSlowLog.java); negative = disabled
        self.indexing_slowlog_warn_s = (
            indexing_slowlog_warn_s if indexing_slowlog_warn_s is not None
            and indexing_slowlog_warn_s >= 0 else None)
        self.indexing_slowlog_info_s = (
            indexing_slowlog_info_s if indexing_slowlog_info_s is not None
            and indexing_slowlog_info_s >= 0 else None)
        self.indexing_slowlog_source_chars = indexing_slowlog_source_chars
        if data_path:
            os.makedirs(data_path, exist_ok=True)
            translog = Translog(os.path.join(data_path, "translog"), durability)
            store = Store(os.path.join(data_path, "index"))
        else:
            import tempfile

            self._tmp = tempfile.TemporaryDirectory(prefix="estpu-shard-")
            translog = Translog(os.path.join(self._tmp.name, "translog"), durability)
            store = Store(os.path.join(self._tmp.name, "index"))
        self.engine = Engine(
            f"{index_name}[{shard_id}]", mapper_service, translog, store,
            segment_prefix=f"{index_name}_{shard_id}_seg",
            index_sort=index_sort, index_name=index_name,
        )
        self.searcher = ShardSearcher(
            shard_id, self.engine, mapper_service,
            slowlog_warn_s=slowlog_warn_s, slowlog_info_s=slowlog_info_s,
            index_name=index_name,
        )
        # corruption quarantine flag (ISSUE 16): set when the copy's
        # store carries a corrupted_* marker so the query path fails the
        # shard (PR-4 partial contract) without an os.listdir per query;
        # cleared only by a successful re-recovery installing verified
        # bytes (IndexService._quarantine_shard / multinode heal path)
        self.store_corrupted = False
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    # Recovery (store + translog replay; §3.5 / §5.4 of SURVEY.md)
    # ------------------------------------------------------------------

    def recover_from_store(self) -> None:
        # a _cat/recovery "store" row is recorded only for a cold boot:
        # peer recovery re-enters this method to install shipped files
        # (already STARTED), and that recovery owns its own "peer" row
        boot = self.state == ShardState.CREATED
        self.state = ShardState.RECOVERING
        segments = self.engine.store.load_segments()
        self.engine.segments = segments
        # advance the segment-name counter past every recovered name: a
        # fresh engine restarts at 0, and a later seal reusing an existing
        # name would make store.commit() skip writing the new segment and
        # clobber the old one's live mask — silent data loss on the next
        # flush (bites both restart recovery and peer file recovery)
        for seg in segments:
            tail = seg.name.rsplit("_", 1)[-1]
            if tail.isdigit():
                self.engine._segment_counter = max(
                    self.engine._segment_counter, int(tail))
        # the in-progress buffer was named with the stale counter at
        # engine construction; rename it clear of the recovered names
        if self.engine.buffer.num_docs == 0:
            self.engine.buffer = self.engine._new_builder()
        commit = self.engine.store.read_commit() or {}
        doc_terms = commit.get("doc_terms", {})
        max_seq = -1
        for seg in segments:
            for local, doc_id in enumerate(seg.doc_ids):
                if seg.live[local]:
                    self.engine.version_map[doc_id] = VersionEntry(
                        int(seg.versions[local]), int(seg.seqnos[local]),
                        seg.name, local, term=doc_terms.get(doc_id, 1),
                    )
            if seg.num_docs:
                max_seq = max(max_seq, int(seg.seqnos.max()))
        # re-adopt persisted delete tombstones: without them a stale op
        # replayed by recovery could resurrect a deleted doc
        import time as _time

        for doc_id, t in commit.get("tombstones", {}).items():
            self.engine.version_map[doc_id] = VersionEntry(
                t["version"], t["seq_no"], None, -1, deleted=True,
                ts=_time.monotonic(), term=t.get("term", 1),
            )
            max_seq = max(max_seq, t["seq_no"])
        if max_seq >= 0:
            self.engine.note_external_seqno(max_seq)
        # re-adopt the synced-flush marker (ISSUE 14): its presence plus
        # a zero-op translog replay is the ops-free warm-restart proof
        self.engine.last_sync_id = commit.get("sync_id")
        replayed = self.engine.recover_from_translog()
        if boot:
            # _cat/recovery row for the store recovery (RecoveryState
            # type "store"): a drained shutdown's synced flush makes
            # `replayed` ZERO — the ops-free warm-restart contract
            # (docs/RESILIENCE.md "Rollout & drain"; lazy import:
            # multinode imports this module)
            from elasticsearch_tpu.cluster.multinode import (
                record_recovery_progress,
            )

            import time as _time

            now_ms = int(_time.time() * 1000)
            record_recovery_progress(
                self.index_name, self.shard_id,
                f"store[{self.shard_id}]",
                source=None, type="store", stage="done",
                start_ms=now_ms, stop_ms=now_ms,
                files_total=len(segments), files_recovered=len(segments),
                bytes_total=0, bytes_recovered=0,
                ops_total=replayed, ops_recovered=replayed)
        self.state = ShardState.POST_RECOVERY
        self.state = ShardState.STARTED

    def start_fresh(self) -> None:
        self.state = ShardState.STARTED

    # ------------------------------------------------------------------
    # Write ops (primary-term fenced in the clustered path)
    # ------------------------------------------------------------------

    @contextmanager
    def acquire_primary_permit(self, op_term: Optional[int] = None,
                               timeout: float = 30.0):
        """Primary-term-fenced operation permit
        (IndexShard.acquirePrimaryOperationPermit, IndexShard.java:2089).
        ``op_term``: the term the coordinator routed the op under — an
        op carrying a term OLDER than this copy's current term raced a
        promotion and must be rejected (the new primary may have
        re-assigned its seqno); None means a local single-node op that
        trivially runs under the current term.

        The permit is acquired FIRST and primary/term are validated under
        it: validating before acquiring leaves a stale-write window — a
        promotion or relocation handoff can drain and flip primary/term
        while this op is parked waiting for the permit, and the
        pre-validated op would then land under the new term. The permit
        is released automatically when validation raises."""
        with self.permits.acquire(timeout=timeout):
            if not self.primary:
                raise ShardNotPrimaryException(
                    f"shard [{self.index_name}][{self.shard_id}] is not a "
                    f"primary")
            if op_term is not None and op_term < self.primary_term:
                raise ShardNotPrimaryException(
                    f"operation primary term [{op_term}] is too old "
                    f"(current [{self.primary_term}])")
            yield

    def promote_to_primary(self, new_term: int) -> None:
        """Replica promotion: drain in-flight ops, then adopt the
        master-assigned term so everything after the barrier is fenced
        by it (primaryTerm bump under blockOperations in the
        reference)."""
        with self.permits.block_and_drain():
            self.primary = True
            self.primary_term = max(self.primary_term, new_term)

    @contextmanager
    def relocation_handoff(self):
        """Primary relocation handoff: quiesce the shard, run the
        handoff critical section, then reject further primary ops here
        (IndexShard.relocated + the drain inside blockOperations)."""
        with self.permits.block_and_drain():
            yield
            self.primary = False

    def index_doc(self, doc_id: str, source: dict, routing: Optional[str] = None,
                  version: Optional[int] = None, version_type: str = "internal",
                  op_type: str = "index", seqno: Optional[int] = None,
                  parent: Optional[str] = None) -> dict:
        self._ensure_started()
        t0 = time.monotonic()
        with self.permits.acquire():
            r = self.engine.index(doc_id, source, routing, version,
                                  version_type, op_type, seqno,
                                  primary_term=self.primary_term,
                                  parent=parent)
        self._maybe_indexing_slowlog(time.monotonic() - t0, doc_id, source)
        r["_index"] = self.index_name
        r["_shard"] = self.shard_id
        r["_primary_term"] = self.primary_term
        return r

    def _maybe_indexing_slowlog(self, took_s: float, doc_id: str,
                                source: dict) -> None:
        """Indexing slow log (index/IndexingSlowLog.java): per-index
        warn/info thresholds, source truncated to
        index.indexing.slowlog.source chars."""
        warn = self.indexing_slowlog_warn_s
        info = self.indexing_slowlog_info_s
        level = None
        if warn is not None and took_s >= warn:
            level = _indexing_slow_logger.warning
        elif info is not None and took_s >= info:
            level = _indexing_slow_logger.info
        if level is not None:
            level("took[%dms], shard[[%s][%s]], id[%s], source[%s]",
                  int(took_s * 1000), self.index_name, self.shard_id,
                  doc_id, str(source)[: self.indexing_slowlog_source_chars])

    def delete_doc(self, doc_id: str, version: Optional[int] = None,
                   seqno: Optional[int] = None,
                   version_type: str = "internal") -> dict:
        self._ensure_started()
        with self.permits.acquire():
            r = self.engine.delete(doc_id, version, seqno,
                                   primary_term=self.primary_term,
                                   version_type=version_type)
        r["_index"] = self.index_name
        r["_primary_term"] = self.primary_term
        return r

    def get_doc(self, doc_id: str, realtime: bool = True):
        self._ensure_started()
        return self.engine.get(doc_id, realtime=realtime)

    def refresh(self) -> bool:
        return self.engine.refresh()

    def flush(self) -> None:
        self.engine.flush()

    def synced_flush(self) -> str:
        """Drain-path flush + synced-flush marker (docs/RESILIENCE.md
        "Rollout & drain"): after it, restart recovery over this data
        path replays zero translog ops."""
        return self.engine.synced_flush()

    def force_merge(self, stage_reason: str = "refresh") -> None:
        self.engine.force_merge(stage_reason=stage_reason)

    def _ensure_started(self) -> None:
        if self.state not in (ShardState.STARTED, ShardState.POST_RECOVERY):
            raise IllegalArgumentException(
                f"shard [{self.index_name}][{self.shard_id}] is not started "
                f"(state: {self.state})"
            )

    # ------------------------------------------------------------------

    @property
    def num_docs(self) -> int:
        return self.engine.num_docs

    def seq_no_stats(self) -> dict:
        """max_seq_no / local_checkpoint / global_checkpoint
        (SeqNoStats in the reference). A single-copy primary's global
        checkpoint IS its local checkpoint; with replication the primary's
        GlobalCheckpointTracker (``self.checkpoints``) owns it."""
        tracker = self.checkpoints
        if tracker is not None:
            gcp = tracker.global_checkpoint
        elif self.primary:
            gcp = self.engine.local_checkpoint
        else:
            gcp = self.engine.global_checkpoint
        return {
            "max_seq_no": self.engine.max_seqno,
            "local_checkpoint": self.engine.local_checkpoint,
            "global_checkpoint": gcp,
        }

    def stats(self) -> dict:
        s = self.engine.stats()
        s["search"] = {
            "query_total": self.searcher.query_total,
            "query_time_in_millis": int(self.searcher.query_time * 1000),
            "fetch_total": self.searcher.fetch_total,
            # which scoring engine served each segment query (execution-
            # plane observability; index-level stats add mesh vs host)
            "planes": {
                "pallas_segments_total": self.searcher.pallas_segments_total,
                "scatter_segments_total": self.searcher.scatter_segments_total,
            },
        }
        if self.searcher.group_stats:
            s["search"]["groups"] = {
                g: dict(v) for g, v in self.searcher.group_stats.items()}
        s["routing"] = {
            "state": self.state,
            "primary": self.primary,
        }
        s["seq_no"] = self.seq_no_stats()
        import base64 as _b64

        # Lucene commit identity (SegmentInfos.getId analog): stable per
        # (shard, committed generation)
        gen = s.get("translog", {}).get("generation", 0)
        cid = _b64.b64encode(
            f"{self.index_name}/{self.shard_id}/{gen}".encode()).decode()
        s["commit"] = {
            "id": cid,
            "generation": gen,
            "user_data": {},
            "num_docs": s.get("docs", {}).get("count", 0),
        }
        return s

    def close(self) -> None:
        self.state = ShardState.CLOSED
        self.engine.close()
