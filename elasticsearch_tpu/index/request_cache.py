"""Shard-level search request cache.

Role model: ``IndicesRequestCache``
(core/src/main/java/org/elasticsearch/indices/IndicesRequestCache.java:64)
— the reference caches the shard-level query result of size==0 (agg/count)
requests, keyed by the reader identity + request bytes, invalidated when
the reader changes (refresh with new segments, deletes, merges).

Here the cached unit is the index-level reduced response (this engine
reduces aggregations from segment views in-process, so the shard/index
boundary collapses) and the "reader identity" is a visibility epoch per
shard: the sealed-segment name set plus the delete counter. An empty
refresh (no new docs, no deletes) keeps the epoch — and the cache —
valid, exactly like an unchanged IndexReader.

Entries are LRU-evicted by an approximate byte budget
(indices.requests.cache.size analog).
"""

from __future__ import annotations

import json
import sys
import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple


def _approx_bytes(obj: Any) -> int:
    """Cheap recursive size estimate for a JSON-like response tree."""
    size = sys.getsizeof(obj)
    if isinstance(obj, dict):
        for k, v in obj.items():
            size += _approx_bytes(k) + _approx_bytes(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            size += _approx_bytes(v)
    return size


class RequestCache:
    """LRU response cache with hit/miss/eviction stats."""

    def __init__(self, max_bytes: int = 8 * 1024 * 1024):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[str, Tuple[dict, int]]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def key_for(body: dict, epochs) -> Optional[str]:
        """Canonical cache key, or None when the request isn't cacheable
        as JSON (e.g. non-serializable values from an internal caller —
        no default= fallback: stringified object reprs would make
        never-matching or, worse, colliding keys)."""
        try:
            return json.dumps({"body": body, "epochs": epochs},
                              sort_keys=True)
        except (TypeError, ValueError):
            return None

    def get(self, key: str) -> Optional[dict]:
        """Returns a deep copy of the cached response (callers mutate
        responses — e.g. patching `took`)."""
        import copy

        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            value = hit[0]
        return copy.deepcopy(value)

    def put(self, key: str, value: dict) -> None:
        """Stores a deep copy (taken only after the size check passes, so
        oversized responses cost no copy)."""
        import copy

        size = _approx_bytes(value)
        if size > self.max_bytes:
            return  # a single oversized response never enters the cache
        value = copy.deepcopy(value)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, size)
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= evicted
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "memory_size_in_bytes": self._bytes,
                "entries": len(self._entries),
                "evictions": self.evictions,
                "hit_count": self.hits,
                "miss_count": self.misses,
            }


def cacheable(body: dict) -> bool:
    """The reference's default policy (IndicesRequestCache + the
    canCache checks in IndicesService.canCache): only hit-less requests
    (size == 0 — aggs/counts), never profiled or scrolled searches,
    never search_after/scroll cursors."""
    if body.get("profile"):
        return False
    if body.get("scroll") or body.get("search_after"):
        return False
    size = body.get("size", 10)
    try:
        return int(size) == 0
    except (TypeError, ValueError):
        return False


def shard_epoch(shard) -> tuple:
    """Visibility epoch of one shard: sealed-segment identity + write
    counters. Segment names change on every refresh-with-new-docs /
    merge; the delete counter covers explicit tombstones, and the
    indexing counter covers in-place updates (re-indexing an existing id
    kills the old copy's live-mask slot immediately, before any refresh,
    so writes must invalidate even though the buffered new doc isn't
    searchable yet)."""
    eng = shard.engine
    # visibility_epoch moves on delete-only refreshes, whose segment
    # names and write counters are unchanged (buffered NRT deletes)
    return (tuple(s.name for s in eng.searchable_segments()),
            eng.indexing_total, eng.delete_total,
            eng.visibility_epoch)
