"""The per-shard write engine: buffer + segments + version map + translog.

Role model: ``InternalEngine`` (core/.../index/engine/InternalEngine.java —
index:597, delete:1148 area, refresh:1148, flush:1272) with Lucene's
IndexWriter replaced by the block-packing ``SegmentBuilder``:

- ``index()``: version-check against the live version map
  (LiveVersionMap), assign seqno (SequenceNumbersService), buffer the doc,
  append to the translog.
- ``refresh()``: seal the buffer into an immutable Segment — the NRT
  reader swap. Searches only see sealed segments (same visibility rule as
  the reference).
- ``flush()``: refresh + ask the store to persist a commit point, then trim
  the translog (CombinedDeletionPolicy).
- updates/deletes tombstone the old doc in whichever segment holds it.
- realtime GET reads unrefreshed docs straight from the buffer (the
  reference serves these from the translog, index/get/ShardGetService.java:77).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common.errors import VersionConflictEngineException
from elasticsearch_tpu.index.segment import Segment, SegmentBuilder
from elasticsearch_tpu.index.translog import Translog, TranslogOp


@dataclass
class VersionEntry:
    version: int
    seqno: int
    # where the doc lives: segment name, or None while still in the buffer
    segment: Optional[str]
    local_doc: int
    deleted: bool = False
    # tombstone creation time, for gc_deletes pruning (deletes only)
    ts: float = 0.0
    # primary term of the op that produced this entry — equal-seqno ties
    # in the staleness guard break by term (reference:
    # InternalEngine.compareOpToLuceneDocBasedOnSeqNo)
    term: int = 1


@dataclass
class GetResult:
    found: bool
    doc_id: str
    source: Optional[dict] = None
    version: int = -1
    seqno: int = -1
    routing: Optional[str] = None


class Engine:
    def __init__(self, shard_id, mapper_service, translog: Translog,
                 store=None, segment_prefix: str = "seg", index_sort=None,
                 index_name: Optional[str] = None):
        self.shard_id = shard_id
        # the owning index's name: the device-memory accountant's top
        # hierarchy level — stamped onto every segment before staging
        # (see searchable_segments). The split fallback parses the
        # "index[sid]" shard_id render for direct constructions (tests)
        # that don't pass the name explicitly
        self.index_name = (index_name if index_name is not None
                           else str(shard_id).split("[", 1)[0])
        self.mapper_service = mapper_service
        self.translog = translog
        self.store = store  # index.store.Store or None (transient shard)
        self._segment_prefix = segment_prefix
        self._segment_counter = 0
        # index.sort.* spec — every sealed segment is doc-permuted by it
        self.index_sort = index_sort
        self.segments: List[Segment] = []
        self.buffer = self._new_builder()
        self._buffer_deletes: set = set()
        # deletes against SEALED segments buffered until the next refresh
        # (NRT visibility — see _tombstone): (segment_name, local_doc)
        self._pending_seg_deletes: List[tuple] = []
        self._buffer_routings: Dict[int, Optional[str]] = {}
        self.version_map: Dict[str, VersionEntry] = {}
        self._seqno = -1  # last assigned
        self._local_checkpoint = -1
        # global checkpoint: on replicas, learned from the primary
        # (piggybacked on replication ops); on a primary the shard's
        # GlobalCheckpointTracker is the source of truth
        self.global_checkpoint = -1
        # tombstone retention (reference: index.gc_deletes, default 60s —
        # InternalEngine.maybePruneDeletes); pruned entries below the
        # global checkpoint can no longer be needed by recovery deltas
        # except in the reference's own documented late-op window
        self.gc_deletes = 60.0
        self._last_tombstone_prune = 0.0
        self._lock = threading.RLock()
        self.refresh_count = 0
        # bumps only when a refresh CHANGED visibility (sealed new docs
        # or applied buffered deletes) — the request-cache epoch
        # component for delete-only refreshes, whose segment names and
        # write counters are otherwise unchanged
        self.visibility_epoch = 0
        self.flush_count = 0
        # last stamped synced-flush marker (graceful drain stamps one at
        # shutdown; recover_from_store re-adopts it from the commit)
        self.last_sync_id: Optional[str] = None
        self.indexing_total = 0
        self.delete_total = 0
        self.indexing_time = 0.0
        self._refresh_listeners: List = []

    # ------------------------------------------------------------------

    def _new_builder(self) -> SegmentBuilder:
        self._segment_counter += 1
        return SegmentBuilder(f"{self._segment_prefix}_{self._segment_counter}",
                              index_sort=self.index_sort)

    def _next_seqno(self) -> int:
        self._seqno += 1
        self._local_checkpoint = self._seqno  # single-writer: contiguous
        return self._seqno

    @property
    def local_checkpoint(self) -> int:
        return self._local_checkpoint

    @property
    def max_seqno(self) -> int:
        return self._seqno

    def note_external_seqno(self, seqno: int) -> None:
        """Replica path: ops carry the primary's seqno."""
        self._seqno = max(self._seqno, seqno)
        self._local_checkpoint = self._seqno

    # ------------------------------------------------------------------
    # Write path
    # ------------------------------------------------------------------

    def index(self, doc_id: str, source: dict, routing: Optional[str] = None,
              version: Optional[int] = None, version_type: str = "internal",
              op_type: str = "index", seqno: Optional[int] = None,
              add_to_translog: bool = True,
              replicated_version: Optional[int] = None,
              primary_term: int = 1,
              parent: Optional[str] = None) -> dict:
        """Index one document (create or update). Returns the result dict
        {_id, _version, _seq_no, result: created|updated}.

        ``replicated_version``: replica/recovery path — the op carries the
        version the primary assigned; no conflict check, the version is
        taken as-is (requires an explicit ``seqno``)."""
        t0 = time.monotonic()
        with self._lock:
            existing = self.version_map.get(doc_id)
            if (seqno is not None and existing is not None
                    and (existing.seqno > seqno
                         or (existing.seqno == seqno
                             and existing.term >= primary_term))):
                # stale replica/recovery op: a newer op for this doc was
                # already applied (reference: InternalEngine
                # compareOpToLuceneDocBasedOnSeqNo) — equal seqnos break
                # by primary term (a new primary may reuse seqnos above
                # the old primary's checkpoint) — idempotent skip
                self.note_external_seqno(seqno)
                return {
                    "_id": doc_id,
                    "_version": existing.version,
                    "_seq_no": seqno,
                    "result": "noop",
                }
            current_version = (
                existing.version if existing and not existing.deleted else 0
            )
            if op_type == "create" and existing is not None and not existing.deleted:
                raise VersionConflictEngineException(doc_id, current_version, 0)
            if version is not None and version_type == "internal":
                if current_version != version:
                    raise VersionConflictEngineException(doc_id, current_version, version)
            elif version is not None and version_type == "external":
                # VersionType.EXTERNAL: strictly greater, equality conflicts
                if version <= current_version:
                    raise VersionConflictEngineException(
                        doc_id, current_version, version)
            elif version is not None and version_type == "external_gte":
                if version < current_version:
                    raise VersionConflictEngineException(
                        doc_id, current_version, version)
            if replicated_version is not None:
                new_version = replicated_version
            else:
                new_version = (
                    version if version is not None
                    and version_type in ("external", "external_gte")
                    else current_version + 1
                )
            if seqno is None:
                seqno = self._next_seqno()
            else:
                self.note_external_seqno(seqno)

            parsed = self.mapper_service.parse_document(doc_id, source, routing)
            # tombstone any previous copy of this id
            created = existing is None or existing.deleted
            if existing is not None and not existing.deleted:
                self._tombstone(existing)
            local_doc = self.buffer.add_document(parsed, seqno, new_version,
                                                 parent=parent)
            self._buffer_routings[local_doc] = routing
            self.version_map[doc_id] = VersionEntry(
                new_version, seqno, None, local_doc, term=primary_term
            )
            if add_to_translog:
                self.translog.add(TranslogOp(
                    TranslogOp.INDEX, seqno, doc_id, source, routing,
                    new_version, primary_term, parent=parent
                ))
            # any write voids the synced-flush marker (reference: a
            # sync_id is only valid while the commit covers every op)
            self.last_sync_id = None
            self.indexing_total += 1
            self.indexing_time += time.monotonic() - t0
            return {
                "_id": doc_id,
                "_version": new_version,
                "_seq_no": seqno,
                "result": "created" if created else "updated",
            }

    def delete(self, doc_id: str, version: Optional[int] = None,
               seqno: Optional[int] = None, add_to_translog: bool = True,
               replicated_version: Optional[int] = None,
               primary_term: int = 1,
               version_type: str = "internal") -> dict:
        with self._lock:
            existing = self.version_map.get(doc_id)
            if (seqno is not None and existing is not None
                    and (existing.seqno > seqno
                         or (existing.seqno == seqno
                             and existing.term >= primary_term))):
                # stale replica/recovery op — idempotent skip (see index())
                self.note_external_seqno(seqno)
                return {
                    "_id": doc_id,
                    "_version": existing.version,
                    "_seq_no": seqno,
                    "result": "noop",
                    "found": not existing.deleted,
                }
            found = existing is not None and not existing.deleted
            current_version = existing.version if found else 0
            external_delete = False
            if version is not None:
                if version_type == "external":
                    # VersionType.EXTERNAL.isVersionConflictForWrites:
                    # conflict unless the provided version is STRICTLY
                    # greater (equality conflicts; only external_gte
                    # accepts it)
                    if version <= current_version:
                        raise VersionConflictEngineException(
                            doc_id, current_version, version)
                    external_delete = True
                elif version_type == "external_gte":
                    if version < current_version:
                        raise VersionConflictEngineException(
                            doc_id, current_version, version)
                    external_delete = True
                elif current_version != version:
                    raise VersionConflictEngineException(
                        doc_id, current_version, version)
            if seqno is None:
                seqno = self._next_seqno()
            else:
                self.note_external_seqno(seqno)
            if replicated_version is not None:
                new_version = replicated_version
            elif external_delete:
                new_version = version
            else:
                new_version = current_version + 1
            if found:
                self._tombstone(existing)
                self.version_map[doc_id] = VersionEntry(
                    new_version, seqno, existing.segment, existing.local_doc,
                    deleted=True, ts=time.monotonic(), term=primary_term
                )
            else:
                # record the tombstone even when the doc isn't present:
                # the seqno staleness guard needs it to reject an older
                # index op that arrives after this delete (out-of-order
                # replica delivery / recovery-delta vs fan-out race)
                self.version_map[doc_id] = VersionEntry(
                    new_version, seqno, None, -1, deleted=True,
                    ts=time.monotonic(), term=primary_term
                )
            if add_to_translog:
                self.translog.add(TranslogOp(
                    TranslogOp.DELETE, seqno, doc_id, version=new_version,
                    primary_term=primary_term
                ))
            self.last_sync_id = None  # a delete voids the marker too
            self.delete_total += 1
            return {
                "_id": doc_id,
                "_version": new_version,
                "_seq_no": seqno,
                "result": "deleted" if found else "not_found",
                "found": found,
            }

    def _tombstone(self, entry: VersionEntry) -> None:
        if entry.segment is None:
            self._buffer_deletes.add(entry.local_doc)
        else:
            # NRT semantics: a delete against a sealed segment becomes
            # SEARCH-visible only at the next refresh (Lucene applies
            # buffered deletes on reader reopen); realtime GET sees it
            # immediately through the version map tombstone
            self._pending_seg_deletes.append(
                (entry.segment, entry.local_doc))

    # ------------------------------------------------------------------
    # Read path
    # ------------------------------------------------------------------

    def get(self, doc_id: str, realtime: bool = True) -> GetResult:
        """Realtime get: buffer (unrefreshed) or sealed segment. With
        realtime=False, only search-visible (sealed) docs are returned —
        the reference reads the last refreshed reader
        (ShardGetService realtime=false)."""
        with self._lock:
            entry = self.version_map.get(doc_id)
            if entry is None or entry.deleted:
                return GetResult(False, doc_id)
            if entry.segment is None:
                if not realtime:
                    return GetResult(False, doc_id)
                return GetResult(
                    True, doc_id,
                    source=self.buffer.sources[entry.local_doc],
                    version=entry.version, seqno=entry.seqno,
                    routing=self._buffer_routings.get(entry.local_doc),
                )
            for seg in self.segments:
                if seg.name == entry.segment:
                    return GetResult(
                        True, doc_id, source=seg.sources[entry.local_doc],
                        version=entry.version, seqno=entry.seqno,
                        routing=seg.routings[entry.local_doc],
                    )
            return GetResult(False, doc_id)

    def _stamp_owner(self, seg: Segment) -> None:
        if seg.owner_index != self.index_name:
            seg.owner_index = self.index_name
            for nctx in seg.nested.values():
                self._stamp_owner(nctx.segment)

    def searchable_segments(self) -> List[Segment]:
        with self._lock:
            segs = [s for s in self.segments
                    if s.live_doc_count > 0 or s.num_docs == 0]
            codec = getattr(self, "postings_codec", None)
            for s in segs:
                # the device-memory accountant attributes stagings to
                # the owning index; stamp before any lazy staging runs
                self._stamp_owner(s)
                if codec is not None and \
                        getattr(s, "postings_codec", None) != codec:
                    # index-setting preference for the kernel staging
                    # (index.search.pallas.postings_codec); consulted
                    # once at the segment's lazy device staging, so a
                    # changed setting applies to segments staged AFTER
                    # the change (docs/PRUNING.md)
                    s.postings_codec = codec
            return segs

    @property
    def num_docs(self) -> int:
        """Live, searchable doc count (excludes unrefreshed buffer)."""
        return sum(s.live_doc_count for s in self.segments)

    @property
    def buffered_docs(self) -> int:
        return self.buffer.num_docs - len(self._buffer_deletes)

    # ------------------------------------------------------------------
    # Refresh / flush / merge
    # ------------------------------------------------------------------

    def _prune_tombstones(self) -> None:
        """Drop delete tombstones that are old (gc_deletes) AND globally
        durable (seqno <= global checkpoint) — reference:
        InternalEngine.maybePruneDeletes. Bounds version_map memory and
        recovery-stream size for long-lived indices."""
        now = time.monotonic()
        # throttle the full-map scan off the hot NRT path (reference
        # prunes at most every gcDeletes/4)
        if now - self._last_tombstone_prune < self.gc_deletes / 4:
            return
        self._last_tombstone_prune = now
        horizon = now - self.gc_deletes
        gcp = self.global_checkpoint
        stale = [doc_id for doc_id, e in self.version_map.items()
                 if e.deleted and e.ts <= horizon and e.seqno <= gcp]
        for doc_id in stale:
            del self.version_map[doc_id]

    def refresh(self) -> bool:
        """Seal the buffer into a searchable segment + apply buffered
        sealed-segment deletes (NRT reader swap)."""
        with self._lock:
            self.refresh_count += 1
            self._prune_tombstones()
            applied_deletes = bool(self._pending_seg_deletes)
            if applied_deletes:
                by_seg: Dict[str, list] = {}
                for seg_name, local in self._pending_seg_deletes:
                    by_seg.setdefault(seg_name, []).append(local)
                for seg in self.segments:
                    locals_ = by_seg.get(seg.name)
                    if locals_:
                        seg.delete_docs(np.asarray(locals_, dtype=np.int64))
                self._pending_seg_deletes = []
            if self.buffer.num_docs == 0:
                if applied_deletes:
                    self.visibility_epoch += 1
                    for listener in self._refresh_listeners:
                        listener()
                    self._refresh_listeners = []
                return applied_deletes
            seg = self.buffer.seal()
            # index sorting permutes docs at seal; pre-seal local ids held
            # by the version map / buffered deletes must translate
            remap = self.buffer.seal_doc_remap
            for local_doc in self._buffer_deletes:
                seg.delete_doc(int(remap[local_doc]) if remap is not None
                               else local_doc)
            for doc_id, entry in self.version_map.items():
                # local_doc < 0: tombstone for a doc that was never in the
                # buffer (not-found delete) — nothing to re-home
                if entry.segment is None and entry.local_doc >= 0:
                    entry.segment = seg.name
                    if remap is not None:
                        entry.local_doc = int(remap[entry.local_doc])
            self.segments.append(seg)
            self.buffer = self._new_builder()
            self._buffer_deletes = set()
            self._buffer_routings = {}
            self.visibility_epoch += 1
            for listener in self._refresh_listeners:
                listener()
            self._refresh_listeners = []
            return True

    def add_refresh_listener(self, listener) -> None:
        """wait_for refresh support (RefreshListeners in the reference).
        Fires immediately only when NOTHING is pending visibility —
        buffered docs AND buffered sealed-segment deletes both wait."""
        with self._lock:
            if self.buffer.num_docs == 0 and not self._pending_seg_deletes:
                listener()
            else:
                self._refresh_listeners.append(listener)

    def flush(self, sync_id: Optional[str] = None) -> None:
        """Refresh + durable commit + translog trim (InternalEngine.flush).
        ``sync_id``: stamp a synced-flush marker into the commit (ISSUE
        14 graceful drain — the reference's _flush/synced sync_id)."""
        with self._lock:
            self.refresh()
            if self.store is not None:
                self.store.commit(self.segments, self.max_seqno,
                                  self.version_map, sync_id=sync_id)
            self.translog.mark_committed(self.max_seqno)
            self.translog.roll_generation()
            self.flush_count += 1
            if sync_id is not None:
                self.last_sync_id = sync_id

    def synced_flush(self) -> str:
        """Flush + stamp a fresh synced-flush marker (SyncedFlushService
        analog for the drained-shutdown path): after this, the commit
        provably covers every acked op — a warm restart over the same
        data path replays ZERO translog ops (`_cat/recovery` ops-free
        contract, docs/RESILIENCE.md "Rollout & drain")."""
        import uuid as _uuid

        sync_id = _uuid.uuid4().hex
        self.flush(sync_id=sync_id)
        return sync_id

    def force_merge(self, stage_reason: str = "refresh") -> None:
        """Rewrite all segments into one (expunges deletes). The reference
        merges Lucene segments; we re-index live docs from stored source —
        correct and simple, at rebuild cost (acceptable: force-merge is an
        offline optimization op). ``stage_reason`` classifies the merge
        product's first device staging in the lifecycle ring — "refresh"
        for an operator force-merge, "compaction" when the background
        slot-compaction pass (ISSUE 20) drives the merge."""
        with self._lock:
            self.refresh()
            live_docs = []
            for seg in self.segments:
                seg_parents = getattr(seg, "parents", None) or []
                for local_doc in range(seg.num_docs):
                    if seg.live[local_doc]:
                        live_docs.append((
                            seg.doc_ids[local_doc], seg.sources[local_doc],
                            seg.routings[local_doc],
                            int(seg.seqnos[local_doc]), int(seg.versions[local_doc]),
                            (seg_parents[local_doc]
                             if local_doc < len(seg_parents) else None),
                        ))
            builder = self._new_builder()
            for doc_id, source, routing, seqno, version, parent in live_docs:
                parsed = self.mapper_service.parse_document(doc_id, source, routing)
                local = builder.add_document(parsed, seqno, version,
                                             parent=parent)
                # carry the op's primary term through the rebuild — the
                # equal-seqno staleness tie-break and recovery streams
                # read it from the version map
                old = self.version_map.get(doc_id)
                self.version_map[doc_id] = VersionEntry(
                    version, seqno, builder.name, local,
                    term=old.term if old is not None else 1)
            merged = builder.seal()
            remap = builder.seal_doc_remap
            if remap is not None:
                for entry in self.version_map.values():
                    if entry.segment == builder.name:
                        entry.local_doc = int(remap[entry.local_doc])
            for old_seg in self.segments:
                old_seg.release_breaker_charges()
                # segment retirement: give its staged device bytes back
                # to the ledger (the merged segment restages lazily)
                old_seg.release_device_staging()
            # the merge product re-stages the SAME logical corpus the
            # retired segments held: its first staging is a "refresh"
            # restage in the lifecycle ring, like the mesh plane
            # classifies the same merge (Segment.stage_reason_initial)
            def _mark_restage(seg: Segment) -> None:
                seg.stage_reason_initial = stage_reason
                for nctx in seg.nested.values():
                    _mark_restage(nctx.segment)

            _mark_restage(merged)
            self.segments = [merged] if merged.num_docs else []
            self._stamp_owner(merged)

    def recover_from_translog(self) -> int:
        """Replay uncommitted translog ops (engine open after crash)."""
        ops = self.translog.uncommitted_ops()
        for op in ops:
            if op.op_type == TranslogOp.INDEX:
                self.index(op.doc_id, op.source, op.routing, seqno=op.seqno,
                           add_to_translog=False,
                           replicated_version=op.version,
                           primary_term=op.primary_term,
                           parent=op.parent)
            elif op.op_type == TranslogOp.DELETE:
                self.delete(op.doc_id, seqno=op.seqno, add_to_translog=False,
                            replicated_version=op.version,
                            primary_term=op.primary_term)
        if ops:
            self.refresh()
        return len(ops)

    def stats(self) -> dict:
        return {
            "docs": {"count": self.num_docs, "buffered": self.buffered_docs},
            "indexing": {
                "index_total": self.indexing_total,
                "index_time_in_millis": int(self.indexing_time * 1000),
                "delete_total": self.delete_total,
            },
            "refresh": {"total": self.refresh_count},
            "flush": {"total": self.flush_count},
            "segments": {
                "count": len(self.segments),
                "memory_in_bytes": sum(s.memory_bytes() for s in self.segments),
            },
            "translog": self.translog.stats(),
            "seq_no": {
                "max_seq_no": self.max_seqno,
                "local_checkpoint": self.local_checkpoint,
            },
        }

    def close(self) -> None:
        for seg in self.segments:
            seg.release_breaker_charges()
            seg.release_device_staging()
        self.translog.close()
