"""Mapping (schema) service and document parsing.

Role model: ``MapperService`` (core/.../index/mapper/MapperService.java:274
merge), ``DocumentParser`` (index/mapper/DocumentParser.java:56) and
``DynamicTemplate``. A mapping is a tree of properties; parsing a JSON doc
produces (a) inverted-index terms per field, (b) doc values per field, and
(c) possibly a dynamic mapping update (new fields seen). Metadata fields
(_id, _source, _routing, _seq_no, _field_names) are synthesized.
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
)
from elasticsearch_tpu.mapper.field_types import (
    FieldType,
    GeoPointFieldType,
    TextFieldType,
    create_field_type,
)

_ISO_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([T ]\d{2}:\d{2}(:\d{2}(\.\d+)?)?(Z|[+-]\d{2}:?\d{2})?)?$")


@dataclass
class ParsedDocument:
    """Output of parsing one JSON document."""

    doc_id: str
    source: dict
    routing: Optional[str]
    # field name -> list of index terms (inverted index input)
    terms: Dict[str, List[str]] = field(default_factory=dict)
    # field name -> list of numeric doc values (float) — multi-valued allowed
    numeric_values: Dict[str, List[float]] = field(default_factory=dict)
    # field name -> list of string doc values (ordinal columns)
    string_values: Dict[str, List[str]] = field(default_factory=dict)
    # geo points: field -> list[(lat, lon)]
    geo_values: Dict[str, List[Tuple[float, float]]] = field(default_factory=dict)
    # geo shapes: field -> list of raw GeoJSON dicts / WKT strings
    shape_values: Dict[str, List[Any]] = field(default_factory=dict)
    # range fields: field -> list[(lo, hi)] inclusive float bounds
    range_values: Dict[str, List[Tuple[float, float]]] = field(default_factory=dict)
    # dense vectors: field -> ONE [dims] float list per doc (the mapper
    # rejects multiple vectors per field per document, like the
    # reference's DenseVectorFieldMapper)
    vector_values: Dict[str, List[float]] = field(default_factory=dict)
    # fields present (for exists query — the reference's _field_names field)
    field_names: List[str] = field(default_factory=list)
    # dynamic mapping update produced while parsing, or None
    mapping_update: Optional[dict] = None
    # nested path -> sub-documents (one per nested object, in source order).
    # The reference indexes these as separate Lucene docs in the same block
    # (DocumentParser nested handling); here they become rows of a per-path
    # nested sub-segment joined to the parent by an explicit pointer column.
    nested: Dict[str, List["ParsedDocument"]] = field(default_factory=dict)


class DocumentMapper:
    """A compiled mapping for one index: flat field-path -> FieldType."""

    def __init__(self, mapping: dict, analyzers: AnalysisRegistry,
                 total_fields_limit: int = 1000,
                 dense_vector_max_dims: int = 1024):
        self.mapping = mapping  # the raw {"properties": {...}} tree
        self.analyzers = analyzers
        self.total_fields_limit = total_fields_limit
        # index.mapping.dense_vector.max_dims — validated at mapping
        # compile so an oversized field rejects at put-mapping time
        self.dense_vector_max_dims = dense_vector_max_dims
        self.fields: Dict[str, FieldType] = {}
        self._object_paths: set = set()
        # nested object paths ("type": "nested") -> their mapping params
        self.nested_paths: Dict[str, dict] = {}
        # _size metadata field (plugins/mapper-size SizeFieldMapper):
        # {"_size": {"enabled": true}} indexes the source's byte size as a
        # queryable/aggregatable/sortable numeric field
        self.size_enabled = bool((mapping.get("_size") or {}).get("enabled"))
        if self.size_enabled:
            from elasticsearch_tpu.mapper.field_types import LongFieldType

            self.fields["_size"] = LongFieldType("_size", {})
        self._compile("", mapping.get("properties", {}))
        if len(self.fields) > total_fields_limit:
            raise IllegalArgumentException(
                f"Limit of total fields [{total_fields_limit}] in index has been exceeded"
            )

    def _compile(self, prefix: str, properties: dict) -> None:
        for name, params in properties.items():
            path = f"{prefix}{name}"
            if params.get("type") == "nested":
                self._object_paths.add(path)
                self.nested_paths[path] = params
                self._compile(path + ".", params.get("properties", {}))
                continue
            if "properties" in params and "type" not in params:
                self._object_paths.add(path)
                self._compile(path + ".", params["properties"])
                continue
            ft = create_field_type(path, params)
            self._check_vector_dims(ft)
            self.fields[path] = ft
            for sub_name, sub_params in (params.get("fields") or {}).items():
                sub_path = f"{path}.{sub_name}"
                if (sub_params or {}).get("type") == "dense_vector":
                    # multi-field value fan-out splits arrays into
                    # elements, which can never carry a whole vector —
                    # reject at compile instead of silently indexing
                    # nothing (and bypassing the max_dims bound)
                    raise MapperParsingException(
                        f"Field [{sub_path}]: [dense_vector] cannot be "
                        f"used in multi-fields")
                self.fields[sub_path] = create_field_type(sub_path, sub_params)

    def _check_vector_dims(self, ft: FieldType) -> None:
        from elasticsearch_tpu.mapper.field_types import DenseVectorFieldType

        if (isinstance(ft, DenseVectorFieldType)
                and ft.dims > self.dense_vector_max_dims):
            raise IllegalArgumentException(
                f"The number of dimensions for field [{ft.name}] "
                f"[{ft.dims}] exceeds "
                f"[index.mapping.dense_vector.max_dims] "
                f"[{self.dense_vector_max_dims}]")

    def field_type(self, path: str) -> Optional[FieldType]:
        return self.fields.get(path)

    def simple_match_to_fields(self, pattern: str) -> List[str]:
        """Expand a field pattern ('*', 'user.*') to concrete field names."""
        if "*" not in pattern:
            return [pattern] if pattern in self.fields else []
        rx = re.compile("^" + re.escape(pattern).replace(r"\*", ".*") + "$")
        return sorted(f for f in self.fields if rx.match(f))

    # ------------------------------------------------------------------
    # Document parsing
    # ------------------------------------------------------------------

    def parse(self, doc_id: str, source: dict, routing: Optional[str] = None,
              dynamic: str = "true") -> ParsedDocument:
        out = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        new_props: dict = {}
        self._parse_object("", source, out, self.mapping.get("properties", {}),
                           new_props, dynamic)
        if new_props:
            out.mapping_update = {"properties": new_props}
        if self.size_enabled:
            import json as _json

            out.numeric_values["_size"] = [float(len(
                _json.dumps(source, separators=(",", ":"), default=str)))]
        out.field_names = sorted(
            set(out.terms) | set(out.numeric_values) | set(out.string_values)
            | set(out.geo_values) | set(out.range_values)
            | set(out.shape_values) | set(out.vector_values)
        )
        return out

    def _parse_object(self, prefix: str, obj: dict, out: ParsedDocument,
                      props: dict, new_props: dict, dynamic: str) -> None:
        if not isinstance(obj, dict):
            raise MapperParsingException(
                f"object mapping for [{prefix.rstrip('.')}] tried to parse field as "
                "object, but found a concrete value"
            )
        for key, value in obj.items():
            path = f"{prefix}{key}"
            if value is None:
                self._index_null(path, out)
                continue
            if path in self.nested_paths:
                self._parse_nested(path, key, value, out, props, new_props, dynamic)
                continue
            ft = self.fields.get(path)
            if ft is None and path in self._object_paths and not isinstance(value, dict):
                raise MapperParsingException(
                    f"object mapping for [{path}] tried to parse field [{key}] as "
                    "object, but found a concrete value"
                )
            if ft is None and path in self._object_paths and isinstance(value, dict):
                sub = props.get(key, {}).get("properties", {})
                sub_new = new_props.setdefault(key, {"properties": {}})["properties"] \
                    if dynamic == "true" else {}
                self._parse_object(path + ".", value, out, sub, sub_new, dynamic)
                if dynamic == "true" and not sub_new:
                    new_props.pop(key, None)
                continue
            if ft is None:
                if isinstance(value, dict):
                    # new object
                    if dynamic == "strict":
                        raise MapperParsingException(
                            f"mapping set to strict, dynamic introduction of [{key}] "
                            f"within [{prefix.rstrip('.') or '_doc'}] is not allowed"
                        )
                    if dynamic == "false":
                        continue
                    sub_new = new_props.setdefault(key, {"properties": {}})["properties"]
                    self._object_paths.add(path)
                    self._parse_object(path + ".", value, out, {}, sub_new, dynamic)
                    continue
                if dynamic == "strict":
                    raise MapperParsingException(
                        f"mapping set to strict, dynamic introduction of [{key}] "
                        f"within [{prefix.rstrip('.') or '_doc'}] is not allowed"
                    )
                if dynamic == "false":
                    continue
                sample = value[0] if isinstance(value, list) and value else value
                if sample is None:
                    continue
                params = self._dynamic_type_for(sample)
                ft = create_field_type(path, params)
                self.fields[path] = ft
                if len(self.fields) > self.total_fields_limit:
                    raise IllegalArgumentException(
                        f"Limit of total fields [{self.total_fields_limit}] in index "
                        "has been exceeded"
                    )
                new_props[key] = params
                if params.get("type") == "text":
                    kw_path = f"{path}.keyword"
                    self.fields[kw_path] = create_field_type(
                        kw_path, {"type": "keyword", "ignore_above": 256}
                    )
            self._index_value(ft, value, out)

    def _parse_nested(self, path: str, key: str, value: Any, out: ParsedDocument,
                      props: dict, new_props: dict, dynamic: str) -> None:
        """Each object under a nested path becomes its own sub-document
        (the reference's block-join child docs, DocumentParser nested
        handling); fields are keyed by full path within the sub-doc."""
        objs = value if isinstance(value, list) else [value]
        sub_props = props.get(key, {}).get("properties", {})
        params_n = self.nested_paths[path]
        sub_new = (
            new_props.setdefault(key, {"type": "nested", "properties": {}})["properties"]
            if dynamic == "true" else {}
        )
        for obj in objs:
            if obj is None:
                continue  # the reference skips null array elements
            if not isinstance(obj, dict):
                raise MapperParsingException(
                    f"object mapping for [{path}] tried to parse field [{key}] as "
                    "object, but found a concrete value"
                )
            sub = ParsedDocument(doc_id=out.doc_id, source=obj, routing=None)
            self._parse_object(path + ".", obj, sub, sub_props, sub_new, dynamic)
            sub.field_names = sorted(
                set(sub.terms) | set(sub.numeric_values) | set(sub.string_values)
                | set(sub.geo_values) | set(sub.range_values)
                | set(sub.shape_values) | set(sub.vector_values)
            )
            out.nested.setdefault(path, []).append(sub)
            if params_n.get("include_in_parent") or params_n.get("include_in_root"):
                # copy the object's flat fields onto the enclosing doc —
                # but NOT its inner nested docs, which `sub` already
                # carries (they would double-index otherwise)
                inc = ParsedDocument(doc_id=out.doc_id, source=obj, routing=None)
                self._parse_object(path + ".", obj, inc, sub_props,
                                   sub_new if dynamic == "true" else {}, dynamic)
                for store in ("terms", "numeric_values", "string_values",
                              "geo_values", "range_values", "shape_values"):
                    for f, vals in getattr(inc, store).items():
                        getattr(out, store).setdefault(f, []).extend(vals)
                for f, vec in inc.vector_values.items():
                    # one vector per field per (parent) doc — two nested
                    # objects carrying the same dense_vector path cannot
                    # both flatten onto the root
                    if f in out.vector_values:
                        raise MapperParsingException(
                            f"Field [{f}] of type [dense_vector] doesn't "
                            f"support indexing multiple values for the "
                            f"same field in one document")
                    out.vector_values[f] = vec
        if dynamic == "true" and not sub_new:
            new_props.pop(key, None)

    def _dynamic_type_for(self, sample: Any) -> dict:
        """Dynamic mapping rules (DocumentParser.createBuilderFromFieldType)."""
        if isinstance(sample, bool):
            return {"type": "boolean"}
        if isinstance(sample, int):
            return {"type": "long"}
        if isinstance(sample, float):
            return {"type": "float"}
        if isinstance(sample, str):
            if _ISO_DATE_RE.match(sample):
                return {"type": "date"}
            return {
                "type": "text",
                "fields": {"keyword": {"type": "keyword", "ignore_above": 256}},
            }
        if isinstance(sample, dict):
            return {"properties": {}}
        raise MapperParsingException(f"cannot infer mapping for value [{sample!r}]")

    def _index_null(self, path: str, out: ParsedDocument) -> None:
        ft = self.fields.get(path)
        if ft is not None and ft.null_value is not None:
            self._index_value(ft, ft.null_value, out)

    def _index_value(self, ft: FieldType, value: Any, out: ParsedDocument) -> None:
        from elasticsearch_tpu.mapper.field_types import DenseVectorFieldType

        if isinstance(ft, DenseVectorFieldType):
            # the WHOLE array is one value — it must not be split into
            # elements like a multi-valued field; one vector per doc
            if ft.name in out.vector_values:
                raise MapperParsingException(
                    f"Field [{ft.name}] of type [dense_vector] doesn't "
                    f"support indexing multiple values for the same "
                    f"field in one document")
            out.vector_values[ft.name] = ft.parse_vector(value)
            return
        values = value if isinstance(value, list) else [value]
        for v in values:
            if v is None:
                if ft.null_value is not None:
                    v = ft.null_value
                else:
                    continue
            self._index_single(ft, v, out)
        # multi-fields (e.g. text + .keyword) get the same values
        for sub_name in (ft.params.get("fields") or {}):
            sub_ft = self.fields.get(f"{ft.name}.{sub_name}")
            if sub_ft is not None:
                for v in values:
                    if v is not None:
                        self._index_single(sub_ft, v, out)

    def _index_single(self, ft: FieldType, v: Any, out: ParsedDocument) -> None:
        if isinstance(ft, GeoPointFieldType):
            out.geo_values.setdefault(ft.name, []).append(ft.parse_point(v))
            return
        from elasticsearch_tpu.mapper.field_types import GeoShapeFieldType

        if isinstance(ft, GeoShapeFieldType):
            out.shape_values.setdefault(ft.name, []).append(
                ft.parse_shape_value(v))
            return
        from elasticsearch_tpu.mapper.field_types import (
            CompletionFieldType,
            JoinFieldType,
            RangeFieldType,
            TokenCountFieldType,
        )

        if isinstance(ft, JoinFieldType):
            name, parent = ft.parse_join(v)
            out.terms.setdefault(ft.name, []).append(name)
            out.string_values.setdefault(ft.name, []).append(name)
            if parent is not None:
                out.string_values.setdefault(f"{ft.name}#parent", []).append(parent)
            return
        if isinstance(ft, RangeFieldType):
            out.range_values.setdefault(ft.name, []).append(ft.parse_range(v))
            return
        if isinstance(ft, TokenCountFieldType):
            out.numeric_values.setdefault(ft.name, []).append(
                ft.count_tokens(v, self.analyzers)
            )
            return
        if isinstance(ft, CompletionFieldType):
            inputs, weight, ctxs = ft.parse_completion(v)
            out.string_values.setdefault(ft.name, []).extend(inputs)
            out.numeric_values.setdefault(f"{ft.name}#weight", []).append(weight)
            for cname, cvals in ctxs.items():
                out.string_values.setdefault(
                    f"{ft.name}#ctx.{cname}", []).extend(cvals)
            return
        if ft.index:
            terms = ft.index_terms(v, self.analyzers)
            if terms:
                out.terms.setdefault(ft.name, []).extend(terms)
        if ft.doc_values:
            dv = ft.doc_value(v)
            if dv is None:
                pass
            elif isinstance(dv, str):
                out.string_values.setdefault(ft.name, []).append(dv)
            else:
                out.numeric_values.setdefault(ft.name, []).append(float(dv))
        elif isinstance(ft, TextFieldType) and ft.fielddata:
            # text fielddata: terms double as string "values" for aggs
            for t in ft.index_terms(v, self.analyzers):
                out.string_values.setdefault(ft.name, []).append(t)

    def to_mapping_dict(self) -> dict:
        return copy.deepcopy(self.mapping)


class MapperService:
    """Per-index mapping holder with merge semantics.

    Role model: MapperService.merge (index/mapper/MapperService.java:274):
    merging an incompatible type change fails; new fields extend the tree.
    """

    def __init__(self, analyzers: AnalysisRegistry, mapping: Optional[dict] = None,
                 total_fields_limit: int = 1000, similarity_service=None,
                 dense_vector_max_dims: int = 1024):
        self.analyzers = analyzers
        self.total_fields_limit = total_fields_limit
        self.dense_vector_max_dims = dense_vector_max_dims
        if similarity_service is None:
            from elasticsearch_tpu.index.similarity import SimilarityService
            similarity_service = SimilarityService()
        self.similarity_service = similarity_service
        self._mapping = copy.deepcopy(mapping) if mapping else {"properties": {}}
        self._mapper = DocumentMapper(self._mapping, analyzers, total_fields_limit,
                                      dense_vector_max_dims)
        self._validate_similarities()

    def _validate_similarities(self) -> None:
        """Reject unknown similarity names at mapping time, like the
        reference (MapperService resolves them via SimilarityService when
        building the field type, failing the mapping update)."""
        for name, ft in self._mapper.fields.items():
            sim_name = getattr(ft, "similarity_name", None)
            if sim_name is not None:
                self.similarity_service.get(sim_name)  # raises on unknown

    @property
    def mapper(self) -> DocumentMapper:
        return self._mapper

    @property
    def dynamic(self) -> str:
        return str(self._mapping.get("dynamic", "true")).lower()

    @property
    def parent_type(self) -> Optional[str]:
        """Legacy ``_parent`` metadata field (ParentFieldMapper): its
        presence makes routing REQUIRED on single-doc ops, with the
        ``parent`` param acting as the routing value."""
        p = self._mapping.get("_parent") or {}
        return p.get("type")

    def mapping_dict(self) -> dict:
        return copy.deepcopy(self._mapping)

    def field_type(self, path: str) -> Optional[FieldType]:
        return self._mapper.field_type(path)

    def merge(self, new_mapping: dict) -> None:
        merged = copy.deepcopy(self._mapping)
        self._merge_props(
            merged.setdefault("properties", {}),
            copy.deepcopy(new_mapping.get("properties", {})),
            "",
        )
        for meta_key in ("dynamic", "_source", "_routing", "date_detection",
                         "_size"):
            if meta_key in new_mapping:
                merged[meta_key] = new_mapping[meta_key]
        # recompile validates the merged tree
        self._mapper = DocumentMapper(merged, self.analyzers, self.total_fields_limit,
                                      self.dense_vector_max_dims)
        self._mapping = merged
        self._validate_similarities()

    def _merge_props(self, base: dict, incoming: dict, prefix: str) -> None:
        for name, params in incoming.items():
            path = f"{prefix}{name}"
            if name not in base:
                base[name] = params
                continue
            existing = base[name]
            existing_type = existing.get("type", "object" if "properties" in existing else None)
            incoming_type = params.get("type", "object" if "properties" in params else None)
            if existing_type != incoming_type:
                raise IllegalArgumentException(
                    f"mapper [{path}] of different type, current_type [{existing_type}], "
                    f"merged_type [{incoming_type}]"
                )
            if "properties" in params:
                self._merge_props(
                    existing.setdefault("properties", {}), params["properties"], path + "."
                )
            else:
                for k, v in params.items():
                    if k in ("type", "properties"):
                        continue
                    if k == "fields":
                        existing.setdefault("fields", {}).update(v)
                    else:
                        existing[k] = v

    def parse_document(self, doc_id: str, source: dict,
                       routing: Optional[str] = None) -> ParsedDocument:
        parsed = self._mapper.parse(doc_id, source, routing, dynamic=self.dynamic)
        if parsed.mapping_update:
            # apply the dynamic update to the authoritative mapping (in the
            # clustered path this is the master round-trip; single-node: local)
            self.merge(parsed.mapping_update)
        return parsed
