"""Field types: JSON value -> indexable terms + columnar doc values.

Role model: ``MappedFieldType`` and the concrete mappers
(core/.../index/mapper/TextFieldMapper.java, KeywordFieldMapper.java,
NumberFieldMapper.java, DateFieldMapper.java, BooleanFieldMapper.java,
IpFieldMapper.java, ScaledFloatFieldMapper.java). Each type decides how a
field value is (a) analyzed into inverted-index terms and (b) encoded into
a columnar doc value for sorting/aggregations.

TPU adaptation: doc values are *always* numeric float64/int64 columns
(keywords become ordinals at segment seal), so every aggregation/sort is a
dense vector op. Range queries on numerics run against the column, not a
BKD tree.
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import math
from typing import Any, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
)

NUMERIC_TYPES = {
    "long", "integer", "short", "byte", "double", "float", "half_float",
    "scaled_float",
}

_INT_RANGES = {
    "long": (-(2**63), 2**63 - 1),
    "integer": (-(2**31), 2**31 - 1),
    "short": (-(2**15), 2**15 - 1),
    "byte": (-(2**7), 2**7 - 1),
}


def parse_date(value: Any, formats: Optional[List[str]] = None) -> int:
    """Parse a date value to epoch milliseconds (UTC).

    Reference behavior: DateFieldMapper with default format
    ``strict_date_optional_time||epoch_millis``.
    """
    if isinstance(value, bool):
        raise MapperParsingException(f"failed to parse date field [{value}]")
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if formats:
        for fmt in formats:
            if fmt == "epoch_millis":
                try:
                    return int(s)
                except ValueError:
                    continue
            if fmt == "epoch_second":
                try:
                    return int(s) * 1000
                except ValueError:
                    continue
            try:
                dt = _dt.datetime.strptime(s, _java_to_strptime(fmt))
                return _to_millis(dt)
            except ValueError:
                continue
        raise MapperParsingException(
            f"failed to parse date field [{s}] with format [{'||'.join(formats)}]"
        )
    # default: ISO-8601 (strict_date_optional_time) or epoch_millis
    try:
        return int(s)
    except ValueError:
        pass
    try:
        iso = s.replace("Z", "+00:00")
        if len(iso) == 10:  # yyyy-MM-dd
            dt = _dt.datetime.fromisoformat(iso + "T00:00:00+00:00")
        else:
            dt = _dt.datetime.fromisoformat(iso)
        return _to_millis(dt)
    except ValueError:
        raise MapperParsingException(f"failed to parse date field [{s}]") from None


def _to_millis(dt: _dt.datetime) -> int:
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


_JAVA_FMT = {
    "yyyy": "%Y", "MM": "%m", "dd": "%d", "HH": "%H", "mm": "%M", "ss": "%S",
}


def _java_to_strptime(fmt: str) -> str:
    out = fmt
    for j, p in _JAVA_FMT.items():
        out = out.replace(j, p)
    return out


def format_epoch_millis(millis: int) -> str:
    dt = _dt.datetime.fromtimestamp(millis / 1000.0, tz=_dt.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def parse_ip(value: Any) -> int:
    """Encode an IP as an integer (IPv4-mapped into IPv6 space, like Lucene's
    16-byte encoding; we keep a python int, stored as the doc value)."""
    try:
        addr = ipaddress.ip_address(str(value))
    except ValueError:
        raise MapperParsingException(f"'{value}' is not an IP string literal.") from None
    if isinstance(addr, ipaddress.IPv4Address):
        addr = ipaddress.IPv6Address(f"::ffff:{addr}")
    return int(addr)


def format_ip(value: int) -> str:
    addr = ipaddress.IPv6Address(int(value))
    v4 = addr.ipv4_mapped
    return str(v4) if v4 is not None else str(addr)


class FieldType:
    """Base field type. Subclasses override value handling.

    Attributes mirror the mapping parameters the reference accepts for the
    type (index, doc_values, store, boost, analyzer, ...).
    """

    type_name = "object"
    # does this type produce inverted-index terms?
    indexable = True
    # does this type produce a numeric doc-value column?
    has_doc_values = True
    # string-ordinal doc values (keyword-family) vs plain numeric
    ordinal_doc_values = False

    def __init__(self, name: str, params: Optional[dict] = None):
        self.name = name
        self.params = dict(params or {})
        self.index = self.params.get("index", True)
        self.doc_values = self.params.get("doc_values", self.has_doc_values)
        self.boost = float(self.params.get("boost", 1.0))
        self.null_value = self.params.get("null_value")

    # --- index-time ---

    def index_terms(self, value: Any, analyzers) -> List[str]:
        """Terms for the inverted index (already analyzed)."""
        raise NotImplementedError

    def doc_value(self, value: Any):
        """Columnar value: float for numerics/dates/bools, str for ordinals."""
        raise NotImplementedError

    # --- query-time ---

    def term_for_query(self, value: Any, analyzers) -> str:
        """Normalize a user-provided term the way index_terms would."""
        return str(value)

    def numeric_for_query(self, value: Any) -> float:
        raise IllegalArgumentException(
            f"Field [{self.name}] of type [{self.type_name}] does not support numeric queries"
        )

    def to_mapping(self) -> dict:
        out = {"type": self.type_name}
        out.update({k: v for k, v in self.params.items() if k != "type"})
        return out


class TextFieldType(FieldType):
    type_name = "text"
    has_doc_values = False  # like ES: text has no doc_values (fielddata opt-in)

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.analyzer = self.params.get("analyzer", "standard")
        self.search_analyzer = self.params.get("search_analyzer", self.analyzer)
        self.fielddata = bool(self.params.get("fielddata", False))
        # per-field similarity name (index/similarity/SimilarityService.java)
        self.similarity_name = self.params.get("similarity")

    def index_terms(self, value, analyzers):
        return analyzers.get(self.analyzer).analyze(str(value))

    def doc_value(self, value):
        return None

    def term_for_query(self, value, analyzers):
        toks = analyzers.get(self.search_analyzer).analyze(str(value))
        return toks[0] if toks else ""

    def query_terms(self, value, analyzers):
        return analyzers.get(self.search_analyzer).analyze(str(value))


class KeywordFieldType(FieldType):
    type_name = "keyword"
    ordinal_doc_values = True

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.ignore_above = int(self.params.get("ignore_above", 2**31 - 1))
        self.normalizer = self.params.get("normalizer")

    def _normalize(self, s: str) -> str:
        if self.normalizer == "lowercase":
            return s.lower()
        return s

    def index_terms(self, value, analyzers):
        s = str(value)
        if len(s) > self.ignore_above:
            return []
        return [self._normalize(s)]

    def doc_value(self, value):
        s = str(value)
        if len(s) > self.ignore_above:
            return None
        return self._normalize(s)

    def term_for_query(self, value, analyzers):
        return self._normalize(str(value))


class NumberFieldType(FieldType):
    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.coerce = bool(self.params.get("coerce", True))

    def _parse(self, value):
        if isinstance(value, bool):
            raise MapperParsingException(
                f"failed to parse field [{self.name}] of type [{self.type_name}]: "
                f"booleans are not numbers"
            )
        try:
            if isinstance(value, str) and not self.coerce:
                raise ValueError(value)
            f = float(value)
        except (TypeError, ValueError):
            raise MapperParsingException(
                f"failed to parse field [{self.name}] of type [{self.type_name}] "
                f"value [{value}]"
            ) from None
        if math.isnan(f) or math.isinf(f):
            raise MapperParsingException(
                f"failed to parse field [{self.name}]: non-finite value"
            )
        return f

    def index_terms(self, value, analyzers):
        # numeric "terms" are the doc values themselves; term queries on
        # numerics run against the column (no BKD analog needed).
        return []

    def numeric_for_query(self, value):
        return self._parse(value)


class IntegerLikeFieldType(NumberFieldType):
    def doc_value(self, value):
        f = self._parse(value)
        i = int(f)
        if not self.coerce and f != i:
            raise MapperParsingException(
                f"failed to parse field [{self.name}]: [{value}] has a decimal part"
            )
        lo, hi = _INT_RANGES[self.type_name]
        if not (lo <= i <= hi):
            raise MapperParsingException(
                f"failed to parse field [{self.name}]: value [{value}] is out of "
                f"range for type [{self.type_name}]"
            )
        return float(i)


class LongFieldType(IntegerLikeFieldType):
    type_name = "long"


class IntegerFieldType(IntegerLikeFieldType):
    type_name = "integer"


class ShortFieldType(IntegerLikeFieldType):
    type_name = "short"


class ByteFieldType(IntegerLikeFieldType):
    type_name = "byte"


class DoubleFieldType(NumberFieldType):
    type_name = "double"

    def doc_value(self, value):
        return self._parse(value)


class FloatFieldType(DoubleFieldType):
    type_name = "float"


class HalfFloatFieldType(DoubleFieldType):
    type_name = "half_float"


class ScaledFloatFieldType(NumberFieldType):
    type_name = "scaled_float"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        if "scaling_factor" not in self.params:
            raise MapperParsingException(
                f"Field [{name}] misses required parameter [scaling_factor]"
            )
        self.scaling_factor = float(self.params["scaling_factor"])

    def doc_value(self, value):
        # stored scaled+rounded, like the reference (value*factor rounded to long)
        return float(round(self._parse(value) * self.scaling_factor)) / self.scaling_factor

    def numeric_for_query(self, value):
        return self._parse(value)


class DateFieldType(FieldType):
    type_name = "date"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        fmt = self.params.get("format")
        self.formats = fmt.split("||") if isinstance(fmt, str) else None

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return float(parse_date(value, self.formats))

    def numeric_for_query(self, value):
        return float(parse_date(value, self.formats))


class BooleanFieldType(FieldType):
    type_name = "boolean"

    def _parse(self, value) -> bool:
        if isinstance(value, bool):
            return value
        s = str(value)
        if s == "true":
            return True
        if s == "false":
            return False
        raise MapperParsingException(
            f"Failed to parse value [{value}] as only [true] or [false] are allowed."
        )

    def index_terms(self, value, analyzers):
        return ["T" if self._parse(value) else "F"]

    def doc_value(self, value):
        return 1.0 if self._parse(value) else 0.0

    def term_for_query(self, value, analyzers):
        return "T" if self._parse(value) else "F"

    def numeric_for_query(self, value):
        return 1.0 if self._parse(value) else 0.0


class IpFieldType(FieldType):
    type_name = "ip"
    ordinal_doc_values = True  # store dotted string as ordinal; range via int

    def index_terms(self, value, analyzers):
        return [format_ip(parse_ip(value))]

    def doc_value(self, value):
        return format_ip(parse_ip(value))

    def term_for_query(self, value, analyzers):
        return format_ip(parse_ip(value))


class GeoPointFieldType(FieldType):
    """geo_point: stored as two numeric columns (<name>.lat / <name>.lon)
    managed by the segment writer; distance/bbox filters are vector math."""

    type_name = "geo_point"

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return self.parse_point(value)

    @staticmethod
    def parse_point(value):
        if isinstance(value, dict):
            lat, lon = value.get("lat"), value.get("lon")
        elif isinstance(value, (list, tuple)) and len(value) == 2:
            lon, lat = value  # GeoJSON order [lon, lat]
        elif isinstance(value, str):
            parts = value.split(",")
            if len(parts) != 2:
                raise MapperParsingException(f"failed to parse geo_point [{value}]")
            lat, lon = float(parts[0]), float(parts[1])
        else:
            raise MapperParsingException(f"failed to parse geo_point [{value}]")
        lat, lon = float(lat), float(lon)
        if not (-90.0 <= lat <= 90.0) or not (-180.0 <= lon <= 180.0):
            raise MapperParsingException(
                f"illegal latitude/longitude value [{lat}, {lon}]"
            )
        return (lat, lon)


class RangeFieldType(FieldType):
    """Range family (index/mapper/RangeFieldMapper.java:73 — RangeType enum
    :435): a value is a {gte/gt/lte/lt} pair. Lucene stores these as
    RangeField BKD points; here each value becomes an aligned (lo, hi) pair
    in two parallel CSR numeric columns (`<field>#lo`, `<field>#hi`) so
    intersects/contains/within relations are elementwise comparisons."""

    has_doc_values = True
    # the scalar type used to parse each bound
    value_parser: str = "double"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.coerce = bool(self.params.get("coerce", True))

    def _bound(self, v):
        raise NotImplementedError

    # exclusive-bound adjustment step (1.0 for int-like, ulp for floats)
    def _next_up(self, v: float) -> float:
        return math.nextafter(v, math.inf)

    def _next_down(self, v: float) -> float:
        return math.nextafter(v, -math.inf)

    def parse_range(self, value) -> tuple:
        """-> (lo, hi) inclusive float bounds."""
        if not isinstance(value, dict):
            raise MapperParsingException(
                f"error parsing field [{self.name}], expected an object but got "
                f"[{value!r}]"
            )
        lo, hi = -math.inf, math.inf
        for k, v in value.items():
            if k == "gte":
                lo = self._bound(v)
            elif k == "gt":
                lo = self._next_up(self._bound(v))
            elif k == "lte":
                hi = self._bound(v)
            elif k == "lt":
                hi = self._next_down(self._bound(v))
            else:
                raise MapperParsingException(
                    f"error parsing field [{self.name}], unknown range parameter [{k}]"
                )
        return lo, hi

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None

    def numeric_for_query(self, value):
        return self._bound(value)


class IntegerRangeFieldType(RangeFieldType):
    type_name = "integer_range"

    def _bound(self, v):
        return float(int(float(v)))

    def _next_up(self, v):
        return v + 1.0

    def _next_down(self, v):
        return v - 1.0


class LongRangeFieldType(IntegerRangeFieldType):
    type_name = "long_range"


class FloatRangeFieldType(RangeFieldType):
    type_name = "float_range"

    def _bound(self, v):
        return float(v)


class DoubleRangeFieldType(FloatRangeFieldType):
    type_name = "double_range"


class DateRangeFieldType(RangeFieldType):
    type_name = "date_range"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        fmt = self.params.get("format")
        self.formats = fmt.split("||") if isinstance(fmt, str) else None

    def _bound(self, v):
        return float(parse_date(v, self.formats))

    def _next_up(self, v):  # +1ms, like the reference's DATE range type
        return v + 1.0

    def _next_down(self, v):
        return v - 1.0


class IpRangeFieldType(RangeFieldType):
    type_name = "ip_range"

    def _bound(self, v):
        return float(parse_ip(v))

    # exclusive bounds step by one float64 ulp (the base-class default):
    # a +1 integer step is below ulp at IPv6 magnitudes (~2^128), which
    # would silently turn gt/lt into gte/lte; one ulp correctly excludes
    # the (float64-rounded) stored bound itself.

    def parse_range(self, value):
        # CIDR shorthand: "10.0.0.0/8"
        if isinstance(value, str) and "/" in value:
            net = ipaddress.ip_network(value, strict=False)
            lo = net.network_address
            hi = net.broadcast_address
            if isinstance(lo, ipaddress.IPv4Address):
                lo = ipaddress.IPv6Address(f"::ffff:{lo}")
                hi = ipaddress.IPv6Address(f"::ffff:{hi}")
            return float(int(lo)), float(int(hi))
        return super().parse_range(value)


class TokenCountFieldType(NumberFieldType):
    """token_count (index/mapper/TokenCountFieldMapper): analyzes the text
    and indexes the token count as a numeric doc value. Subclasses the
    numeric family so term/range queries run against the column."""

    type_name = "token_count"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.analyzer = self.params.get("analyzer", "standard")

    def doc_value(self, value):  # replaced by count_tokens at parse time
        return None

    def count_tokens(self, value, analyzers) -> float:
        # counts emitted tokens; the analysis chain does not track position
        # increments, so enable_position_increments is not supported
        return float(len(analyzers.get(self.analyzer).analyze(str(value))))


class BinaryFieldType(FieldType):
    """binary (index/mapper/BinaryFieldMapper): base64 payload, not
    searchable; doc values keep the base64 string (ordinal column)."""

    type_name = "binary"
    indexable = False
    has_doc_values = False  # like the reference: doc_values default false
    ordinal_doc_values = True

    def __init__(self, name, params=None):
        super().__init__(name, params)

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        if not self.doc_values:
            return None
        s = str(value)
        import base64 as _b64

        try:
            _b64.b64decode(s, validate=True)
        except Exception:
            raise MapperParsingException(
                f"failed to parse field [{self.name}]: invalid base64"
            ) from None
        return s


class Murmur3FieldType(NumberFieldType):
    """murmur3 (plugins/mapper-murmur3 — Murmur3FieldMapper): stores the
    murmur3 hash of the value as a numeric doc value, so cardinality aggs
    skip hashing at query time."""

    type_name = "murmur3"

    def doc_value(self, value):
        from elasticsearch_tpu.utils.murmur3 import murmur3_32

        # murmur3_32 already returns a signed Java-int-style value
        return float(murmur3_32(str(value).encode("utf-8")))


class JoinFieldType(FieldType):
    """join (modules/parent-join — ParentJoinFieldMapper): one relation
    field per index declaring parent->child relations. A doc's value is
    either the relation name (parent) or {"name": ..., "parent": id}
    (child). The relation name lands in the field's ordinal column + the
    inverted index; the parent id in a parallel '<field>#parent' ordinal
    column (standing in for Lucene's per-relation join doc-values field).

    Parent/child joins require same-shard colocation: children must be
    indexed with routing = parent id (enforced at the write path)."""

    type_name = "join"
    ordinal_doc_values = True

    def __init__(self, name, params=None):
        super().__init__(name, params)
        rel = self.params.get("relations") or {}
        # parent -> [children]
        self.relations: dict = {
            p: (c if isinstance(c, list) else [c]) for p, c in rel.items()
        }
        self._parent_of = {
            c: p for p, cs in self.relations.items() for c in cs
        }

    def parent_of(self, child_name: str) -> Optional[str]:
        return self._parent_of.get(child_name)

    def is_parent(self, name: str) -> bool:
        return name in self.relations

    def valid_relation(self, name: str) -> bool:
        return name in self.relations or name in self._parent_of

    def parse_join(self, value) -> tuple:
        """-> (relation_name, parent_id or None)."""
        if isinstance(value, str):
            name, parent = value, None
        elif isinstance(value, dict):
            name = value.get("name")
            parent = value.get("parent")
        else:
            raise MapperParsingException(
                f"failed to parse join field [{self.name}] value [{value!r}]"
            )
        if not self.valid_relation(name):
            raise MapperParsingException(
                f"unknown join name [{name}] for field [{self.name}]"
            )
        if name in self._parent_of and parent is None:
            raise MapperParsingException(
                f"[parent] is missing for join field [{self.name}]"
            )
        if name in self.relations and name not in self._parent_of and parent is not None:
            raise MapperParsingException(
                f"[parent] is specified but the join name [{name}] is a parent"
            )
        return str(name), (str(parent) if parent is not None else None)

    def index_terms(self, value, analyzers):
        name, _ = self.parse_join(value)
        return [name]

    def doc_value(self, value):
        return None  # handled specially in DocumentMapper._index_single


class DenseVectorFieldType(FieldType):
    """dense_vector: a fixed-dimension float embedding per document
    (the reference grew this in 7.x — DenseVectorFieldMapper; the 8.x
    ``similarity`` mapping param picks the kNN metric). Values are NOT
    inverted-index terms or scalar doc values: they land in a dedicated
    per-segment ``[nd_pad, dims]`` column stored bf16 on device and
    scored by the MXU kNN kernel (ops/pallas_knn.py). See
    docs/VECTOR.md."""

    type_name = "dense_vector"
    indexable = False
    has_doc_values = False

    SIMILARITIES = ("cosine", "dot_product")

    def __init__(self, name, params=None):
        super().__init__(name, params)
        dims = self.params.get("dims")
        if dims is None:
            raise MapperParsingException(
                f"Field [{name}] of type [dense_vector] misses required "
                f"parameter [dims]")
        try:
            self.dims = int(dims)
        except (TypeError, ValueError):
            raise MapperParsingException(
                f"Field [{name}]: [dims] must be an integer, got "
                f"[{dims!r}]") from None
        if self.dims < 1:
            raise MapperParsingException(
                f"Field [{name}]: [dims] must be a positive integer, got "
                f"[{self.dims}]")
        self.similarity = self.params.get("similarity", "cosine")
        if self.similarity not in self.SIMILARITIES:
            raise MapperParsingException(
                f"Field [{name}]: unknown [similarity] "
                f"[{self.similarity}]; expected one of "
                f"{list(self.SIMILARITIES)}")

    def parse_vector(self, value) -> List[float]:
        """Validate one document's vector: a list of exactly ``dims``
        finite numbers. Anything else is a 400 at index time."""
        if not isinstance(value, (list, tuple)):
            raise MapperParsingException(
                f"failed to parse field [{self.name}] of type "
                f"[dense_vector]: expected an array of {self.dims} "
                f"numbers, got [{value!r}]")
        if len(value) != self.dims:
            raise MapperParsingException(
                f"failed to parse field [{self.name}]: the [dims] of the "
                f"vector [{len(value)}] does not match the mapping "
                f"[{self.dims}]")
        out = []
        for v in value:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise MapperParsingException(
                    f"failed to parse field [{self.name}] of type "
                    f"[dense_vector]: non-numeric element [{v!r}]")
            f = float(v)
            if math.isnan(f) or math.isinf(f):
                raise MapperParsingException(
                    f"failed to parse field [{self.name}]: non-finite "
                    f"vector element")
            out.append(f)
        return out

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None


class PercolatorFieldType(FieldType):
    """percolator: stores a query DSL object for inverse search
    (modules/percolator — PercolatorFieldMapper). The query lives in
    _source; matching is done by the percolate query executing stored
    queries against an in-memory one-doc index (the reference additionally
    pre-filters via extracted terms; round-1 evaluates all stored queries)."""

    type_name = "percolator"
    has_doc_values = False

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None


class CompletionFieldType(FieldType):
    """completion: autocomplete inputs (index/mapper/CompletionFieldMapper;
    Lucene stores an FST — here inputs land in the field's sorted ordinal
    column, weights in a parallel '<field>#weight' numeric column)."""

    type_name = "completion"
    ordinal_doc_values = True

    def __init__(self, name, params=None):
        super().__init__(name, params)
        # context mappings (search/suggest/completion/context/*):
        # [{"name": ..., "type": "category"|"geo", "precision": int}]
        self.contexts = {c["name"]: c for c in self.params.get("contexts", [])}

    def parse_completion(self, value):
        """-> (inputs: [str], weight: float, contexts: {name: [str]}).
        Geo context values encode to geohashes (the reference's
        GeoContextMapping prefix encoding)."""
        if isinstance(value, str):
            return [value], 1.0, {}
        if isinstance(value, list):
            return [str(v) for v in value], 1.0, {}
        if isinstance(value, dict):
            inputs = value.get("input", [])
            inputs = [inputs] if isinstance(inputs, str) else [str(v) for v in inputs]
            ctx_out = {}
            for cname, cvals in (value.get("contexts") or {}).items():
                cdef = self.contexts.get(cname)
                if cdef is None:
                    raise MapperParsingException(
                        f"context [{cname}] is not defined on completion "
                        f"field [{self.name}]")
                if not isinstance(cvals, list):
                    cvals = [cvals]
                if cdef.get("type", "category") == "geo":
                    from elasticsearch_tpu.utils.geohash import encode

                    encoded = []
                    for p in cvals:
                        try:
                            if isinstance(p, dict):
                                encoded.append(
                                    encode(float(p["lat"]), float(p["lon"]), 12))
                            elif isinstance(p, str) and "," in p:
                                lat, lon = p.split(",", 1)
                                encoded.append(
                                    encode(float(lat), float(lon), 12))
                            else:  # raw geohash
                                encoded.append(str(p))
                        except (KeyError, TypeError, ValueError) as e:
                            raise MapperParsingException(
                                f"failed to parse geo context [{cname}] of "
                                f"completion field [{self.name}]: {p!r}"
                            ) from e
                    ctx_out[cname] = encoded
                else:
                    ctx_out[cname] = [str(c) for c in cvals]
            return inputs, float(value.get("weight", 1.0)), ctx_out
        raise MapperParsingException(
            f"failed to parse completion field [{self.name}] value [{value!r}]"
        )

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None


class GeoShapeFieldType(FieldType):
    """geo_shape: GeoJSON/WKT geometries kept host-side per doc with a
    dense bbox table for vectorized prefiltering (reference:
    index/mapper/GeoShapeFieldMapper.java over Lucene spatial prefix
    trees; see utils/geometry.py for the TPU-side design)."""

    type_name = "geo_shape"
    has_doc_values = False

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None

    def parse_shape_value(self, value):
        """Validate at index time; the raw GeoJSON dict / WKT string is
        stored and geometry objects build lazily at query time."""
        from elasticsearch_tpu.utils.geometry import parse_shape

        parse_shape(value)  # raises MapperParsingException on bad input
        return value


FIELD_TYPES = {
    t.type_name: t
    for t in [
        GeoShapeFieldType,
        CompletionFieldType,
        DenseVectorFieldType,
        PercolatorFieldType,
        TextFieldType, KeywordFieldType, LongFieldType, IntegerFieldType,
        ShortFieldType, ByteFieldType, DoubleFieldType, FloatFieldType,
        HalfFloatFieldType, ScaledFloatFieldType, DateFieldType,
        BooleanFieldType, IpFieldType, GeoPointFieldType,
        IntegerRangeFieldType, LongRangeFieldType, FloatRangeFieldType,
        DoubleRangeFieldType, DateRangeFieldType, IpRangeFieldType,
        TokenCountFieldType, BinaryFieldType, Murmur3FieldType,
        JoinFieldType,
    ]
}


def join_field_of(mapper_service) -> Optional["JoinFieldType"]:
    """The index's single join field, if mapped (ParentJoinFieldMapper
    enforces at most one per index)."""
    for ft in mapper_service.mapper.fields.values():
        if isinstance(ft, JoinFieldType):
            return ft
    return None


def create_field_type(name: str, params: dict) -> FieldType:
    typ = params.get("type")
    if typ is None and "properties" in params:
        typ = "object"
    cls = FIELD_TYPES.get(typ)
    if cls is None:
        raise MapperParsingException(
            f"No handler for type [{typ}] declared on field [{name}]"
        )
    return cls(name, params)
