"""Client package: the in-process typed facade (client.base) and the HTTP
client (client.http) with connection pooling, round-robin, failure
marking and sniffing — the RestClient/Transport split of the reference's
low-level REST client (client/rest/.../RestClient.java)."""

from elasticsearch_tpu.client_base import Client  # noqa: F401
from elasticsearch_tpu.client.http import (  # noqa: F401
    AmbiguousWriteError,
    HttpClient,
    NoLiveHostError,
    Response,
    TransportError,
)
