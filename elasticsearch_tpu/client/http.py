"""Low-level HTTP client: round-robin, failure marking, sniffing.

Role model: the reference's low-level REST client
(client/rest/src/main/java/org/elasticsearch/client/RestClient.java) —
host rotation (RestClient.performRequest -> nextHost), dead-host marking
with exponentially growing resurrect timeouts
(RestClient.DeadHostState), retry of idempotent requests on connection
errors, and the sniffer that refreshes the host list from /_nodes
(client/sniffer/.../ElasticsearchNodesSniffer.java).

Pure stdlib (urllib + threads): the client is infrastructure, not the
TPU compute path.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple


class TransportError(Exception):
    """HTTP-level error response (status >= 400)."""

    def __init__(self, status: int, body: Any):
        self.status = status
        self.body = body
        reason = body
        if isinstance(body, dict):
            err = body.get("error")
            reason = err.get("reason") if isinstance(err, dict) else err
        super().__init__(f"[{status}] {reason}")


def _was_never_sent(exc) -> bool:
    """True when the failure guarantees the request never reached a
    server (safe to replay non-idempotent requests)."""
    import errno

    reasons = [exc]
    if isinstance(exc, urllib.error.URLError):
        reasons.append(exc.reason)
    for r in reasons:
        if isinstance(r, ConnectionRefusedError):
            return True
        if isinstance(r, OSError) and r.errno in (errno.ECONNREFUSED,
                                                  errno.EHOSTUNREACH,
                                                  errno.ENETUNREACH):
            return True
    return False


class NoLiveHostError(Exception):
    """Every configured host is marked dead and none could be revived."""


class AmbiguousWriteError(Exception):
    """A non-idempotent request failed after it may have reached the
    server (timeout / connection reset mid-flight). The write may or may
    not have been applied; the client did NOT fail over, because a replay
    could duplicate it. Distinct from NoLiveHostError: the cluster is not
    known to be down — this one host gave an ambiguous answer."""

    def __init__(self, host: str, cause: Exception):
        self.host = host
        super().__init__(
            f"non-idempotent request to {host} failed after it may have "
            f"been sent ({cause!r}); not retried to avoid duplicating "
            f"the write")


class Response:
    __slots__ = ("status", "body", "host")

    def __init__(self, status: int, body: Any, host: str):
        self.status = status
        self.body = body
        self.host = host


class _HostState:
    """DeadHostState: failed hosts sit out with exponential backoff
    (1min base, doubling per consecutive failure, capped at 30min)."""

    __slots__ = ("host", "failures", "dead_until")

    BASE_TIMEOUT = 60.0
    MAX_TIMEOUT = 1800.0

    def __init__(self, host: str):
        self.host = host
        self.failures = 0
        self.dead_until = 0.0

    def mark_dead(self, now: float) -> None:
        self.failures += 1
        timeout = min(self.BASE_TIMEOUT * (2 ** (self.failures - 1)),
                      self.MAX_TIMEOUT)
        self.dead_until = now + timeout

    def mark_alive(self) -> None:
        self.failures = 0
        self.dead_until = 0.0

    def usable(self, now: float) -> bool:
        return now >= self.dead_until


class HttpClient:
    """Round-robin HTTP client over one or more nodes.

    >>> client = HttpClient(["http://127.0.0.1:9200"])
    >>> client.request("GET", "/_cluster/health").body["status"]

    sniff=True refreshes the host list from GET /_nodes/http on a
    background interval (and eagerly after a host failure), so nodes
    joining/leaving the cluster rotate in without reconfiguration.
    """

    def __init__(self, hosts: List[str], timeout: float = 30.0,
                 max_retries: int = 3, sniff: bool = False,
                 sniff_interval: float = 300.0):
        if not hosts:
            raise ValueError("at least one host required")
        self._lock = threading.Lock()
        self._states = [_HostState(h.rstrip("/")) for h in hosts]
        self._rr = 0
        self.timeout = timeout
        self.max_retries = max_retries
        self._sniff_enabled = sniff
        self._sniff_interval = sniff_interval
        self._last_sniff = 0.0
        self._closed = False

    # --- host selection (RestClient.nextHost) ---

    def _next_host(self) -> _HostState:
        now = time.monotonic()
        with self._lock:
            n = len(self._states)
            # prefer live hosts in round-robin order
            for i in range(n):
                st = self._states[(self._rr + i) % n]
                if st.usable(now):
                    self._rr = (self._rr + i + 1) % n
                    return st
            # all dead: revive the one whose timeout expires soonest
            # (DeadHostState comparison — gives it a trial request)
            return min(self._states, key=lambda s: s.dead_until)

    def hosts(self) -> List[str]:
        with self._lock:
            return [s.host for s in self._states]

    def set_hosts(self, hosts: List[str]) -> None:
        with self._lock:
            known = {s.host: s for s in self._states}
            self._states = [known.get(h.rstrip("/"), _HostState(h.rstrip("/")))
                            for h in dict.fromkeys(hosts)]

    # --- sniffing (ElasticsearchNodesSniffer) ---

    def sniff(self) -> List[str]:
        """Refresh hosts from /_nodes/http of any live node."""
        resp = self.request("GET", "/_nodes/http", _sniffing=True)
        found = []
        for info in (resp.body.get("nodes") or {}).values():
            addr = (info.get("http") or {}).get("publish_address")
            if addr:
                found.append(addr if addr.startswith("http")
                             else f"http://{addr}")
        if found:
            self.set_hosts(found)
        self._last_sniff = time.monotonic()
        return self.hosts()

    def _maybe_sniff(self, force: bool = False) -> None:
        if not self._sniff_enabled:
            return
        now = time.monotonic()
        if force or now - self._last_sniff >= self._sniff_interval:
            try:
                self.sniff()
            except Exception:  # noqa: BLE001 — sniffing is best-effort
                self._last_sniff = now

    # --- requests ---

    def request(self, method: str, path: str,
                body: Optional[Any] = None,
                params: Optional[Dict[str, Any]] = None,
                _sniffing: bool = False) -> Response:
        if not _sniffing:
            self._maybe_sniff()
        url_path = path if path.startswith("/") else "/" + path
        if params:
            url_path += "?" + urllib.parse.urlencode(
                {k: str(v) for k, v in params.items()})
        data = None
        headers = {}
        if body is not None:
            data = (body.encode() if isinstance(body, str)
                    else json.dumps(body).encode())
            headers["Content-Type"] = "application/json"
        idempotent = method.upper() in ("GET", "HEAD", "PUT", "DELETE")
        attempts = max(1, self.max_retries)
        last_exc: Optional[Exception] = None
        for _ in range(attempts):
            st = self._next_host()
            req = urllib.request.Request(st.host + url_path, data=data,
                                         method=method, headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    st.mark_alive()
                    return Response(r.status, self._parse(r), st.host)
            except urllib.error.HTTPError as e:
                # the node answered: it is alive; 4xx/5xx do not rotate
                st.mark_alive()
                raw = e.read()
                try:
                    parsed = json.loads(raw)
                except (ValueError, TypeError):
                    parsed = raw.decode("utf-8", "replace")
                raise TransportError(e.code, parsed) from None
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                st.mark_dead(time.monotonic())
                last_exc = e
                if not _sniffing:  # a failing sniff must not re-sniff
                    self._maybe_sniff(force=True)
                if not idempotent and not _was_never_sent(e):
                    # the server may have executed the POST before a
                    # timeout/reset: replaying could duplicate the write.
                    # Connection-refused failures were never delivered, so
                    # those still fail over to the next host.
                    raise AmbiguousWriteError(st.host, e) from e
        raise NoLiveHostError(
            f"no usable host out of {self.hosts()}: {last_exc}")

    @staticmethod
    def _parse(r) -> Any:
        raw = r.read()
        if not raw:
            return None
        ctype = r.headers.get("Content-Type", "")
        if "json" in ctype:
            return json.loads(raw)
        return raw.decode("utf-8", "replace")

    # --- convenience verbs (high-level client surface) ---

    def get(self, path: str, **kw) -> Response:
        return self.request("GET", path, **kw)

    def put(self, path: str, body=None, **kw) -> Response:
        return self.request("PUT", path, body=body, **kw)

    def post(self, path: str, body=None, **kw) -> Response:
        return self.request("POST", path, body=body, **kw)

    def delete(self, path: str, **kw) -> Response:
        return self.request("DELETE", path, **kw)

    # typed helpers mirroring client_base.Client

    def index(self, index: str, doc_id: str, body: dict, **params) -> dict:
        return self.put(f"/{index}/_doc/{doc_id}", body=body,
                        params=params or None).body

    def get_doc(self, index: str, doc_id: str) -> dict:
        return self.get(f"/{index}/_doc/{doc_id}").body

    def search(self, index: str, body: dict) -> dict:
        return self.post(f"/{index}/_search", body=body).body

    def bulk(self, lines: List[dict]) -> dict:
        payload = "\n".join(json.dumps(x) for x in lines) + "\n"
        return self.post("/_bulk", body=payload).body

    def refresh(self, index: str) -> dict:
        return self.post(f"/{index}/_refresh").body
