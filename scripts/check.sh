#!/usr/bin/env bash
# Pre-PR contract gate (ISSUE 15, docs/STATIC_ANALYSIS.md): the AST
# contract lints + lock-discipline analyzer, then the two registry
# lints. Run it from the repo root before every PR:
#
#   scripts/check.sh            # the full gate
#   scripts/check.sh --fast     # contract lints only (skip pytest)
#
# Exits non-zero on the first failing stage. The same checks run in
# tier-1 (tests/test_contract_lint.py, tests/test_settings_registry.py,
# tests/test_observability_registry.py) — this script is the fast local
# loop, not a different gate.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== contract lints (python -m elasticsearch_tpu.testing.lint) =="
python -m elasticsearch_tpu.testing.lint

echo "== integrity ledger balance (quarantine releases staged scope) =="
# The quarantine-release lint pass proves every store_corrupted flip
# releases device staging; this runtime probe proves the accountant's
# ledger actually returns to baseline through that path (ISSUE 16).
python - <<'EOF'
import os
import tempfile

os.environ.setdefault("ES_TPU_PALLAS", "interpret")

from elasticsearch_tpu.common.memory import memory_accountant
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.store import CorruptIndexException

with tempfile.TemporaryDirectory() as d:
    svc = IndexService(
        "ledger_probe",
        Settings({"index.number_of_shards": 1,
                  "index.search.mesh": False}),
        mapping={"properties": {"body": {"type": "text"}}},
        data_path=d)
    try:
        for i in range(8):
            svc.index_doc(str(i), {"body": f"alpha beta {i}"})
        svc.refresh()
        svc.search({"query": {"match": {"body": "alpha"}}})
        acct = memory_accountant()
        before = acct.staged_bytes("ledger_probe")
        assert before > 0, "probe search staged nothing"
        svc._quarantine_shard(
            0, CorruptIndexException("check.sh ledger probe"),
            site="scrub")
        after = acct.staged_bytes("ledger_probe")
        assert after == 0, (before, after)
        assert all(not seg._device
                   for sh in svc.shards.values()
                   for seg in sh.engine.segments)
        print(f"   ledger ok: staged {before} -> {after} bytes")
    finally:
        svc.close()
EOF

echo "== restage amplification (delta staging keeps appends ~1x) =="
# ISSUE 20: a scripted refresh/delete sequence against a mesh index —
# the pure-append window must ride the delta path (amplification of
# restaged over logically-changed bytes <= 1.5, not ~n_slots), and a
# delete must restage only live-mask bytes (tombstone path).
python - <<'EOF'
import os

os.environ.setdefault("ES_TPU_PALLAS", "interpret")

from elasticsearch_tpu.common.memory import memory_accountant
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService

svc = IndexService(
    "amp_probe",
    Settings({"index.number_of_shards": 3,
              "index.search.mesh": True,
              "index.search.mesh.plane": "pallas",
              "index.search.mesh.max_slots_per_device": 16,
              "index.staging.compact.threshold": 0.0,
              "index.refresh_interval": -1}),
    mapping={"properties": {"body": {"type": "text",
                                     "analyzer": "whitespace"}}})
try:
    for i in range(48):
        svc.index_doc(str(i), {"body": f"alpha beta w{i % 7}"})
    svc.refresh()
    q = {"query": {"match": {"body": "alpha"}}, "size": 10}
    svc.search(dict(q))
    acct = memory_accountant()
    base = acct.stats("amp_probe")
    # pure-append window: new docs -> refresh -> search restages
    for i in range(48, 72):
        svc.index_doc(str(i), {"body": f"alpha gamma w{i % 7}"})
    svc.refresh()
    svc.search(dict(q))
    after = acct.stats("amp_probe")
    restaged = (after["restaged_bytes_total"]
                - base["restaged_bytes_total"])
    logical = (after["bytes_logically_changed_total"]
               - base["bytes_logically_changed_total"])
    assert logical > 0, "append window logically changed nothing"
    amp = restaged / logical
    assert amp <= 1.5, f"append amplification {amp:.2f} > 1.5"
    planes = svc.search_stats()["planes"]
    assert planes["delta_restage_total"] >= 1, \
        "append window never rode the delta path"
    # delete window: tombstone restages live-mask bytes only
    n_ev = len(after["staging_events"])
    for i in range(0, 12):
        svc.delete_doc(str(i))
    svc.refresh()
    svc.search(dict(q))
    events = acct.stats("amp_probe")["staging_events"][n_ev:]
    kinds = {e["kind"] for e in events}
    assert kinds and kinds <= {"live_mask", "mesh_slot_tables"}, (
        f"delete restaged non-mask kinds: {sorted(kinds)}")
    assert svc.search_stats()["planes"]["tombstone_update_total"] >= 1
    print(f"   amplification ok: append {amp:.2f}x "
          f"({restaged}/{logical} bytes), delete restaged only "
          f"{sorted(kinds)}")
finally:
    svc.close()
EOF

if [[ "${1:-}" == "--fast" ]]; then
    exit 0
fi

echo "== registry lints =="
python -m pytest -q -p no:cacheprovider \
    tests/test_contract_lint.py \
    tests/test_settings_registry.py \
    tests/test_observability_registry.py

echo "== corruption matrix =="
python -m pytest -q -p no:cacheprovider tests/test_corruption.py
