#!/usr/bin/env bash
# Pre-PR contract gate (ISSUE 15, docs/STATIC_ANALYSIS.md): the AST
# contract lints + lock-discipline analyzer, then the two registry
# lints. Run it from the repo root before every PR:
#
#   scripts/check.sh            # the full gate
#   scripts/check.sh --fast     # contract lints only (skip pytest)
#
# Exits non-zero on the first failing stage. The same checks run in
# tier-1 (tests/test_contract_lint.py, tests/test_settings_registry.py,
# tests/test_observability_registry.py) — this script is the fast local
# loop, not a different gate.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== contract lints (python -m elasticsearch_tpu.testing.lint) =="
python -m elasticsearch_tpu.testing.lint

if [[ "${1:-}" == "--fast" ]]; then
    exit 0
fi

echo "== registry lints =="
python -m pytest -q -p no:cacheprovider \
    tests/test_contract_lint.py \
    tests/test_settings_registry.py \
    tests/test_observability_registry.py
