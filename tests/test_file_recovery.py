"""File-based peer recovery (phase1) tests.

Role model: RecoverySourceHandler.phase1
(core/.../indices/recovery/RecoverySourceHandler.java:165) — the source
flushes a commit and ships its segment files in checksummed chunks; the
target installs them and replays only the ops above the shipped seqno
(phase2), instead of re-indexing the whole history doc-by-doc."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.multinode import (
    ACTION_RECOVER_FILE_CHUNK,
    ACTION_RECOVER_FILES_START,
    ClusterClient,
    ClusterNode,
)
from elasticsearch_tpu.common.errors import ElasticsearchTpuException
from elasticsearch_tpu.transport.local import TransportHub


def one_node_with_docs(n_docs=150, deletes=()):
    hub = TransportHub(strict_serialization=True)
    n1 = ClusterNode("n1", hub)
    n1.bootstrap_cluster()
    n1.create_index(
        "logs", {"index": {"number_of_shards": 1, "number_of_replicas": 1}},
        {"properties": {"msg": {"type": "text"}}})
    client = ClusterClient(n1)
    for i in range(n_docs):
        client.index("logs", str(i), {"msg": f"event {i}"})
    for d in deletes:
        client.delete("logs", str(d))
    return hub, n1, client


def spy_phase2(node):
    """Record the phase2 replay floor + op count (handlers are registered
    as bound methods at node construction, so re-register on the
    instance's transport rather than patching the class)."""
    from elasticsearch_tpu.cluster.multinode import ACTION_RECOVER

    seen = {}
    orig = node._on_start_recovery

    def spy(payload, src):
        resp = orig(payload, src)
        seen["above_seqno"] = payload.get("above_seqno", -1)
        seen["n_ops"] = len(resp["ops"])
        return resp

    node.transport.register_handler(ACTION_RECOVER, spy)
    return seen


class TestFileRecovery:
    def test_replica_recovers_via_files_not_ops(self):
        hub, n1, client = one_node_with_docs(200)
        seen = spy_phase2(n1)
        n2 = ClusterNode("n2", hub)
        n2.join("n1")  # reroute allocates the replica -> recovery runs
        actions = [a for _, _, a in hub.requests_log]
        assert ACTION_RECOVER_FILES_START in actions
        assert ACTION_RECOVER_FILE_CHUNK in actions
        # phase2 replayed only the (empty) tail above the shipped commit
        assert seen["above_seqno"] >= 199
        assert seen["n_ops"] == 0
        # the replica serves every doc: kill the primary and search
        hub.disconnect("n1")
        assert n2.check_master() == "n2"
        c2 = ClusterClient(n2)
        c2.refresh("logs")
        res = c2.search("logs", {"query": {"match": {"msg": "event"}},
                                 "size": 300})
        assert res["hits"]["total"] == 200

    def test_deletes_survive_file_recovery(self):
        hub, n1, client = one_node_with_docs(60, deletes=(3, 17, 42))
        n2 = ClusterNode("n2", hub)
        n2.join("n1")
        hub.disconnect("n1")
        n2.check_master()
        c2 = ClusterClient(n2)
        c2.refresh("logs")
        res = c2.search("logs", {"query": {"match": {"msg": "event"}},
                                 "size": 100})
        assert res["hits"]["total"] == 57
        ids = {h["_id"] for h in res["hits"]["hits"]}
        assert not ids & {"3", "17", "42"}

    def test_writes_after_commit_covered_by_phase2(self):
        """Docs written between the file commit and the ops phase arrive
        via the phase2 tail (above the shipped seqno)."""
        hub, n1, client = one_node_with_docs(50)
        orig = n1._on_start_file_recovery
        extra = {"done": False}

        def wedge(payload, src):
            resp = orig(payload, src)
            if not extra["done"]:
                extra["done"] = True
                for i in range(50, 60):
                    client.index("logs", str(i), {"msg": f"event {i}"})
            return resp

        n1.transport.register_handler(ACTION_RECOVER_FILES_START, wedge)
        seen = spy_phase2(n1)
        n2 = ClusterNode("n2", hub)
        n2.join("n1")
        assert seen["n_ops"] == 10  # exactly the post-commit tail
        hub.disconnect("n1")
        n2.check_master()
        c2 = ClusterClient(n2)
        c2.refresh("logs")
        res = c2.search("logs", {"query": {"match": {"msg": "event"}},
                                 "size": 100})
        assert res["hits"]["total"] == 60

    def test_ops_fallback_when_file_phase_fails(self):
        hub, n1, client = one_node_with_docs(80)

        def boom(payload, src):
            raise ElasticsearchTpuException("simulated phase1 failure")

        n1.transport.register_handler(ACTION_RECOVER_FILES_START, boom)
        seen = spy_phase2(n1)
        n2 = ClusterNode("n2", hub)
        n2.join("n1")
        assert seen["above_seqno"] == -1  # full ops replay
        assert seen["n_ops"] == 80
        hub.disconnect("n1")
        n2.check_master()
        c2 = ClusterClient(n2)
        c2.refresh("logs")
        res = c2.search("logs", {"query": {"match": {"msg": "event"}},
                                 "size": 100})
        assert res["hits"]["total"] == 80

    def test_recovered_shard_flush_keeps_new_docs(self):
        """Regression: after file recovery the engine's segment-name
        counter must advance past the shipped names — a promoted replica
        sealing a new segment under an existing name would make the store
        skip it and silently lose the docs on the next flush."""
        hub, n1, client = one_node_with_docs(50)
        n2 = ClusterNode("n2", hub)
        n2.join("n1")
        hub.disconnect("n1")
        n2.check_master()
        c2 = ClusterClient(n2)
        for i in range(50, 60):
            c2.index("logs", str(i), {"msg": f"event {i}"})
        shard = n2.shards[("logs", 0)]
        shard.flush()  # seal + commit on the recovered engine
        names = [s.name for s in shard.engine.searchable_segments()]
        assert len(names) == len(set(names)), f"duplicate segment: {names}"
        # reload the store commit from disk: everything must round-trip
        reloaded = shard.engine.store.load_segments()
        total = sum(int(s.live[: s.num_docs].sum()) for s in reloaded)
        assert total == 60
        c2.refresh("logs")
        res = c2.search("logs", {"query": {"match": {"msg": "event"}},
                                 "size": 100})
        assert res["hits"]["total"] == 60

    def test_sessions_cleaned_up_after_finalize(self):
        hub, n1, client = one_node_with_docs(30)
        n2 = ClusterNode("n2", hub)
        n2.join("n1")
        assert n1._recovery_sessions == {}

    def test_source_throttle_paces_chunks(self):
        hub, n1, client = one_node_with_docs(100)
        n1.recovery_max_bytes_per_sec = 200 * 1024  # 200 KB/s
        import time as _time

        t0 = _time.monotonic()
        n2 = ClusterNode("n2", hub)
        n2.join("n1")
        elapsed = _time.monotonic() - t0
        sent = sum(1 for _, _, a in hub.requests_log
                   if a == ACTION_RECOVER_FILE_CHUNK)
        assert sent > 0
        # with ~100 docs the store is tens of KB; the throttle must have
        # introduced measurable pacing without stalling recovery
        assert elapsed < 30
        hub.disconnect("n1")
        n2.check_master()
        c2 = ClusterClient(n2)
        c2.refresh("logs")
        res = c2.search("logs", {"query": {"match": {"msg": "event"}},
                                 "size": 200})
        assert res["hits"]["total"] == 100
