"""XContent multi-format bodies/responses (XContentFactory/XContentType):
JSON, YAML and CBOR negotiate via Content-Type / Accept / ?format= with
first-bytes sniffing."""

import json

import pytest

from elasticsearch_tpu.common.xcontent import (
    cbor_decode,
    cbor_encode,
    parse,
    response_format,
    serialize,
    sniff_type,
)


class TestCborCodec:
    def test_roundtrip_json_model(self):
        doc = {"title": "hello", "n": 42, "neg": -7, "pi": 3.25,
               "flags": [True, False, None],
               "nested": {"a": [1, 2, {"b": "c"}]},
               "unicode": "héllo wörld", "big": 1 << 40}
        assert cbor_decode(cbor_encode(doc)) == doc

    def test_long_strings_and_arrays(self):
        doc = {"s": "x" * 300, "arr": list(range(500))}
        assert cbor_decode(cbor_encode(doc)) == doc


class TestNegotiation:
    def test_sniffing(self):
        assert sniff_type(b'  {"a": 1}') == "json"
        assert sniff_type(b"---\na: 1\n") == "yaml"
        assert sniff_type(cbor_encode({"a": 1})) == "cbor"

    def test_parse_by_content_type(self):
        assert parse(b"a: 1\nb: [x, y]\n",
                     "application/yaml") == {"a": 1, "b": ["x", "y"]}
        assert parse(cbor_encode({"q": 9}), "application/cbor") == {"q": 9}
        assert parse(b'{"j": true}', "application/json") == {"j": True}

    def test_response_format(self):
        assert response_format({}, None) == "json"
        assert response_format({"format": "yaml"}, None) == "yaml"
        assert response_format({}, "application/cbor") == "cbor"

    def test_serialize_yaml(self):
        data, mime = serialize({"a": [1, 2]}, "yaml")
        assert mime.startswith("application/yaml")
        assert b"a:" in data

    def test_yaml_serializes_non_native_objects(self):
        class Weird:
            def __str__(self):
                return "weird!"

        data, _ = serialize({"x": Weird(), "b": b"\xff\x00"}, "yaml")
        assert b"weird!" in data

    def test_cbor_truncated_string_rejected(self):
        from elasticsearch_tpu.common.xcontent import XContentParseError

        with pytest.raises(XContentParseError, match="truncated"):
            cbor_decode(b"\x65ab")  # declares 5 bytes, 2 present

    def test_cbor_trailing_bytes_rejected(self):
        from elasticsearch_tpu.common.xcontent import XContentParseError

        with pytest.raises(XContentParseError, match="trailing"):
            cbor_decode(cbor_encode({"a": 1}) + b"junk")

    def test_cbor_bigint_degrades_to_string(self):
        assert cbor_decode(cbor_encode({"n": 1 << 70})) == {"n": str(1 << 70)}

    def test_sniff_whitespace_prefixed_yaml(self):
        assert sniff_type(b"\n---\na: 1\n") == "yaml"

    def test_accept_list_with_qvalues(self):
        assert response_format(
            {}, "application/yaml, application/json;q=0.5") == "yaml"


class TestHttpSurface:
    @pytest.fixture(scope="class")
    def server(self):
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.rest.http_server import HttpServer

        node = Node()
        srv = HttpServer(node, port=0)
        srv.start()
        yield f"http://127.0.0.1:{srv.port}"
        srv.stop()

    def _req(self, base, method, path, body=None, headers=None):
        import urllib.request

        req = urllib.request.Request(base + path, data=body, method=method,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, resp.headers.get("Content-Type"), \
                    resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.headers.get("Content-Type"), e.read()

    def test_yaml_request_and_response(self, server):
        st, _, _ = self._req(server, "PUT", "/ymx/_doc/1?refresh=true",
                             b'{"msg": "yaml works"}',
                             {"Content-Type": "application/json"})
        assert st == 201
        body = b"query:\n  match:\n    msg: yaml\n"
        st, ctype, raw = self._req(
            server, "POST", "/ymx/_search?format=yaml", body,
            {"Content-Type": "application/yaml"})
        assert st == 200
        assert ctype.startswith("application/yaml")
        import yaml as _yaml

        parsed = _yaml.safe_load(raw)
        assert parsed["hits"]["total"] == 1

    def test_cbor_request_and_response(self, server):
        doc = cbor_encode({"msg": "cbor payload"})
        st, _, _ = self._req(server, "PUT", "/cbx/_doc/1?refresh=true", doc,
                             {"Content-Type": "application/cbor"})
        assert st == 201
        q = cbor_encode({"query": {"match": {"msg": "cbor"}}})
        st, ctype, raw = self._req(server, "POST", "/cbx/_search", q,
                                   {"Content-Type": "application/cbor",
                                    "Accept": "application/cbor"})
        assert st == 200
        assert ctype.startswith("application/cbor")
        parsed = cbor_decode(raw)
        assert parsed["hits"]["total"] == 1
        assert parsed["hits"]["hits"][0]["_source"]["msg"] == "cbor payload"

    def test_sniffed_yaml_without_header(self, server):
        st, _, raw = self._req(server, "POST", "/ymx/_search",
                               b"---\nquery:\n  match_all: {}\n")
        assert st == 200
        assert json.loads(raw)["hits"]["total"] >= 1
