"""Tests for the pallas segment-sum aggregation kernel (ops/pallas_aggs.py).

Interpret mode on CPU; oracle is a numpy scatter-add — the bucket
collection the reference performs doc-at-a-time in
search/aggregations/bucket/BucketsAggregator.java.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elasticsearch_tpu.ops.pallas_aggs import (
    CHUNK,
    reference_segment_aggregate,
    segment_aggregate,
)


def run(ords, mask, vals=None, n_ords=None):
    if vals is None:
        return segment_aggregate(jnp.asarray(ords), jnp.asarray(mask),
                                 n_ords=n_ords, interpret=True)
    return segment_aggregate(jnp.asarray(ords), jnp.asarray(mask),
                             jnp.asarray(vals), n_ords=n_ords, with_sum=True,
                             interpret=True)


class TestSegmentAggregate:
    def test_counts_and_sums_match_scatter(self):
        rng = np.random.RandomState(1)
        nd = 7000
        ords = rng.randint(-1, 500, nd).astype(np.int32)
        mask = (rng.rand(nd) > 0.3).astype(np.float32)
        vals = rng.randn(nd).astype(np.float32)
        cnt, tot = run(ords, mask, vals, n_ords=500)
        rc, rt = reference_segment_aggregate(ords, mask, vals, n_ords=500)
        np.testing.assert_allclose(np.asarray(cnt), rc, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(tot), rt, rtol=1e-4, atol=1e-4)

    def test_count_only(self):
        rng = np.random.RandomState(2)
        nd = 2000
        ords = rng.randint(0, 64, nd).astype(np.int32)
        mask = np.ones(nd, np.float32)
        (cnt,) = run(ords, mask, n_ords=64)
        (rc,) = reference_segment_aggregate(ords, mask, n_ords=64)
        np.testing.assert_allclose(np.asarray(cnt), rc)
        assert float(np.asarray(cnt).sum()) == nd

    def test_out_of_range_and_masked_skipped(self):
        ords = np.asarray([0, 5, 99, 100, -1, 5], np.int32)
        mask = np.asarray([1, 1, 1, 1, 1, 0], np.float32)
        (cnt,) = run(ords, mask, n_ords=100)
        cnt = np.asarray(cnt)
        assert cnt[0] == 1 and cnt[5] == 1 and cnt[99] == 1
        assert cnt.sum() == 3  # ord 100 out of range, last masked out

    def test_large_ord_space(self):
        rng = np.random.RandomState(3)
        nd = 4000
        ords = rng.randint(0, 10_000, nd).astype(np.int32)
        mask = (rng.rand(nd) > 0.5).astype(np.float32)
        (cnt,) = run(ords, mask, n_ords=10_000)
        (rc,) = reference_segment_aggregate(ords, mask, n_ords=10_000)
        np.testing.assert_allclose(np.asarray(cnt), rc)

    def test_zero_length_input(self):
        (cnt,) = segment_aggregate(
            jnp.asarray(np.zeros(0, np.int32)),
            jnp.asarray(np.zeros(0, np.float32)), n_ords=16, interpret=True)
        assert np.asarray(cnt).shape == (16,) and np.asarray(cnt).sum() == 0

    def test_sum_only(self):
        rng = np.random.RandomState(11)
        ords = rng.randint(0, 30, 500).astype(np.int32)
        mask = np.ones(500, np.float32)
        vals = rng.randn(500).astype(np.float32)
        (tot,) = segment_aggregate(
            jnp.asarray(ords), jnp.asarray(mask), jnp.asarray(vals),
            n_ords=30, with_sum=True, with_count=False, interpret=True)
        _, rt = reference_segment_aggregate(ords, mask, vals, n_ords=30)
        np.testing.assert_allclose(np.asarray(tot), rt, rtol=1e-4, atol=1e-4)

    def test_exact_chunk_multiple(self):
        nd = CHUNK * 3
        ords = np.zeros(nd, np.int32)
        mask = np.ones(nd, np.float32)
        (cnt,) = run(ords, mask, n_ords=8)
        assert float(np.asarray(cnt)[0]) == nd


class TestOpsDispatchParity:
    """Every pallas branch in ops/aggs.py must match its scatter twin
    (ES_TPU_PALLAS=interpret vs off) on the same inputs."""

    @pytest.fixture()
    def csr(self):
        rng = np.random.RandomState(9)
        nd1 = 1025
        n_vals = 3000
        flat_docs = np.sort(rng.randint(0, nd1 - 1, n_vals)).astype(np.int32)
        flat_ords = rng.randint(0, 40, n_vals).astype(np.int32)
        flat_values = (rng.randn(n_vals) * 50).astype(np.float64)
        mask = np.zeros(nd1, bool)
        mask[rng.choice(nd1 - 1, 600, replace=False)] = True
        values_by_doc = (rng.randn(nd1) * 10).astype(np.float64)
        return (jnp.asarray(flat_docs), jnp.asarray(flat_ords),
                jnp.asarray(flat_values), jnp.asarray(mask),
                jnp.asarray(values_by_doc))

    def _both(self, monkeypatch, fn):
        from elasticsearch_tpu.ops import aggs as agg_ops
        monkeypatch.setenv("ES_TPU_PALLAS", "off")
        ref = np.asarray(fn(agg_ops))
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        got = np.asarray(fn(agg_ops))
        return ref, got

    def test_ordinal_counts(self, monkeypatch, csr):
        docs, ords, _, mask, _ = csr
        ref, got = self._both(
            monkeypatch, lambda m: m.ordinal_counts(docs, ords, mask, 40))
        np.testing.assert_array_equal(ref, got)

    def test_ordinal_sums(self, monkeypatch, csr):
        docs, ords, _, mask, vbd = csr
        ref, got = self._both(
            monkeypatch,
            lambda m: m.ordinal_sums(docs, ords, mask, vbd, 40))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-3)

    def test_histogram_counts(self, monkeypatch, csr):
        docs, _, vals, mask, _ = csr
        ref, got = self._both(
            monkeypatch,
            lambda m: m.histogram_counts(docs, vals, mask, 10.0, 0.0,
                                         -30, 60))
        np.testing.assert_array_equal(ref, got)

    def test_histogram_counts_epoch_millis_keys(self, monkeypatch, csr):
        """Date-histogram-scale bucket keys: the int64 rebase must stay
        exact on the pallas path (float rounding would shift buckets)."""
        docs, _, _, mask, _ = csr
        rng = np.random.RandomState(10)
        base = 1_700_000_000_000  # epoch ms
        vals = jnp.asarray(
            base + rng.randint(0, 86_400_000, docs.shape[0]).astype(np.int64),
            jnp.float64)
        ref, got = self._both(
            monkeypatch,
            lambda m: m.histogram_counts(docs, vals, mask, 3_600_000.0, 0.0,
                                         base // 3_600_000, 25))
        np.testing.assert_array_equal(ref, got)

    def test_value_histogram_sums(self, monkeypatch, csr):
        docs, _, vals, mask, vbd = csr
        ref, got = self._both(
            monkeypatch,
            lambda m: m.value_histogram_sums(docs, vals, vbd, mask, 10.0,
                                             0.0, -30, 60))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-3)

    def test_nan_metric_treated_as_missing_not_contagious(self, monkeypatch):
        """Pallas path: a non-finite metric value must not poison other
        buckets through 0*inf=NaN in the one-hot matmul."""
        from elasticsearch_tpu.ops import aggs as agg_ops
        docs = jnp.asarray(np.asarray([0, 1, 2], np.int32))
        ords = jnp.asarray(np.asarray([5, 133, 7], np.int32))
        mask = jnp.asarray(np.ones(4, bool))
        vbd = jnp.asarray(np.asarray([np.inf, 1.0, 2.0, 0.0]))
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        out = np.asarray(agg_ops.ordinal_sums(docs, ords, mask, vbd, 200))
        assert np.isfinite(out[133]) and abs(out[133] - 1.0) < 1e-6
        assert np.isfinite(out[7]) and abs(out[7] - 2.0) < 1e-6
        assert out[5] > 1e38  # inf saturates its own bucket only


class TestEngineScoringParity:
    """Full-text search through Node.search must produce identical hits
    whether the query phase runs the pallas tile-scoring kernel
    (ES_TPU_PALLAS=interpret routes score_terms_node through
    PallasScoreTermsNode) or the XLA scatter program."""

    def test_match_query_parity(self, monkeypatch):
        from elasticsearch_tpu.node import Node

        rng = np.random.RandomState(12)
        words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta",
                 "eta", "theta"]
        monkeypatch.setenv("ES_TPU_PALLAS", "off")
        node = Node()
        node.create_index("docs", {
            "settings": {"number_of_shards": 1},
            "mappings": {"_doc": {"properties": {
                "body": {"type": "text"}}}}})
        for i in range(120):
            text = " ".join(rng.choice(words, rng.randint(3, 9)))
            node.index_doc("docs", str(i), {"body": text},
                           refresh=(i == 119))
        queries = [
            {"query": {"match": {"body": "alpha gamma"}}, "size": 15},
            {"query": {"match": {"body": {"query": "beta delta zeta",
                                          "operator": "and"}}}, "size": 15},
            {"query": {"bool": {"should": [
                {"match": {"body": "theta"}},
                {"match": {"body": "eta epsilon"}}]}}, "size": 20},
        ]
        ref = [node.search("docs", q) for q in queries]
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        got = [node.search("docs", q) for q in queries]
        for r, g, q in zip(ref, got, queries):
            assert g["hits"]["total"] == r["hits"]["total"], q
            r_hits = [(h["_id"], round(h["_score"], 4))
                      for h in r["hits"]["hits"]]
            g_hits = [(h["_id"], round(h["_score"], 4))
                      for h in g["hits"]["hits"]]
            assert sorted(g_hits) == sorted(r_hits), q


class TestEnginePallasParity:
    """The engine's terms partial (search/aggregations.py ->
    ops/aggs.ordinal_counts) must produce identical buckets through the
    pallas segment-sum path (ES_TPU_PALLAS=interpret) and the scatter
    path. (The engine's histogram partial is host-side numpy today, so
    only the terms agg exercises the kernel end-to-end.)"""

    def _search(self, node, body):
        return node.search("logs", body)

    def test_terms_and_histogram_parity(self, monkeypatch):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("logs", {
            "settings": {"number_of_shards": 1},
            "mappings": {"_doc": {"properties": {
                "host": {"type": "keyword"},
                "latency": {"type": "float"},
                "msg": {"type": "text"},
            }}}})
        rng = np.random.RandomState(4)
        hosts = [f"web-{i:02d}" for i in range(12)]
        for i in range(300):
            node.index_doc("logs", str(i), {
                "host": hosts[rng.randint(len(hosts))],
                "latency": float(rng.rand() * 100),
                "msg": "error timeout" if i % 3 == 0 else "ok fast",
            }, refresh=(i == 299))
        body = {
            "query": {"match": {"msg": "error"}},
            "size": 0,
            "aggs": {
                "by_host": {"terms": {"field": "host", "size": 20},
                            "aggs": {"lat": {"avg": {"field": "latency"}}}},
                "lat_histo": {"histogram": {"field": "latency",
                                            "interval": 20}},
            },
        }
        monkeypatch.setenv("ES_TPU_PALLAS", "off")
        ref = self._search(node, body)["aggregations"]
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        got = self._search(node, body)["aggregations"]

        ref_hosts = {b["key"]: b["doc_count"]
                     for b in ref["by_host"]["buckets"]}
        got_hosts = {b["key"]: b["doc_count"]
                     for b in got["by_host"]["buckets"]}
        assert got_hosts == ref_hosts
        for rb, gb in zip(ref["by_host"]["buckets"],
                          got["by_host"]["buckets"]):
            if rb["lat"]["value"] is None:
                assert gb["lat"]["value"] is None
            else:
                assert abs(rb["lat"]["value"] - gb["lat"]["value"]) < 1e-3
        assert [(b["key"], b["doc_count"])
                for b in got["lat_histo"]["buckets"]] == \
            [(b["key"], b["doc_count"]) for b in ref["lat_histo"]["buckets"]]
