"""Scalar reference implementations for golden-value tests.

The kernels in elasticsearch_tpu/ops must agree with these simple,
obviously-correct Python loops (the reference's behavior re-derived from
Lucene BM25Similarity / aggregation semantics). SURVEY.md §7.2.3: kernels
are gated on recall parity vs a scalar reference scorer.
"""

import math
from collections import defaultdict

K1 = 1.2
B = 0.75


def bm25_idf(df, doc_count):
    return math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))


def score_corpus(docs_tokens, query_terms, k1=K1, b=B):
    """docs_tokens: list[list[str]]; returns {doc: score} for docs matching
    ANY query term (disjunction), plus {doc: n_matched_terms}."""
    n = len(docs_tokens)
    postings = defaultdict(dict)  # term -> {doc: tf}
    for d, toks in enumerate(docs_tokens):
        for t in toks:
            postings[t][d] = postings[t].get(d, 0) + 1
    doc_len = [len(t) for t in docs_tokens]
    with_field = [d for d in range(n) if doc_len[d] > 0]
    avgdl = max(sum(doc_len) / max(len(with_field), 1), 1.0) if with_field else 1.0
    doc_count = len(with_field)
    scores = defaultdict(float)
    matched = defaultdict(int)
    for term in query_terms:
        plist = postings.get(term)
        if not plist:
            continue
        idf = bm25_idf(len(plist), doc_count)
        for d, tf in plist.items():
            denom = tf + k1 * (1 - b + b * doc_len[d] / avgdl)
            scores[d] += idf * tf * (k1 + 1) / denom
            matched[d] += 1
    return dict(scores), dict(matched)


def top_k(scores, k):
    """Sorted (score desc, doc asc) top-k list of (doc, score)."""
    return sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))[:k]


def terms_agg(docs_values, mask):
    """docs_values: list[list[str]] per doc; mask: matched docs set."""
    counts = defaultdict(int)
    for d in mask:
        for v in set(docs_values[d]):
            counts[v] += 1
    return dict(counts)


def histogram_agg(docs_values, mask, interval, offset=0.0):
    counts = defaultdict(int)
    for d in mask:
        for v in docs_values[d]:
            counts[math.floor((v - offset) / interval)] += 1
    return dict(counts)


def stats_agg(docs_values, mask):
    vals = [v for d in mask for v in docs_values[d]]
    if not vals:
        return {"count": 0, "sum": 0.0, "min": None, "max": None}
    return {
        "count": len(vals),
        "sum": sum(vals),
        "min": min(vals),
        "max": max(vals),
        "avg": sum(vals) / len(vals),
    }
