"""Snapshot in-progress status + abort (TransportSnapshotsStatusAction,
SnapshotsService:105 deleteSnapshot-aborts) and the secure-settings
keystore (KeyStoreWrapper). VERDICT r4 item 10."""

import os
import threading
import time

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def node():
    n = Node(Settings.EMPTY)
    n.create_index("snapme", {"settings": {"number_of_shards": 3},
                              "mappings": {"properties": {
                                  "msg": {"type": "text"}}}})
    for i in range(30):
        n.index_doc("snapme", str(i), {"msg": f"event {i}"})
    n.indices["snapme"].refresh()
    n.snapshots.put_repository("r1", {"type": "fs",
                                      "settings": {"location": "statusrepo"}})
    yield n
    n.close()


class TestSnapshotStatus:
    def test_status_visible_mid_snapshot(self, node, monkeypatch):
        """_snapshot/_status must show per-shard stages while the
        snapshot RUNS (wait_for_completion=false + a slowed copy)."""
        import shutil as _shutil

        gate = threading.Event()
        orig = _shutil.copytree

        def slow_copytree(*args, **kw):
            gate.wait(5)  # hold the first shard until the test looked
            return orig(*args, **kw)

        monkeypatch.setattr(
            "elasticsearch_tpu.snapshots.service.shutil.copytree",
            slow_copytree)
        r = node.snapshots.create_snapshot("r1", "live", {},
                                           wait_for_completion=False)
        assert r == {"accepted": True}
        time.sleep(0.05)
        st = node.snapshots.snapshot_status("r1", "live")
        s = st["snapshots"][0]
        assert s["state"] == "IN_PROGRESS"
        assert s["shards_stats"]["total"] == 3
        assert s["shards_stats"]["done"] < 3
        assert s["indices"]["snapme"]  # per-shard stages present
        gate.set()
        # drains to completion; status then reads from the manifest
        deadline = time.time() + 10
        while time.time() < deadline:
            s = node.snapshots.snapshot_status("r1", "live")["snapshots"][0]
            if s["state"] == "SUCCESS":
                break
            time.sleep(0.02)
        assert s["state"] == "SUCCESS"
        assert s["shards_stats"]["done"] == 3

    def test_abort_leaves_repo_consistent(self, node, monkeypatch):
        """DELETE of a running snapshot aborts it; the partial snapshot
        vanishes and the repo stays usable."""
        import shutil as _shutil

        gate = threading.Event()
        orig = _shutil.copytree

        def slow_copytree(*args, **kw):
            gate.wait(5)
            return orig(*args, **kw)

        monkeypatch.setattr(
            "elasticsearch_tpu.snapshots.service.shutil.copytree",
            slow_copytree)
        node.snapshots.create_snapshot("r1", "doomed", {},
                                       wait_for_completion=False)
        time.sleep(0.05)
        t0 = time.time()
        gate.set()  # let the in-flight shard finish; abort cuts the rest

        out = node.snapshots.delete_snapshot("r1", "doomed")
        assert out == {"acknowledged": True}
        assert time.time() - t0 < 10
        repo = node.snapshots._repo("r1")
        assert "doomed" not in repo.list_snapshots()
        assert not os.path.exists(repo.snapshot_path("doomed"))
        # the repo still takes new snapshots afterwards
        r = node.snapshots.create_snapshot("r1", "after")
        assert r["snapshot"]["state"] == "SUCCESS"

    def test_status_of_completed_snapshot_from_manifest(self, node):
        node.snapshots.create_snapshot("r1", "done1")
        s = node.snapshots.snapshot_status("r1", "done1")["snapshots"][0]
        assert s["state"] == "SUCCESS"
        assert s["shards_stats"]["done"] == s["shards_stats"]["total"] == 3

    def test_status_missing_snapshot_404(self, node):
        from elasticsearch_tpu.common.errors import ResourceNotFoundException

        with pytest.raises(ResourceNotFoundException):
            node.snapshots.snapshot_status("r1", "nope")


class TestKeystore:
    def test_round_trip_and_wrong_password(self, tmp_path):
        from elasticsearch_tpu.common.keystore import (
            KeyStore,
            KeystoreException,
        )

        ks = KeyStore()
        ks.set_string("s3.client.default.secret_key", "hunter2")
        ks.set_string("repo.password", "p@ss")
        path = str(tmp_path / KeyStore.FILENAME)
        ks.save(path, password="master-pw")
        # secrets are NOT in the file in the clear
        raw = open(path, encoding="utf-8").read()
        assert "hunter2" not in raw and "p@ss" not in raw
        back = KeyStore.load(path, password="master-pw")
        assert back.get_string("s3.client.default.secret_key") == "hunter2"
        assert back.list_settings() == ["repo.password",
                                        "s3.client.default.secret_key"]
        with pytest.raises(KeystoreException, match="password is wrong"):
            KeyStore.load(path, password="not-it")
        # tampering is detected (encrypt-then-MAC)
        import json as _json

        payload = _json.loads(raw)
        payload["data"] = ("00" * 4) + payload["data"][8:]
        open(path, "w", encoding="utf-8").write(_json.dumps(payload))
        with pytest.raises(KeystoreException):
            KeyStore.load(path, password="master-pw")

    def test_node_loads_secure_settings_at_boot(self, tmp_path):
        from elasticsearch_tpu.common.keystore import KeyStore

        data_dir = str(tmp_path / "data")
        os.makedirs(data_dir)
        ks = KeyStore()
        ks.set_string("repo.secret", "squirrel")
        ks.save(os.path.join(data_dir, KeyStore.FILENAME))
        node = Node(Settings.EMPTY, data_path=data_dir)
        try:
            assert node.secure_settings == {"repo.secret": "squirrel"}
            # filtered: never in the displayed node settings
            assert "repo.secret" not in str(
                node.node_info()["nodes"][node.node_id]["settings"])
        finally:
            node.close()

    def test_remove_and_validation(self):
        from elasticsearch_tpu.common.errors import IllegalArgumentException
        from elasticsearch_tpu.common.keystore import KeyStore

        ks = KeyStore()
        with pytest.raises(IllegalArgumentException, match="lowercase"):
            ks.set_string("UPPER.case", "x")
        ks.set_string("a.b", "1")
        ks.remove("a.b")
        with pytest.raises(IllegalArgumentException):
            ks.remove("a.b")


class TestDeleteVsRunningWorkerRace:
    def test_delete_timeout_flags_worker_cleanup(self, node, monkeypatch):
        """delete_snapshot whose abort wait TIMES OUT must not rmtree
        against the worker's copytree: it flags delete_requested; the
        worker removes the partial directory itself and suppresses its
        SUCCESS manifest (ISSUE 2 satellite)."""
        import shutil as _shutil

        gate = threading.Event()
        copying = threading.Event()
        orig = _shutil.copytree

        def stalled_copytree(*args, **kw):
            copying.set()
            gate.wait(10)  # worker stuck mid-copy, past abort checks
            return orig(*args, **kw)

        monkeypatch.setattr(
            "elasticsearch_tpu.snapshots.service.shutil.copytree",
            stalled_copytree)
        r = node.snapshots.create_snapshot("r1", "racy", {},
                                           wait_for_completion=False)
        assert r == {"accepted": True}
        assert copying.wait(5)
        key = ("r1", "racy")
        prog = node.snapshots._in_progress[key]

        class _NeverDone:
            """done-event stand-in whose wait always times out (the
            worker is wedged in copytree for longer than the deleter
            is willing to wait)."""

            def __init__(self, real):
                self.real = real

            def wait(self, timeout=None):
                return False

            def is_set(self):
                return self.real.is_set()

            def set(self):
                self.real.set()

        real_done = prog["done"]
        prog["done"] = _NeverDone(real_done)
        resp = node.snapshots.delete_snapshot("r1", "racy")
        assert resp == {"acknowledged": True}
        assert prog["delete_requested"] is True
        # the deleter did NOT remove the directory out from under the
        # worker — the worker owns the cleanup
        gate.set()
        assert real_done.wait(10)
        time.sleep(0.05)
        assert prog["state"] == "ABORTED"
        repo = node.snapshots._repo("r1")
        assert not os.path.exists(repo.snapshot_path("racy"))
        assert "racy" not in repo.list_snapshots()  # no SUCCESS manifest
