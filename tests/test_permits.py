"""Operation permits / drain (IndexShardOperationPermits.java, acquired
at IndexShard.java:2089). VERDICT r4 item 9: term fencing existed in
writes, but there was no permit/drain primitive for relocation handoff
and primary-term bumps."""

import threading
import time

import pytest

from elasticsearch_tpu.common.settings import Settings  # noqa: F401
from elasticsearch_tpu.index.shard import (
    IndexShard,
    ShardNotPrimaryException,
)
from elasticsearch_tpu.mapper.mapping import MapperService
from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry


def make_shard(primary=True):
    svc = MapperService(AnalysisRegistry(), {"properties": {
        "msg": {"type": "text"}}})
    shard = IndexShard("idx", 0, svc, primary=primary)
    shard.start_fresh()
    return shard


class TestOperationPermits:
    def test_old_term_rejected_new_term_allowed(self):
        shard = make_shard()
        shard.primary_term = 3
        with pytest.raises(ShardNotPrimaryException, match="too old"):
            with shard.acquire_primary_permit(op_term=2):
                pass
        with shard.acquire_primary_permit(op_term=3):
            shard.index_doc("1", {"msg": "ok"})
        assert shard.get_doc("1").found

    def test_non_primary_rejected(self):
        shard = make_shard(primary=False)
        with pytest.raises(ShardNotPrimaryException):
            with shard.acquire_primary_permit():
                pass

    def test_promotion_drains_in_flight_then_fences(self):
        """The VERDICT done-criterion: an in-flight op finishes before
        the term bump; an op racing in with the OLD term afterwards is
        rejected; a new-term op proceeds."""
        shard = make_shard(primary=False)
        shard.primary = True  # temporarily writable to hold a permit
        in_flight = threading.Event()
        release = threading.Event()
        op_done = {}

        def slow_op():
            with shard.permits.acquire():
                in_flight.set()
                release.wait(5)
                op_done["t"] = shard.primary_term  # term seen INSIDE op

        t = threading.Thread(target=slow_op)
        t.start()
        assert in_flight.wait(5)
        shard.primary = False  # back to replica about to be promoted

        promoted = threading.Event()

        def promote():
            shard.promote_to_primary(7)
            promoted.set()

        p = threading.Thread(target=promote)
        p.start()
        time.sleep(0.05)
        assert not promoted.is_set()  # drain waits on the in-flight op
        release.set()
        t.join(5)
        assert promoted.wait(5)
        p.join(5)
        # the in-flight op completed under the OLD term (drained, not
        # killed), and the bump happened only after
        assert op_done["t"] == 1
        assert shard.primary and shard.primary_term == 7
        # a straggler presenting the pre-promotion term is fenced
        with pytest.raises(ShardNotPrimaryException, match="too old"):
            with shard.acquire_primary_permit(op_term=1):
                pass
        with shard.acquire_primary_permit(op_term=7):
            shard.index_doc("after", {"msg": "new-term write"})

    def test_drain_blocks_new_acquisitions_until_done(self):
        shard = make_shard()
        entered = threading.Event()
        holding = threading.Event()
        release = threading.Event()

        def holder():
            with shard.permits.acquire():
                holding.set()
                release.wait(5)

        h = threading.Thread(target=holder)
        h.start()
        assert holding.wait(5)

        order = []

        def drainer():
            with shard.permits.block_and_drain():
                order.append("drain")

        def late_writer():
            entered.wait(5)
            with shard.permits.acquire():
                order.append("write")

        d = threading.Thread(target=drainer)
        w = threading.Thread(target=late_writer)
        d.start()
        time.sleep(0.05)  # drainer is now blocked on the holder
        w.start()
        entered.set()
        time.sleep(0.05)
        release.set()
        for th in (h, d, w):
            th.join(5)
        assert order[0] == "drain"  # parked writer ran after the drain

    def test_relocation_handoff_completes_then_rejects(self):
        shard = make_shard()
        shard.index_doc("1", {"msg": "x"})
        handoff_ran = []
        with shard.relocation_handoff():
            handoff_ran.append(True)  # quiesced critical section
        assert handoff_ran
        assert not shard.primary
        with pytest.raises(ShardNotPrimaryException):
            with shard.acquire_primary_permit():
                pass

    def test_writer_parked_behind_promotion_drain_is_fenced(self):
        """The stale-write window (ADVICE medium): validation must run
        UNDER the permit. A writer that parks behind a promotion's drain
        wakes under the NEW term — its op term is stale and must be
        rejected, not land pre-validated under the bumped term."""
        shard = make_shard(primary=True)
        in_flight = threading.Event()
        release = threading.Event()

        def holder():
            with shard.permits.acquire():
                in_flight.set()
                release.wait(5)

        h = threading.Thread(target=holder)
        h.start()
        assert in_flight.wait(5)

        p = threading.Thread(target=lambda: shard.promote_to_primary(5))
        p.start()
        time.sleep(0.05)  # drain is parked on the holder

        result = {}

        def writer():
            try:
                with shard.acquire_primary_permit(op_term=1):
                    result["landed"] = True
            except ShardNotPrimaryException as e:
                result["error"] = str(e)

        w = threading.Thread(target=writer)
        w.start()
        time.sleep(0.05)  # writer is parked behind the drain
        release.set()
        for t in (h, p, w):
            t.join(5)
        assert "landed" not in result, "stale write landed under new term"
        assert "too old" in result["error"]
        assert shard.primary_term == 5
        # the rejected writer released its permit: a drain can proceed
        with shard.permits.block_and_drain(timeout=1):
            pass

    def test_writer_parked_behind_handoff_loses_primary(self):
        """Same window for relocation handoff: the parked writer wakes
        on a copy that is no longer primary and must be rejected."""
        shard = make_shard(primary=True)
        in_flight = threading.Event()
        release = threading.Event()

        def holder():
            with shard.permits.acquire():
                in_flight.set()
                release.wait(5)

        h = threading.Thread(target=holder)
        h.start()
        assert in_flight.wait(5)

        def handoff():
            with shard.relocation_handoff():
                pass

        p = threading.Thread(target=handoff)
        p.start()
        time.sleep(0.05)

        result = {}

        def writer():
            try:
                with shard.acquire_primary_permit():
                    result["landed"] = True
            except ShardNotPrimaryException as e:
                result["error"] = str(e)

        w = threading.Thread(target=writer)
        w.start()
        time.sleep(0.05)
        release.set()
        for t in (h, p, w):
            t.join(5)
        assert "landed" not in result
        assert "not a" in result["error"]

    def test_drain_timeout_raises_and_unblocks(self):
        from elasticsearch_tpu.common.errors import IllegalArgumentException

        shard = make_shard()
        release = threading.Event()

        def holder():
            with shard.permits.acquire():
                release.wait(5)

        h = threading.Thread(target=holder)
        h.start()
        time.sleep(0.02)
        with pytest.raises(IllegalArgumentException, match="drain"):
            with shard.permits.block_and_drain(timeout=0.1):
                pass
        release.set()
        h.join(5)
        # the failed drain must not leave the shard blocked
        with shard.permits.acquire(timeout=1):
            pass


class TestClusteredTermFencing:
    def test_stale_term_write_rejected_on_primary(self):
        """A write routed under a superseded primary term must be
        rejected by the primary's operation permit (the coordinator may
        have read an old routing table)."""
        from elasticsearch_tpu.cluster.multinode import (
            ACTION_WRITE_PRIMARY,
            ClusterClient,
            ClusterNode,
        )
        from elasticsearch_tpu.transport.local import TransportHub

        hub = TransportHub(strict_serialization=True)
        nodes = {x: ClusterNode(x, hub) for x in ("n1", "n2")}
        nodes["n1"].bootstrap_cluster()
        nodes["n2"].join("n1")
        nodes["n1"].create_index(
            "t", {"index": {"number_of_shards": 1,
                            "number_of_replicas": 0}})
        client = ClusterClient(nodes["n1"])
        client.index("t", "1", {"x": 1})  # current-term write works
        primary = nodes["n1"]._primary_node("t", 0)
        shard = nodes[primary].shards[("t", 0)]
        shard.primary_term = 5  # a promotion bumped the term
        with pytest.raises(ShardNotPrimaryException, match="too old"):
            nodes["n1"].transport.send_request(
                primary, ACTION_WRITE_PRIMARY,
                {"op": "index", "index": "t", "shard": 0, "id": "2",
                 "source": {"x": 2}, "routing": None,
                 "wait_for_active_shards": None, "term": 1})
        # current-term writes keep flowing
        nodes[primary].primary_terms[("t", 0)] = 5
        r = ClusterClient(nodes[primary]).index("t", "3", {"x": 3})
        assert r["result"] == "created"
