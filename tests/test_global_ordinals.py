"""Global ordinals (index/global_ordinals.py): one ordinal space across
segments — GlobalOrdinalsBuilder/OrdinalMap semantics."""

import numpy as np

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.global_ordinals import global_ordinals
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.mapper.mapping import MapperService

MAPPING = {"properties": {"tag": {"type": "keyword"}}}


def _seg(name, values):
    svc = MapperService(AnalysisRegistry(), MAPPING)
    b = SegmentBuilder(name)
    for i, v in enumerate(values):
        b.add_document(svc.parse_document(str(i), {"tag": v}), i)
    return b.seal()


class TestGlobalOrdinals:
    def test_merged_space_and_fold(self):
        s1 = _seg("s1", ["b", "a", "c"])
        s2 = _seg("s2", ["c", "d"])
        g = global_ordinals([s1, s2], "tag")
        assert g.terms == ["a", "b", "c", "d"]
        # fold per-segment counts (local ord order is segment-sorted)
        out = np.zeros(4, np.int64)
        g.fold_counts(s1, np.asarray([1, 1, 1]), out)   # a b c
        g.fold_counts(s2, np.asarray([2, 5]), out)      # c d
        assert out.tolist() == [1, 1, 3, 5]

    def test_cache_by_segment_identity(self):
        s1 = _seg("s1", ["x"])
        s2 = _seg("s2", ["y"])
        a = global_ordinals([s1, s2], "tag")
        b = global_ordinals([s1, s2], "tag")
        assert a is b  # cached
        s3 = _seg("s2", ["y"])  # same name, new object (post-refresh)
        c = global_ordinals([s1, s3], "tag")
        assert c is not a

    def test_terms_agg_parity_across_segments(self):
        """End-to-end: multi-segment terms agg equals single-segment
        semantics (global-ordinals merge vs per-segment dicts)."""
        idx = IndexService("gords", Settings.EMPTY, MAPPING)
        tags = ["red", "green", "blue", "red", "red", "green"]
        for i, t in enumerate(tags[:3]):
            idx.index_doc(str(i), {"tag": t})
        idx.refresh()  # segment 1
        for i, t in enumerate(tags[3:], start=3):
            idx.index_doc(str(i), {"tag": t})
        idx.refresh()  # segment 2
        r = idx.search({"size": 0, "aggs": {
            "t": {"terms": {"field": "tag"}}}})
        buckets = {b["key"]: b["doc_count"]
                   for b in r["aggregations"]["t"]["buckets"]}
        assert buckets == {"red": 3, "green": 2, "blue": 1}
        idx.close()
