"""geo_shape field type + query.

Mirrors the reference's geo_shape support: GeoJSON + WKT shape parsing
(common/geo/builders, GeoWKTParser), the geo_shape query with
INTERSECTS / DISJOINT / WITHIN / CONTAINS relations
(index/query/GeoShapeQueryBuilder.java), and pre-indexed shape
references resolved by coordinator rewrite.
"""

import pytest

from elasticsearch_tpu.common.errors import (
    MapperParsingException,
    QueryShardException,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.utils import geometry as G


class TestGeometry:
    def test_point_in_polygon(self):
        sq = G.Polygon([(0, 0), (10, 0), (10, 10), (0, 10), (0, 0)])
        assert sq.contains_point((5, 5))
        assert sq.contains_point((0, 5))  # boundary counts
        assert not sq.contains_point((11, 5))

    def test_polygon_with_hole(self):
        donut = G.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10), (0, 0)],
            holes=[[(4, 4), (6, 4), (6, 6), (4, 6), (4, 4)]],
        )
        assert donut.contains_point((1, 1))
        assert not donut.contains_point((5, 5))  # in the hole

    def test_relations(self):
        a = G.Polygon([(0, 0), (4, 0), (4, 4), (0, 4), (0, 0)])
        b = G.Polygon([(1, 1), (2, 1), (2, 2), (1, 2), (1, 1)])
        c = G.Polygon([(10, 10), (12, 10), (12, 12), (10, 12), (10, 10)])
        assert b.within(a) and a.contains(b)
        assert a.intersects(b) and not a.intersects(c)
        assert a.disjoint(c)
        line = G.LineString([(-1, 2), (5, 2)])
        assert line.intersects(a)
        assert not line.within(a)  # endpoints stick out

    def test_wkt_roundtrip(self):
        p = G.parse_wkt("POINT (30 10)")
        assert (p.lon, p.lat) == (30.0, 10.0)
        poly = G.parse_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")
        assert poly.contains_point((5, 5))
        mp = G.parse_wkt("MULTIPOLYGON (((0 0, 2 0, 2 2, 0 2, 0 0)), ((5 5, 7 5, 7 7, 5 7, 5 5)))")
        assert mp.contains_point((1, 1)) and mp.contains_point((6, 6))
        env = G.parse_wkt("ENVELOPE (0, 10, 10, 0)")
        assert env.contains_point((5, 5))

    def test_geojson_parse_errors(self):
        with pytest.raises(MapperParsingException):
            G.parse_geojson({"type": "blob", "coordinates": []})
        with pytest.raises(MapperParsingException):
            G.parse_geojson({"type": "polygon",
                             "coordinates": [[[0, 0], [1, 1], [0, 0]]]})
        with pytest.raises(MapperParsingException):
            G.parse_geojson({"no": "type"})

    def test_point_to_point_and_point_on_line_intersect(self):
        p = G.Point(5, 5)
        assert p.intersects(G.Point(5, 5))
        assert not p.intersects(G.Point(5, 6))
        line = G.LineString([(0, 5), (10, 5)])
        assert p.intersects(line) and line.intersects(p)
        assert not G.Point(5, 6).intersects(line)

    def test_circle_approximation(self):
        c = G.circle((0.0, 0.0), 111_000)  # ~1 degree radius
        assert c.contains_point((0.0, 0.9))
        assert not c.contains_point((0.0, 1.2))


@pytest.fixture()
def places():
    idx = IndexService(
        "places", Settings({"index.number_of_shards": 1}),
        mapping={"properties": {"area": {"type": "geo_shape"},
                                "name": {"type": "keyword"}}},
    )
    idx.index_doc("sq_small", {"name": "small", "area": {
        "type": "polygon",
        "coordinates": [[[1, 1], [2, 1], [2, 2], [1, 2], [1, 1]]]}})
    idx.index_doc("sq_big", {"name": "big", "area": {
        "type": "polygon",
        "coordinates": [[[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]]]}})
    idx.index_doc("far_pt", {"name": "far", "area": {
        "type": "point", "coordinates": [50, 50]}})
    idx.index_doc("line", {"name": "line", "area": "LINESTRING (0 5, 20 5)"})
    idx.refresh()
    yield idx
    idx.close()


def hit_ids(r):
    return {h["_id"] for h in r["hits"]["hits"]}


class TestGeoShapeQuery:
    QUERY_SQUARE = {"type": "envelope", "coordinates": [[0.5, 3.5], [3.5, 0.5]]}

    def test_intersects_default(self, places):
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": self.QUERY_SQUARE}}}})
        assert hit_ids(r) == {"sq_small", "sq_big"}

    def test_within(self, places):
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": {"type": "envelope", "coordinates": [[0, 10], [10, 0]]},
            "relation": "within"}}}})
        assert hit_ids(r) == {"sq_small", "sq_big"}
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": self.QUERY_SQUARE, "relation": "within"}}}})
        assert hit_ids(r) == {"sq_small"}

    def test_contains(self, places):
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": {"type": "point", "coordinates": [1.5, 1.5]},
            "relation": "contains"}}}})
        assert hit_ids(r) == {"sq_small", "sq_big"}

    def test_disjoint(self, places):
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": self.QUERY_SQUARE, "relation": "disjoint"}}}})
        assert hit_ids(r) == {"far_pt", "line"}

    def test_wkt_query_shape(self, places):
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": "POLYGON ((45 45, 55 45, 55 55, 45 55, 45 45))"}}}})
        assert hit_ids(r) == {"far_pt"}

    def test_unmapped_field(self, places):
        with pytest.raises(QueryShardException):
            places.search({"query": {"geo_shape": {"nope": {
                "shape": self.QUERY_SQUARE}}}})
        r = places.search({"query": {"geo_shape": {
            "nope": {"shape": self.QUERY_SQUARE},
            "ignore_unmapped": True}}})
        assert r["hits"]["total"] == 0

    def test_within_multivalue_combined_bbox(self, places):
        # doc with one shape inside + one far away must still match WITHIN
        places.index_doc("multi", {"area": [
            {"type": "point", "coordinates": [1.5, 1.5]},
            {"type": "point", "coordinates": [80, 80]},
        ]})
        places.refresh()
        r = places.search({"query": {"geo_shape": {"area": {
            "shape": self.QUERY_SQUARE, "relation": "within"}}}})
        assert "multi" in hit_ids(r)

    def test_query_without_shape_rejected(self, places):
        from elasticsearch_tpu.common.errors import ParsingException

        with pytest.raises(ParsingException):
            places.search({"query": {"geo_shape": {"area": {
                "relation": "within"}}}})

    def test_bad_shape_value_rejected_at_index_time(self, places):
        with pytest.raises(MapperParsingException):
            places.index_doc("bad", {"area": {"type": "polygon",
                                              "coordinates": [[[0, 0]]]}})

    def test_bool_filter_combination(self, places):
        r = places.search({"query": {"bool": {
            "must": [{"match_all": {}}],
            "filter": [{"geo_shape": {"area": {"shape": self.QUERY_SQUARE}}},
                       {"term": {"name": "big"}}]}}})
        assert hit_ids(r) == {"sq_big"}


class TestIndexedShape:
    def test_indexed_shape_rewrite(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("shapes", {"mappings": {"properties": {
            "footprint": {"type": "geo_shape"}}}})
        node.create_index("places", {"mappings": {"properties": {
            "area": {"type": "geo_shape"}}}})
        node.index_doc("shapes", "zone", {"footprint": {
            "type": "envelope", "coordinates": [[0, 10], [10, 0]]}})
        node.index_doc("places", "inside", {"area": {
            "type": "point", "coordinates": [5, 5]}})
        node.index_doc("places", "outside", {"area": {
            "type": "point", "coordinates": [50, 50]}})
        for svc in node.indices.values():
            svc.refresh()
        r = node.search("places", {"query": {"geo_shape": {"area": {
            "indexed_shape": {"index": "shapes", "id": "zone",
                              "path": "footprint"},
            "relation": "within"}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"inside"}
        node.close()

    def test_missing_indexed_shape_errors(self):
        from elasticsearch_tpu.common.errors import ResourceNotFoundException
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("places", {"mappings": {"properties": {
            "area": {"type": "geo_shape"}}}})
        node.index_doc("places", "x", {"area": {"type": "point",
                                                "coordinates": [1, 1]}})
        node.indices["places"].refresh()
        with pytest.raises(ResourceNotFoundException):
            node.search("places", {"query": {"geo_shape": {"area": {
                "indexed_shape": {"index": "places", "id": "nope"}}}}})
        node.close()


class TestPersistence:
    def test_shapes_survive_flush_and_reload(self, tmp_data_dir):
        import os

        path = os.path.join(tmp_data_dir, "geo")
        idx = IndexService("geo", Settings({"index.number_of_shards": 1}),
                           mapping={"properties": {
                               "area": {"type": "geo_shape"}}},
                           data_path=path)
        idx.index_doc("a", {"area": {"type": "point", "coordinates": [5, 5]}})
        idx.refresh()
        idx.flush()
        idx.close()
        idx2 = IndexService("geo", Settings({"index.number_of_shards": 1}),
                            mapping={"properties": {
                                "area": {"type": "geo_shape"}}},
                            data_path=path)
        r = idx2.search({"query": {"geo_shape": {"area": {
            "shape": {"type": "envelope", "coordinates": [[0, 10], [10, 0]]}}}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"a"}
        idx2.close()
