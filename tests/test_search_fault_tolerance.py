"""Query-path fault tolerance: shard-failure isolation, partial results,
timeouts, cancellation, and plane-health quarantine.

Mirrors the reference's SearchWithFailuresIT / SearchTimeoutIT /
SearchCancellationIT suites (server/src/test/.../search/), driven here by
the shard-search disruption schemes in testing/disruption.py — the
query-path analog of the transport schemes PR 2 introduced.
"""

import threading
import time

import pytest

from elasticsearch_tpu.common.errors import (
    SearchPhaseExecutionException,
    TaskCancelledException,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.testing.disruption import (
    PlaneFailScheme,
    SearchDelayScheme,
    SearchFailScheme,
    clear_search_disruptions,
)

MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "n": {"type": "integer"},
}}


@pytest.fixture(autouse=True)
def _clean_schemes():
    yield
    clear_search_disruptions()


def make_index(name, shards=3, mesh=False, extra=None):
    settings = {"index.number_of_shards": shards,
                "index.search.mesh": mesh,
                "index.refresh_interval": -1}
    settings.update(extra or {})
    idx = IndexService(name, Settings(settings), mapping=MAPPING)
    for d in range(30):
        idx.index_doc(str(d), {"body": f"w{d % 5} w1", "n": d})
    idx.refresh()
    return idx


@pytest.fixture()
def idx():
    svc = make_index("ftol")
    yield svc
    svc.close()


class TestShardFailureIsolation:
    """Tentpole (1): an exception in one shard yields a failures[] entry
    and _shards.failed >= 1 instead of a 500."""

    def test_one_failed_shard_degrades_to_partial(self, idx):
        baseline = idx.search({"query": {"match": {"body": "w1"}},
                               "size": 30})
        assert baseline["_shards"]["failed"] == 0
        fail = SearchFailScheme(indices=["ftol"], shards=[1]).install()
        r = idx.search({"query": {"match": {"body": "w1"}}, "size": 30})
        assert fail.hits == 1
        assert r["_shards"]["failed"] == 1
        assert r["_shards"]["successful"] == 2
        entry = r["_shards"]["failures"][0]
        assert entry["shard"] == 1 and entry["index"] == "ftol"
        assert "injected" in entry["reason"]["reason"]
        # surviving shards' results are intact and correct
        assert 0 < r["hits"]["total"] < baseline["hits"]["total"]
        shard1_ids = {str(d) for d in range(30)
                      if idx._route(str(d)) == 1}
        got_ids = {h["_id"] for h in r["hits"]["hits"]}
        assert not got_ids & shard1_ids
        assert got_ids == {h["_id"] for h in baseline["hits"]["hits"]
                           if h["_id"] not in shard1_ids}

    def test_typed_failure_reason(self, idx):
        from elasticsearch_tpu.common.errors import (
            QueryPhaseExecutionException,
        )

        SearchFailScheme(QueryPhaseExecutionException("shard blew up"),
                         indices=["ftol"], shards=[0]).install()
        r = idx.search({"query": {"match_all": {}}})
        reason = r["_shards"]["failures"][0]["reason"]
        assert reason["type"] == "query_phase_execution_exception"
        assert reason["reason"] == "shard blew up"

    def test_allow_partial_false_raises(self, idx):
        SearchFailScheme(indices=["ftol"], shards=[1]).install()
        with pytest.raises(SearchPhaseExecutionException) as ei:
            idx.search({"query": {"match_all": {}},
                        "allow_partial_search_results": False})
        failed = ei.value.to_dict()["error"]["failed_shards"]
        assert [f["shard"] for f in failed] == [1]

    def test_all_shards_failed_raises(self, idx):
        SearchFailScheme(indices=["ftol"]).install()
        with pytest.raises(SearchPhaseExecutionException) as ei:
            idx.search({"query": {"match_all": {}}})
        assert "all shards failed" in ei.value.reason

    def test_failed_response_not_cached(self, idx):
        # size=0 responses are request-cache eligible; a partial response
        # must not be served to later callers
        body = {"query": {"match": {"body": "w1"}}, "size": 0}
        fail = SearchFailScheme(indices=["ftol"], shards=[1]).install()
        r1 = idx.search(dict(body))
        assert r1["_shards"]["failed"] == 1
        fail.remove()
        r2 = idx.search(dict(body))
        assert r2["_shards"]["failed"] == 0


class TestSearchViaNodeAndRest:
    @pytest.fixture()
    def node(self):
        from elasticsearch_tpu.node import Node

        n = Node(Settings({"node.name": "ft-node"}))
        n.create_index("ftr", {
            "settings": {"index": {"number_of_shards": 3,
                                   "search": {"mesh": False},
                                   "refresh_interval": -1}},
            "mappings": MAPPING,
        })
        for d in range(30):
            n.index_doc("ftr", str(d), {"body": f"w{d % 5} w1", "n": d})
        n.indices["ftr"].refresh()
        yield n
        n.close()

    def test_rest_partial_is_200_with_failed_shards(self, node):
        from elasticsearch_tpu.rest.controller import RestController

        rc = RestController(node)
        SearchFailScheme(indices=["ftr"], shards=[2]).install()
        status, payload = rc.dispatch(
            "GET", "/ftr/_search", {}, b'{"query": {"match_all": {}}}')
        assert status == 200
        assert payload["_shards"]["failed"] == 1
        assert payload["_shards"]["failures"][0]["shard"] == 2

    def test_rest_allow_partial_false_param(self, node):
        from elasticsearch_tpu.rest.controller import RestController

        rc = RestController(node)
        SearchFailScheme(indices=["ftr"], shards=[2]).install()
        status, payload = rc.dispatch(
            "GET", "/ftr/_search",
            {"allow_partial_search_results": "false"},
            b'{"query": {"match_all": {}}}')
        assert status == 500
        assert (payload["error"]["type"]
                == "search_phase_execution_exception")

    def test_default_allow_partial_setting(self):
        from elasticsearch_tpu.node import Node

        n = Node(Settings({"search.default_allow_partial_results": False}))
        n.create_index("strict", {
            "settings": {"index": {"number_of_shards": 2,
                                   "search": {"mesh": False},
                                   "refresh_interval": -1}}})
        n.index_doc("strict", "1", {"body": "x"})
        n.indices["strict"].refresh()
        SearchFailScheme(indices=["strict"], shards=[0]).install()
        with pytest.raises(SearchPhaseExecutionException):
            n.search("strict", {"query": {"match_all": {}}})
        n.close()

    def test_multi_index_fanout_isolates_failures(self, node):
        node.create_index("ftr2", {
            "settings": {"index": {"number_of_shards": 2,
                                   "search": {"mesh": False},
                                   "refresh_interval": -1}},
            "mappings": MAPPING,
        })
        for d in range(10):
            node.index_doc("ftr2", f"b{d}", {"body": "w1"})
        node.indices["ftr2"].refresh()
        SearchFailScheme(indices=["ftr2"], shards=[0]).install()
        r = node.search("ftr,ftr2", {"query": {"match": {"body": "w1"}},
                                     "size": 50})
        assert r["_shards"]["total"] == 5
        assert r["_shards"]["failed"] == 1
        assert r["_shards"]["failures"][0]["index"] == "ftr2"
        # ftr's 30 hits all present; ftr2 degraded to its surviving shard
        assert sum(h["_index"] == "ftr" for h in r["hits"]["hits"]) == 30


class TestTimeout:
    """Tentpole (2a): the `timeout` request param bounds the query phase;
    expiry returns accumulated hits with timed_out: true."""

    def test_timeout_returns_partial_with_flag(self, idx):
        # shard 0 completes; the straggler trips the deadline at its next
        # checkpoint; remaining shards are skipped
        SearchDelayScheme(0.3, indices=["ftol"], shards=[1]).install()
        t0 = time.monotonic()
        r = idx.search({"query": {"match": {"body": "w1"}}, "size": 30,
                        "timeout": "50ms"})
        took = time.monotonic() - t0
        assert r["timed_out"] is True
        assert r["_shards"]["failed"] == 0
        # shard 0's accumulated hits survive the cut
        shard0_ids = {str(d) for d in range(30) if idx._route(str(d)) == 0}
        assert {h["_id"] for h in r["hits"]["hits"]} >= shard0_ids
        # within ~2 checkpoints of the deadline: one 0.3s stall, not 2x
        assert took < 0.9, took

    def test_no_timeout_by_default(self, idx):
        SearchDelayScheme(0.05, indices=["ftol"]).install()
        r = idx.search({"query": {"match": {"body": "w1"}}, "size": 30})
        assert r["timed_out"] is False
        assert r["hits"]["total"] == 30

    def test_timeout_with_partial_disallowed_raises(self, idx):
        SearchDelayScheme(0.2, indices=["ftol"], shards=[0]).install()
        with pytest.raises(SearchPhaseExecutionException) as ei:
            idx.search({"query": {"match_all": {}}, "timeout": "20ms",
                        "allow_partial_search_results": False})
        assert "timed out" in ei.value.reason

    def test_default_search_timeout_setting(self):
        from elasticsearch_tpu.node import Node

        n = Node(Settings({"search.default_search_timeout": "30ms"}))
        n.create_index("deft", {
            "settings": {"index": {"number_of_shards": 2,
                                   "search": {"mesh": False},
                                   "refresh_interval": -1}}})
        for d in range(8):
            n.index_doc("deft", str(d), {"body": "w1"})
        n.indices["deft"].refresh()
        SearchDelayScheme(0.15, indices=["deft"]).install()
        r = n.search("deft", {"query": {"match_all": {}}})
        assert r["timed_out"] is True
        n.close()


class TestCancellation:
    """Tentpole (2b): _tasks registration + _tasks/{id}/_cancel trips the
    same checkpoints as the timeout."""

    @pytest.fixture()
    def node(self):
        from elasticsearch_tpu.node import Node

        n = Node(Settings({"node.name": "cx-node"}))
        n.create_index("cx", {
            "settings": {"index": {"number_of_shards": 3,
                                   "search": {"mesh": False},
                                   "refresh_interval": -1}},
            "mappings": MAPPING,
        })
        for d in range(30):
            n.index_doc("cx", str(d), {"body": f"w{d % 5} w1"})
        n.indices["cx"].refresh()
        yield n
        n.close()

    def _start_search(self, node, errs, done):
        def run():
            try:
                done.append(node.search("cx",
                                        {"query": {"match": {"body": "w1"}}}))
            except Exception as e:  # noqa: BLE001 — collected for asserts
                errs.append(e)
        t = threading.Thread(target=run)
        t.start()
        return t

    def _wait_for_task(self, node, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            tasks = node.tasks.list_tasks(actions="*search*")
            entries = tasks["nodes"][node.node_id]["tasks"]
            if entries:
                return next(iter(entries))
            time.sleep(0.005)
        raise AssertionError("search task never appeared in _tasks")

    def test_running_search_listed_and_cancellable(self, node):
        SearchDelayScheme(0.15, indices=["cx"]).install()
        errs, done = [], []
        t = self._start_search(node, errs, done)
        task_id = self._wait_for_task(node)
        listed = node.tasks.list_tasks(actions="*search*")
        entry = listed["nodes"][node.node_id]["tasks"][task_id]
        assert entry["action"] == "indices:data/read/search"
        assert entry["cancellable"] is True
        node.tasks.cancel(task_id, "test cancel")
        t.join(timeout=10)
        assert not t.is_alive()
        assert done == [], "cancelled search returned a response"
        assert isinstance(errs[0], TaskCancelledException)
        assert "test cancel" in errs[0].reason
        # the finished task is unregistered
        assert not node.tasks.list_tasks(
            actions="*search*")["nodes"][node.node_id]["tasks"]

    def test_cancel_via_rest(self, node):
        from elasticsearch_tpu.rest.controller import RestController

        rc = RestController(node)
        SearchDelayScheme(0.15, indices=["cx"]).install()
        errs, done = [], []
        t = self._start_search(node, errs, done)
        task_id = self._wait_for_task(node)
        status, payload = rc.dispatch(
            "POST", f"/_tasks/{task_id}/_cancel", {}, b"")
        assert status == 200
        assert task_id in payload["nodes"][node.node_id]["tasks"]
        t.join(timeout=10)
        assert isinstance(errs[0], TaskCancelledException)
        # the cancellation error serializes cleanly for REST callers
        assert errs[0].to_dict()["error"]["type"] == "task_cancelled_exception"

    def test_uncancelled_search_unaffected(self, node):
        r = node.search("cx", {"query": {"match": {"body": "w1"}},
                               "size": 30})
        assert r["hits"]["total"] == 30
        assert r["timed_out"] is False


class TestPlaneQuarantine:
    """Tentpole (3): a plane fault (compile error / OOM / injected)
    quarantines the plane for the cooldown, serves from the next rung,
    and probes recovery after the cooldown; counters export in _stats."""

    def _mk(self, name, cooldown="1500ms"):
        idx = make_index(name, shards=3, mesh=True, extra={
            "index.search.plane_quarantine.cooldown": cooldown})
        # pre-warm the host fallback compile so the post-fault assertions
        # don't race the cooldown window (profile no longer forces the
        # host path — ISSUE 8 — so pin it explicitly)
        idx._search_uncached({"query": {"match": {"body": "w1"}},
                              "size": 5}, skip_mesh=True)
        return idx

    def test_mesh_fault_quarantines_then_recovers(self):
        idx = self._mk("pqmesh")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        assert idx.search(dict(body))["_plane"] == "mesh"
        scheme = PlaneFailScheme(planes=("mesh",),
                                 indices=["pqmesh"]).install()
        t_fault = time.monotonic()
        r = idx.search(dict(body))
        assert r["_plane"] == "host", "fault must fall to the next rung"
        assert r["hits"]["total"] == 30
        planes = idx.stats()["total"]["search"]["planes"]
        assert planes["plane_failures_total"]["mesh"] == 1
        assert planes["plane_quarantined"] == ["mesh"]
        scheme.remove()
        # still benched inside the cooldown
        r = idx.search(dict(body, size=6))
        assert r["_plane"] == "host"
        assert idx.stats()["total"]["search"]["planes"][
            "plane_failures_total"]["mesh"] == 1, "no re-paid failure"
        time.sleep(max(0.0, t_fault + 1.6 - time.monotonic()))
        r = idx.search(dict(body, size=7))
        assert r["_plane"] == "mesh", "plane must recover after cooldown"
        assert idx.stats()["total"]["search"]["planes"][
            "plane_quarantined"] == []
        idx.close()

    def test_pallas_fault_serves_from_mesh_rung(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = self._mk("pqpal")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        PlaneFailScheme(planes=("mesh_pallas",),
                        indices=["pqpal"]).install()
        r = idx.search(dict(body))
        # same query, same ladder walk: the scatter mesh serves it
        assert r["_plane"] == "mesh"
        assert r["hits"]["total"] == 30
        planes = idx.stats()["total"]["search"]["planes"]
        assert planes["plane_failures_total"]["mesh_pallas"] == 1
        assert planes["plane_quarantined"] == ["mesh_pallas"]
        idx.close()

    def test_pallas_pref_quarantine_skips_scatter(self, monkeypatch):
        # index.search.mesh.plane=pallas pins "kernel or host": a
        # quarantined kernel must fall to the HOST rung, never to the
        # scatter mesh the operator excluded
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("pqpin", shards=3, mesh=True, extra={
            "index.search.mesh.plane": "pallas",
            "index.search.plane_quarantine.cooldown": "60s"})
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        PlaneFailScheme(planes=("mesh_pallas",),
                        indices=["pqpin"]).install()
        r = idx.search(dict(body))
        assert r["_plane"] == "host", r["_plane"]
        assert r["hits"]["total"] == 30
        clear_search_disruptions()
        r = idx.search(dict(body, size=6))  # still benched: host again
        assert r["_plane"] == "host"
        idx.close()

    def test_pallas_recovers_after_cooldown(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = self._mk("pqpal2", cooldown="300ms")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        idx.search(dict(body))  # stage + compile both mesh planes
        scheme = PlaneFailScheme(planes=("mesh_pallas",),
                                 indices=["pqpal2"]).install()
        t_fault = time.monotonic()
        assert idx.search(dict(body))["_plane"] == "mesh"
        scheme.remove()
        time.sleep(max(0.0, t_fault + 0.4 - time.monotonic()))
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        idx.close()


class TestMultinodeFanout:
    """Tentpole (1b): the clustered scatter-gather isolates per-shard
    query failures the same way (failures[] + partial, failover across
    copies first)."""

    def test_remote_shard_failure_degrades(self):
        from elasticsearch_tpu.cluster.multinode import (
            ClusterClient,
            ClusterNode,
        )
        from elasticsearch_tpu.transport.local import TransportHub

        hub = TransportHub()
        nodes = [ClusterNode(f"node-{i}", hub) for i in range(2)]
        nodes[0].bootstrap_cluster()
        nodes[1].join("node-0")
        client = ClusterClient(nodes[0])
        nodes[0].create_index("mn", {"index": {"number_of_shards": 2,
                                               "number_of_replicas": 0}})
        for i in range(20):
            client.index("mn", str(i), {"n": i})
        client.refresh("mn")
        baseline = client.search("mn", {"size": 20})
        assert baseline["_shards"]["failed"] == 0
        SearchFailScheme(indices=["mn"], shards=[0]).install()
        r = client.search("mn", {"size": 20})
        assert r["_shards"]["failed"] == 1
        assert r["_shards"]["failures"][0]["shard"] == 0
        assert 0 < r["hits"]["total"] < baseline["hits"]["total"]
        with pytest.raises(SearchPhaseExecutionException):
            client.search("mn", {"size": 20,
                                 "allow_partial_search_results": False})
        for n in nodes:
            n.close()

    def test_default_allow_partial_setting_applies(self):
        from elasticsearch_tpu.cluster.multinode import (
            ClusterClient,
            ClusterNode,
        )
        from elasticsearch_tpu.transport.local import TransportHub

        hub = TransportHub()
        node = ClusterNode("node-0", hub, settings=Settings(
            {"search.default_allow_partial_results": False}))
        node.bootstrap_cluster()
        client = ClusterClient(node)
        node.create_index("mns", {"index": {"number_of_shards": 2,
                                            "number_of_replicas": 0}})
        for i in range(8):
            client.index("mns", str(i), {"n": i})
        client.refresh("mns")
        SearchFailScheme(indices=["mns"], shards=[0]).install()
        with pytest.raises(SearchPhaseExecutionException):
            client.search("mns", {"size": 20})
        # an explicit request-level true overrides the strict default
        r = client.search("mns", {"size": 20,
                                  "allow_partial_search_results": True})
        assert r["_shards"]["failed"] == 1
        node.close()
