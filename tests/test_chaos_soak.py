"""Deterministic chaos soak (ISSUE 10 acceptance).

All three scheme families — transport (PR 2), search/plane (PR 4), and
device staging/launch (this issue) — active simultaneously under
concurrent bulk-ingest + zipfian search on a packed multi-shard corpus,
asserting the standing invariants every round: no acked-write loss,
hits byte-identical to an undisrupted oracle, ledger leak-free,
restage amplification bounded, zero 5xx while any copy survives.

Fast seeded smoke in tier-1; the full soak is slow-marked.
"""

import pytest

from elasticsearch_tpu.testing.chaos import ChaosSoak

SMOKE_SEED = 1007


class TestChaosSoakSmoke:
    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")

    def test_schedule_is_deterministic_under_pinned_seed(self):
        a = ChaosSoak(seed=SMOKE_SEED, rounds=4).schedule()
        b = ChaosSoak(seed=SMOKE_SEED, rounds=4).schedule()
        assert a == b
        assert ChaosSoak(seed=SMOKE_SEED + 1, rounds=4).schedule() != a \
            or True  # different seeds may coincide; determinism is the claim
        # every round composes at least one device/search scheme plus
        # the PR-4 search-delay family
        assert all("search_delay" in r for r in a)

    def test_smoke_all_families(self):
        soak = ChaosSoak(seed=SMOKE_SEED, rounds=2, docs_per_round=18,
                         searches_per_round=5, search_threads=2,
                         shards=3, seed_docs=36, with_cluster=True,
                         index="chaos_smoke")
        report = soak.run()
        # faults actually bit: at least one scheme fired somewhere
        assert sum(report["scheme_hits"].values()) >= 1, report
        assert report["acked_writes"] == 2 * 18
        assert report["searches_under_fault"] == 2 * 2 * 5
        assert report["search_errors"] == []
        assert report["parity_checked"] >= 8
        # the fast plane served at least part of the traffic and the
        # soak ended back on it (asserted inside run — planes_seen is
        # the observability breadcrumb)
        assert "mesh_pallas" in report["planes_seen"], report
        # transport side: every acked write visible, none lost
        assert report["cluster"] is not None
        assert report["cluster"]["visible"] == report["cluster"]["acked"]
        amp = report["restage_amplification"]
        assert amp is None or amp < soak.amplification_bound
        # overload leg (ISSUE 12): under pinned queue pressure +
        # transport faults, every offered query ended in a complete
        # answer or a clean 429 — rejected == offered − admitted with
        # exact counters (no silent drops), asserted inside run; the
        # report carries the accounting breadcrumb
        ov = report["overload"]
        assert ov is not None, report
        assert ov["rejected"] >= 1
        assert ov["rejected"] == ov["offered"] - ov["admitted"]


class TestCorruptionSoakSmoke:
    """Data-integrity corruption phase (ISSUE 16 acceptance): every
    injected corruption is detected (zero silent wrong results), a
    corrupt replica re-recovers from the primary, a corrupt primary
    fails over to the STARTED replica and rebuilds, in-flight recovery
    corruption is caught by the manifest-digest check and retried, and
    the device-memory ledger stays leak-free through every quarantine."""

    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")

    def test_corruption_phase(self, tmp_path):
        soak = ChaosSoak(seed=SMOKE_SEED, shards=3, seed_docs=24,
                         index="chaos_int")
        report = soak.run_corruption(str(tmp_path))
        # every injection was counted as a detection somewhere
        assert report["injected"] >= 4, report
        assert report["detected"] >= report["injected"], report
        local = report["local"]
        assert local["at_rest"]["scrub"]["checksum_failures"] >= 1
        assert local["at_rest"]["failed_shards"] >= 1
        assert local["drift"]["scrub"]["drift"] >= 1
        scenarios = {s["scenario"]: s
                     for s in report["cluster"]["scenarios"]}
        assert scenarios["corrupt_replica"]["by_site"]["load"] >= 1
        assert scenarios["corrupt_replica"]["cleared"] >= 1
        assert scenarios["corrupt_primary"]["by_site"]["load"] >= 1
        assert scenarios["recovery_in_flight"]["by_site"]["recovery"] >= 1


@pytest.mark.slow
class TestChaosSoakFull:
    def test_full_soak(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        soak = ChaosSoak(seed=2024, rounds=5, docs_per_round=40,
                         searches_per_round=10, search_threads=3,
                         shards=4, seed_docs=80, with_cluster=True,
                         cluster_drop_p=0.3, index="chaos_full")
        report = soak.run()
        assert report["search_errors"] == []
        assert report["cluster"]["visible"] == report["cluster"]["acked"]
        assert "mesh_pallas" in report["planes_seen"]
