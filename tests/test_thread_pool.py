"""Named bounded executors + backpressure (ThreadPool.java:67-77,
EsRejectedExecutionException -> HTTP 429)."""

import threading
import time

import pytest

from elasticsearch_tpu.common.thread_pool import (
    EsRejectedExecutionException,
    ThreadPool,
)


class TestThreadPool:
    def test_submit_runs_and_returns(self):
        tp = ThreadPool(cores=2)
        try:
            assert tp.run("search", lambda: 41 + 1) == 42
        finally:
            tp.shutdown()

    def test_exceptions_propagate(self):
        tp = ThreadPool(cores=2)
        try:
            with pytest.raises(ValueError, match="boom"):
                tp.run("write", lambda: (_ for _ in ()).throw(
                    ValueError("boom")))
        finally:
            tp.shutdown()

    def test_bounded_queue_rejects(self):
        tp = ThreadPool(cores=1, overrides={
            "tiny": {"threads": 1, "queue_size": 2}})
        try:
            gate = threading.Event()
            futures = [tp.submit("tiny", gate.wait)]
            # wait until the single worker picked the task up...
            deadline = time.monotonic() + 2
            while (tp.executor("tiny").stats().active == 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            # ...then fill the 2-slot queue; the next submit must reject
            futures += [tp.submit("tiny", gate.wait) for _ in range(2)]
            with pytest.raises(EsRejectedExecutionException):
                tp.submit("tiny", gate.wait)
            st = tp.executor("tiny").stats()
            assert st.rejected >= 1
            gate.set()
            for f in futures:
                f.result(5)
        finally:
            tp.shutdown()

    def test_stats_shape(self):
        tp = ThreadPool(cores=2)
        try:
            tp.run("get", lambda: None)
            st = tp.stats()
            assert {"search", "write", "get", "management",
                    "generic"} <= set(st)
            assert st["get"]["completed"] >= 1
            for pool in st.values():
                assert {"threads", "queue_size", "active", "queue",
                        "rejected", "completed"} <= set(pool)
        finally:
            tp.shutdown()

    def test_unknown_pool_falls_back_to_generic(self):
        tp = ThreadPool(cores=2)
        try:
            assert tp.run("no-such-pool", lambda: "ok") == "ok"
            assert tp.executor("generic").stats().completed >= 1
        finally:
            tp.shutdown()


class TestRestBackpressure:
    def test_search_overload_returns_429(self, monkeypatch):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("idx")
        node.index_doc("idx", "1", {"f": "v"}, refresh=True)
        # shrink the search pool so overload is cheap to produce
        from elasticsearch_tpu.common.thread_pool import ThreadPool

        node.thread_pool.shutdown()
        node.thread_pool = ThreadPool(cores=1, overrides={
            "search": {"threads": 1, "queue_size": 1}})
        from elasticsearch_tpu.rest.controller import RestController

        ctrl = RestController(node)

        gate = threading.Event()
        started = threading.Event()

        def slow_search():
            started.set()
            gate.wait(10)
            return ctrl_result[0]

        # occupy the single search thread
        blocker = node.thread_pool.submit("search", slow_search)
        ctrl_result = [None]
        started.wait(5)
        node.thread_pool.submit("search", lambda: None)  # fills the queue
        status, body = ctrl.dispatch(
            "GET", "/idx/_search", {}, None)
        gate.set()
        blocker.result(10)
        assert status == 429
        assert body["error"]["type"] == "es_rejected_execution_exception"

    def test_thread_pool_stats_in_node_stats(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        st = node.node_stats()
        pools = st["nodes"][node.node_id]["thread_pool"]
        assert "search" in pools and "write" in pools
