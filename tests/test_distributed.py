"""Distributed execution tests on the 8-device virtual CPU mesh.

The reference tests multi-node behavior with InternalTestCluster (many
nodes in one JVM); we test the mesh data plane with many virtual devices in
one process (SURVEY.md §4.6.3) — the sharding/collective code paths are
identical to real multi-chip TPU.
"""

import numpy as np
import pytest

import jax

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.mapper.mapping import MapperService
from elasticsearch_tpu.parallel.distributed import DistributedSearcher
from elasticsearch_tpu.parallel.mesh import shard_mesh

import golden


def build_sharded_corpus(n_shards, docs_per_shard, seed=0):
    """Returns (segments, all_docs_tokens, doc_locator)."""
    rng = np.random.RandomState(seed)
    vocab = [f"w{i}" for i in range(30)]
    svc = MapperService(
        AnalysisRegistry(),
        {"properties": {"body": {"type": "text", "analyzer": "whitespace"}}},
    )
    segments = []
    all_docs = []
    locator = []  # global index -> (shard, local)
    for s in range(n_shards):
        b = SegmentBuilder(f"shard{s}")
        for d in range(docs_per_shard):
            toks = [vocab[rng.randint(len(vocab))] for _ in range(rng.randint(1, 20))]
            b.add_document(
                svc.parse_document(f"{s}-{d}", {"body": " ".join(toks)}), d
            )
            all_docs.append(toks)
            locator.append((s, d))
        segments.append(b.seal())
    return segments, all_docs, locator


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return shard_mesh(8)


class TestDistributedSearch:
    def test_matches_single_node_golden(self, mesh8):
        segments, all_docs, locator = build_sharded_corpus(8, 40)
        searcher = DistributedSearcher(segments, mesh8)
        terms = ["w0", "w1", "w2"]
        scores, shards, docs, total = searcher.search("body", terms, k=10)

        # golden: score ALL docs as one corpus — DFS mode makes the
        # distributed scores identical to a single-shard index
        ref_scores, ref_matched = golden.score_corpus(all_docs, terms)
        assert total == len(ref_matched)
        ref_top = golden.top_k(ref_scores, 10)
        got = [
            (int(s_id), int(d), float(sc))
            for sc, s_id, d in zip(scores, shards, docs)
            if sc > -np.inf
        ]
        assert len(got) == len(ref_top)
        for (shard_id, local_doc, score), (ref_doc, ref_score) in zip(got, ref_top):
            assert score == pytest.approx(ref_score, rel=1e-5)
        # exact same global doc set
        got_globals = {
            locator.index((sh, d)) for sh, d, _ in got
        }
        assert got_globals == {d for d, _ in ref_top}

    def test_uneven_shards(self, mesh8):
        # shards of very different sizes stack + score correctly
        segments, all_docs, locator = build_sharded_corpus(3, 5)
        big_segments, big_docs, big_loc = build_sharded_corpus(1, 300, seed=9)
        segments.append(big_segments[0])
        offset = len(all_docs)
        all_docs.extend(big_docs)
        locator.extend((3, d) for _, d in big_loc)
        searcher = DistributedSearcher(segments, shard_mesh(8))
        scores, shards, docs, total = searcher.search("body", ["w5"], k=5)
        ref_scores, ref_matched = golden.score_corpus(all_docs, ["w5"])
        assert total == len(ref_matched)
        ref_top = golden.top_k(ref_scores, 5)
        got_scores = [float(s) for s in scores if s > -np.inf]
        for got_s, (_, ref_s) in zip(got_scores, ref_top):
            assert got_s == pytest.approx(ref_s, rel=1e-5)

    def test_program_reuse_across_queries(self, mesh8):
        segments, _, _ = build_sharded_corpus(8, 20)
        searcher = DistributedSearcher(segments, mesh8)
        searcher.search("body", ["w1"], k=5)
        n_programs = len(searcher._programs)
        searcher.search("body", ["w2"], k=5)  # same shapes -> same program
        assert len(searcher._programs) == n_programs


class TestMeshHelpers:
    def test_shard_mesh_axis(self, mesh8):
        assert mesh8.axis_names == ("shards",)
        assert mesh8.devices.size == 8

    def test_shard_replica_mesh(self):
        from elasticsearch_tpu.parallel.mesh import shard_replica_mesh

        m = shard_replica_mesh(4, 2)
        assert m.axis_names == ("shards", "replicas")
        assert m.devices.shape == (4, 2)
