"""Span query family (ref: index/query/Span*QueryBuilder)."""

import pytest

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def hit_ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


@pytest.fixture()
def idx():
    idx = IndexService("spans", Settings({"index.number_of_shards": 1}))
    docs = {
        "1": "the quick brown fox jumps over the lazy dog",
        "2": "the brown quick fox sleeps",
        "3": "quick thinking saved the brown bear",
        "4": "a fox and a dog",
    }
    for i, text in docs.items():
        idx.index_doc(i, {"body": text})
    idx.refresh()
    yield idx
    idx.close()


class TestSpanTerm:
    def test_span_term(self, idx):
        resp = idx.search({"query": {"span_term": {"body": "fox"}}})
        assert hit_ids(resp) == ["1", "2", "4"]

    def test_span_term_scores_like_term(self, idx):
        resp = idx.search({"query": {"span_term": {"body": "fox"}}})
        assert all(h["_score"] > 0 for h in resp["hits"]["hits"])


class TestSpanNear:
    def test_in_order_adjacent(self, idx):
        resp = idx.search({"query": {"span_near": {
            "clauses": [{"span_term": {"body": "quick"}},
                        {"span_term": {"body": "brown"}}],
            "slop": 0, "in_order": True}}})
        assert hit_ids(resp) == ["1"]

    def test_unordered(self, idx):
        resp = idx.search({"query": {"span_near": {
            "clauses": [{"span_term": {"body": "quick"}},
                        {"span_term": {"body": "brown"}}],
            "slop": 0, "in_order": False}}})
        assert hit_ids(resp) == ["1", "2"]

    def test_slop(self, idx):
        # doc 3: "quick thinking saved the brown" — gap of 3
        resp = idx.search({"query": {"span_near": {
            "clauses": [{"span_term": {"body": "quick"}},
                        {"span_term": {"body": "brown"}}],
            "slop": 3, "in_order": True}}})
        assert hit_ids(resp) == ["1", "3"]


class TestSpanFirst:
    def test_span_first(self, idx):
        # "quick" within the first 2 positions: doc 3 (pos 0); doc 1 has pos 1
        resp = idx.search({"query": {"span_first": {
            "match": {"span_term": {"body": "quick"}}, "end": 2}}})
        assert hit_ids(resp) == ["1", "3"]
        resp = idx.search({"query": {"span_first": {
            "match": {"span_term": {"body": "quick"}}, "end": 1}}})
        assert hit_ids(resp) == ["3"]


class TestSpanOrNot:
    def test_span_or(self, idx):
        resp = idx.search({"query": {"span_or": {
            "clauses": [{"span_term": {"body": "bear"}},
                        {"span_term": {"body": "dog"}}]}}})
        assert hit_ids(resp) == ["1", "3", "4"]

    def test_span_not(self, idx):
        # fox not immediately preceded by brown: doc2 "quick fox" wait —
        # doc1 "brown fox", doc2 "quick fox", doc4 "a fox"
        resp = idx.search({"query": {"span_not": {
            "include": {"span_term": {"body": "fox"}},
            "exclude": {"span_term": {"body": "brown"}},
            "pre": 1}}})
        assert hit_ids(resp) == ["2", "4"]


class TestSpanContainingWithin:
    def test_span_containing(self, idx):
        big = {"span_near": {"clauses": [{"span_term": {"body": "quick"}},
                                         {"span_term": {"body": "fox"}}],
                             "slop": 1, "in_order": True}}
        resp = idx.search({"query": {"span_containing": {
            "little": {"span_term": {"body": "brown"}}, "big": big}}})
        assert hit_ids(resp) == ["1"]

    def test_span_within(self, idx):
        big = {"span_near": {"clauses": [{"span_term": {"body": "quick"}},
                                         {"span_term": {"body": "fox"}}],
                             "slop": 1, "in_order": True}}
        resp = idx.search({"query": {"span_within": {
            "little": {"span_term": {"body": "brown"}}, "big": big}}})
        assert hit_ids(resp) == ["1"]


class TestSpanMulti:
    def test_span_multi_prefix(self, idx):
        resp = idx.search({"query": {"span_near": {
            "clauses": [
                {"span_multi": {"match": {"prefix": {"body": "qui"}}}},
                {"span_term": {"body": "brown"}},
            ], "slop": 0, "in_order": True}}})
        assert hit_ids(resp) == ["1"]

    def test_span_multi_rejects_match(self, idx):
        with pytest.raises(ParsingException):
            idx.search({"query": {"span_multi": {
                "match": {"match": {"body": "quick"}}}}})


class TestSpanCompose:
    def test_span_inside_bool(self, idx):
        resp = idx.search({"query": {"bool": {
            "must": [{"span_near": {
                "clauses": [{"span_term": {"body": "quick"}},
                            {"span_term": {"body": "brown"}}],
                "slop": 0, "in_order": True}}],
            "must_not": [{"term": {"body": "bear"}}]}}})
        assert hit_ids(resp) == ["1"]

    def test_non_span_in_clauses_rejected(self, idx):
        with pytest.raises(ParsingException):
            idx.search({"query": {"span_near": {
                "clauses": [{"term": {"body": "quick"}}], "slop": 0}}})
