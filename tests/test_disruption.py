"""Disruption harness + transport resilience tests.

Role models: the reference's disruption ITs
(test/framework/.../test/disruption/NetworkDisruption.java,
core/src/test/.../discovery/DiscoveryWithServiceDisruptionsIT.java):
every coordination path — publish, master failover, replica recovery,
replication fan-out — driven through injectable delay/drop/partition/
unresponsive schemes, asserting convergence and no stale writes.

Fast smoke subset runs in tier-1; the full 30%-drop + 200ms-delay
convergence scenarios are marked ``slow``.
"""

import threading
import time

import pytest

from elasticsearch_tpu.cluster.multinode import (
    ACTION_WRITE_PRIMARY,
    ACTION_WRITE_REPLICA,
    ClusterClient,
    ClusterNode,
)
from elasticsearch_tpu.cluster.state import ShardRoutingState
from elasticsearch_tpu.common.errors import (
    ConnectTransportException,
    NodeNotConnectedException,
    ReceiveTimeoutTransportException,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.shard import ShardNotPrimaryException
from elasticsearch_tpu.testing.disruption import (
    ActionBlackhole,
    DisruptionScheme,
    NetworkDelay,
    NetworkDrop,
    NetworkPartition,
    UnresponsiveNode,
)
from elasticsearch_tpu.transport.local import (
    ConnectionHealth,
    RetryPolicy,
    TransportHub,
    TransportService,
)

# tight deadlines/backoffs so fault paths resolve in test time
FAST = Settings({
    "transport.request.timeout": "3s",
    "transport.retry.max_attempts": 4,
    "transport.retry.initial_backoff": "20ms",
    "transport.retry.max_backoff": "200ms",
    "transport.health.failure_threshold": 3,
    "transport.health.quarantine": "300ms",
    "discovery.zen.fd.ping_timeout": "500ms",
    "discovery.zen.fd.ping_retries": 3,
    "discovery.zen.publish_timeout": "2s",
    "cluster.replication.timeout": "600ms",
    "indices.recovery.retry_delay_network": "20ms",
    "indices.recovery.max_retries": 4,
    "indices.recovery.internal_action_timeout": "2s",
})


def cluster(names=("n1", "n2", "n3"), settings=FAST):
    hub = TransportHub(strict_serialization=True)
    nodes = {n: ClusterNode(n, hub, settings=settings) for n in names}
    nodes[names[0]].bootstrap_cluster()
    for n in names[1:]:
        nodes[n].join(names[0])
    return hub, nodes


def converge(nodes, attempts=40):
    """Drive FD/election ticks until every node agrees on one live
    master and state version; returns the master id."""
    for _ in range(attempts):
        for node in nodes.values():
            try:
                if node.is_master:
                    node.check_nodes()
                else:
                    node.check_master()
            except Exception:  # noqa: BLE001 — disruption may still bite
                pass
        masters = {n.master_id for n in nodes.values()}
        versions = {n.state_version for n in nodes.values()}
        if len(masters) == 1 and None not in masters and len(versions) == 1:
            return masters.pop()
        time.sleep(0.05)
    raise AssertionError(
        f"cluster did not converge: masters="
        f"{ {n.node_id: n.master_id for n in nodes.values()} } versions="
        f"{ {n.node_id: n.state_version for n in nodes.values()} }")


def wait_started(nodes, index, attempts=80):
    """Reroute/tick until every copy of every shard is STARTED."""
    master = next((n for n in nodes.values() if n.is_master), None)
    for _ in range(attempts):
        master = next((n for n in nodes.values() if n.is_master), master)
        try:
            master.reroute()
        except Exception:  # noqa: BLE001
            pass
        routing = master.routing.get(index, {})
        copies = [c for copies in routing.values() for c in copies]
        if copies and all(c.state == ShardRoutingState.STARTED
                          for c in copies):
            return
        time.sleep(0.05)
    raise AssertionError(f"shards of [{index}] never all STARTED")


class DropFirstN(DisruptionScheme):
    """Deterministic transient fault: drop the first N matching
    deliveries, then pass everything."""

    def __init__(self, n: int, **filters):
        super().__init__(**filters)
        self.remaining = n
        self._lock = threading.Lock()

    def disrupt(self, src, dst, action):
        with self._lock:
            if self.remaining <= 0:
                return
            self.remaining -= 1
        raise NodeNotConnectedException(f"dropped [{action}] (injected)")


class TestRetryPolicy:
    def test_backoff_sequence_and_cap(self):
        p = RetryPolicy(max_attempts=5, initial_backoff=0.1,
                        backoff_multiplier=2.0, max_backoff=0.5)
        assert [p.backoff(i) for i in range(4)] == [0.1, 0.2, 0.4, 0.5]

    def test_classification(self):
        p = RetryPolicy()
        assert p.is_retryable(NodeNotConnectedException("x"))
        assert p.is_retryable(ReceiveTimeoutTransportException("x"))
        from elasticsearch_tpu.transport.local import RemoteActionException

        assert not p.is_retryable(RemoteActionException("handler blew up"))
        assert not p.is_retryable(ValueError("x"))
        # fast-fails never hit the wire; retrying them in-place just
        # spins on the quarantine window
        assert not p.is_retryable(ConnectTransportException("x"))


class TestTransportResilience:
    def _pair(self):
        hub = TransportHub()
        a = TransportService("a", hub)
        b = TransportService("b", hub)
        b.register_handler("act", lambda payload, src: {"ok": True})
        return hub, a, b

    def test_transient_drop_retried_and_counted(self):
        hub, a, b = self._pair()
        DropFirstN(2, actions=["act"]).apply_to(hub)
        resp = a.send_request("b", "act", {}, retry=RetryPolicy(
            max_attempts=4, initial_backoff=0.01))
        assert resp == {"ok": True}
        assert a.stats["retries"] == 2
        assert a.stats["failures"] == 2

    def test_retry_exhaustion_raises_last_error(self):
        hub, a, b = self._pair()
        DropFirstN(10, actions=["act"]).apply_to(hub)
        with pytest.raises(NodeNotConnectedException):
            a.send_request("b", "act", {}, retry=RetryPolicy(
                max_attempts=3, initial_backoff=0.01))
        assert a.stats["failures"] == 3

    def test_timeout_on_unresponsive_node(self):
        hub, a, b = self._pair()
        scheme = UnresponsiveNode("b", max_block_s=5).apply_to(hub)
        t0 = time.monotonic()
        with pytest.raises(ReceiveTimeoutTransportException):
            a.send_request("b", "act", {}, timeout=0.2)
        assert time.monotonic() - t0 < 2.0
        assert a.stats["timeouts"] == 1
        scheme.remove()
        assert a.send_request("b", "act", {}) == {"ok": True}

    def test_fast_fail_after_threshold_and_heal(self):
        hub = TransportHub()
        a = TransportService("a", hub, health=ConnectionHealth(
            failure_threshold=3, quarantine_s=30.0))
        b = TransportService("b", hub)
        b.register_handler("act", lambda payload, src: {"ok": True})
        hub.disconnect("a", "b")
        for _ in range(3):
            with pytest.raises(NodeNotConnectedException):
                a.send_request("b", "act", {})
        wire_before = len(hub.requests_log)
        with pytest.raises(ConnectTransportException):
            a.send_request("b", "act", {})
        assert len(hub.requests_log) == wire_before  # never hit the wire
        assert a.stats["fast_fails"] == 1
        hub.heal()  # resets health: usable immediately
        assert a.send_request("b", "act", {}) == {"ok": True}

    def test_one_way_partition(self):
        hub, a, b = self._pair()
        a.register_handler("act", lambda payload, src: {"ok": "a"})
        NetworkPartition(["a"], ["b"], one_way=True).apply_to(hub)
        with pytest.raises(NodeNotConnectedException):
            a.send_request("b", "act", {})
        assert b.send_request("a", "act", {}) == {"ok": "a"}

    def test_delay_scheme_applies(self):
        hub, a, b = self._pair()
        NetworkDelay(0.15, dst=["b"]).apply_to(hub)
        t0 = time.monotonic()
        assert a.send_request("b", "act", {}) == {"ok": True}
        assert time.monotonic() - t0 >= 0.15

    def test_drop_scheme_is_seeded_deterministic(self):
        d1 = NetworkDrop(0.5, seed=42)
        d2 = NetworkDrop(0.5, seed=42)

        def run(d):
            out = []
            for _ in range(20):
                try:
                    d.disrupt("a", "b", "act")
                    out.append(False)
                except NodeNotConnectedException:
                    out.append(True)
            return out

        assert run(d1) == run(d2)
        assert any(run(NetworkDrop(0.5, seed=1)))


class TestAdaptiveSelectionPenalty:
    def test_failure_penalizes_rank_success_recovers(self):
        from elasticsearch_tpu.cluster.response_collector import (
            ResponseCollectorService,
        )

        rc = ResponseCollectorService()
        rc.add_response_time("good", 0.01)
        rc.add_response_time("flaky", 0.01)
        rc.on_failure("flaky", 0.6)  # timed out
        assert rc.rank("flaky") > rc.rank("good")
        rc.on_failure("flaky", 0.0)  # instant connect error: still worse
        assert rc.rank("flaky") > rc.rank("good")
        for _ in range(30):  # sustained successes recover the rank
            rc.add_response_time("flaky", 0.01)
        assert rc.rank("flaky") < 0.05

    def test_reads_reroute_away_from_unresponsive_replica(self):
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "ars", {"index": {"number_of_shards": 1,
                              "number_of_replicas": 1}},
            {"properties": {"msg": {"type": "text"}}})
        wait_started(nodes, "ars")
        primary = nodes["n1"]._primary_node("ars", 0)
        other = "n2" if primary == "n1" else "n1"
        client = ClusterClient(nodes[primary])
        client.index("ars", "1", {"msg": "x"})
        client.refresh("ars")
        # reads from the coordinator on `primary` may route to `other`;
        # once `other` goes unresponsive the GET fails over and the
        # penalty keeps later reads off it
        scheme = UnresponsiveNode(other, max_block_s=10).apply_to(hub)
        try:
            r = client.get("ars", "1", prefer_replica=True)
            assert r["found"]
            assert client.response_collector.rank(other) > \
                client.response_collector.rank(primary)
        finally:
            scheme.remove()


class TestClusterSmoke:
    """Fast tier-1 smoke: coordination paths under light injected faults."""

    def test_publish_and_write_survive_transient_drops(self):
        hub, nodes = cluster()
        DropFirstN(1, actions=["internal:cluster/coordination/*"]
                   ).apply_to(hub)
        nodes["n1"].create_index(
            "logs", {"index": {"number_of_shards": 2,
                               "number_of_replicas": 1}},
            {"properties": {"msg": {"type": "text"}}})
        client = ClusterClient(nodes["n1"])
        for i in range(6):
            client.index("logs", str(i), {"msg": f"event {i}"})
        client.refresh("logs")
        res = client.search("logs", {"query": {"match": {"msg": "event"}},
                                     "size": 20})
        assert res["hits"]["total"] == 6
        assert nodes["n1"].transport.stats["retries"] >= 1

    def test_unresponsive_master_detected_and_replaced(self):
        hub, nodes = cluster()
        scheme = UnresponsiveNode("n1", max_block_s=5).apply_to(hub)
        try:
            assert nodes["n2"].check_master() == "n2"
            assert nodes["n2"].is_master
            assert nodes["n2"].transport.stats["timeouts"] >= 1
        finally:
            scheme.remove()

    def test_blackholed_replica_failed_without_blocking_primary(self):
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "k", {"index": {"number_of_shards": 1,
                            "number_of_replicas": 1}},
            {"properties": {"msg": {"type": "text"}}})
        wait_started(nodes, "k")
        primary = nodes["n1"]._primary_node("k", 0)
        replica = "n2" if primary == "n1" else "n1"
        scheme = ActionBlackhole([ACTION_WRITE_REPLICA], max_block_s=30,
                                 dst=[replica]).apply_to(hub)
        try:
            client = ClusterClient(nodes[primary])
            t0 = time.monotonic()
            r = client.index("k", "1", {"msg": "served"})
            took = time.monotonic() - t0
            # the primary acked within ~the replication deadline instead
            # of blocking on the blackholed replica...
            assert r["result"] == "created"
            assert took < 10.0
            assert r["_shards"]["failed"] == 1
            assert r["_shards"]["failures"][0]["_node"] == replica
            # ...and the copy was failed + reported to the master, which
            # rerouted (the replica re-initializes and — since only the
            # write action is blackholed — self-heals through recovery,
            # ops replay included)
            from elasticsearch_tpu.cluster.multinode import (
                ACTION_SHARD_FAILED,
            )

            assert any(a == ACTION_SHARD_FAILED
                       for (_s, _d, a) in hub.requests_log) or \
                nodes[primary].is_master  # self-report short-circuits hub
            # primary keeps serving
            r2 = client.index("k", "2", {"msg": "still served"})
            assert r2["result"] == "created"
            # the re-recovered replica holds every acked write (the
            # blackholed fan-out was compensated by recovery ops replay)
            rep_shard = nodes[replica].shards.get(("k", 0))
            if rep_shard is not None and \
                    rep_shard.state == "STARTED":
                rep_shard.refresh()
                assert rep_shard.num_docs >= 1
        finally:
            scheme.remove()

    def test_recovery_retries_chunks_under_drop(self):
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "r", {"index": {"number_of_shards": 1,
                            "number_of_replicas": 0}},
            {"properties": {"msg": {"type": "text"}}})
        client = ClusterClient(nodes["n1"])
        for i in range(20):
            client.index("r", str(i), {"msg": f"doc {i}"})
        primary = nodes["n1"]._primary_node("r", 0)
        nodes[primary].shards[("r", 0)].flush()
        drop = NetworkDrop(0.3, seed=11,
                           actions=["internal:index/shard/recovery/*"]
                           ).apply_to(hub)
        try:
            # bump replicas via metadata mutation + reroute
            def mutate():
                md = nodes["n1"].indices_meta["r"]
                md.settings = md.settings.merged_with(
                    Settings({"index.number_of_replicas": 1}))
            nodes["n1"]._submit_state_update(mutate)
            wait_started(nodes, "r")
        finally:
            drop.remove()
        replica = next(n for n in nodes.values()
                       if n.node_id != primary)
        shard = replica.shards[("r", 0)]
        shard.refresh()
        assert shard.num_docs == 20
        # the retry machinery was actually exercised
        total_retries = sum(n.transport.stats["retries"]
                            for n in nodes.values())
        assert total_retries >= 1

    def test_aborted_file_pull_closes_source_session(self):
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "s", {"index": {"number_of_shards": 1,
                            "number_of_replicas": 0}},
            {"properties": {"msg": {"type": "text"}}})
        client = ClusterClient(nodes["n1"])
        for i in range(10):
            client.index("s", str(i), {"msg": f"doc {i}"})
        primary = nodes["n1"]._primary_node("s", 0)
        nodes[primary].shards[("s", 0)].flush()
        # blackhole ONLY the chunk pulls: the file phase aborts, the
        # close RPC still goes through, and recovery falls back to ops
        # replay — the source must not keep the snapshot session pinned
        scheme = ActionBlackhole(
            ["internal:index/shard/recovery/files/chunk"],
            max_block_s=30).apply_to(hub)
        try:
            def mutate():
                md = nodes["n1"].indices_meta["s"]
                md.settings = md.settings.merged_with(
                    Settings({"index.number_of_replicas": 1}))
            nodes["n1"]._submit_state_update(mutate)
            wait_started(nodes, "s")
        finally:
            scheme.remove()
        assert nodes[primary]._recovery_sessions == {}
        replica = next(n for n in nodes.values() if n.node_id != primary)
        shard = replica.shards[("s", 0)]
        shard.refresh()
        assert shard.num_docs == 10

    def test_fd_tick_republishes_to_lagging_follower(self):
        """A follower that missed a publish (drops ate the phase-1
        retries) must not diverge silently: the master's next FD tick
        sees the stale (epoch, version) in the ping answer and pushes
        the full state."""
        hub, nodes = cluster()
        bh = ActionBlackhole(["internal:cluster/coordination/*"],
                             dst=["n3"], max_block_s=5).apply_to(hub)
        try:
            # quorum is 1 (min_master_nodes default): the publish
            # commits on n1+n2 while n3 misses it entirely
            nodes["n1"].create_index(
                "lag", {"index": {"number_of_shards": 1,
                                  "number_of_replicas": 0}})
        finally:
            bh.remove()
        assert "lag" in nodes["n2"].indices_meta
        assert "lag" not in nodes["n3"].indices_meta  # missed it
        assert nodes["n3"].state_version < nodes["n1"].state_version
        nodes["n1"].check_nodes()  # FD repair tick
        assert nodes["n3"].state_version == nodes["n1"].state_version
        assert "lag" in nodes["n3"].indices_meta

    def test_unreported_replica_failure_fails_the_write(self):
        """If a replica write fails AND the fail-shard report cannot
        reach the master, the write must NOT be acked: an unreported
        diverged copy could be promoted later, losing the op."""
        hub, nodes = cluster(names=("n1", "n2", "n3"))
        # 3 shards over 3 nodes: at least one primary lands off-master,
        # so its fail-shard report really crosses the wire
        nodes["n1"].create_index(
            "ur", {"index": {"number_of_shards": 3,
                             "number_of_replicas": 1}},
            {"properties": {"msg": {"type": "text"}}})
        wait_started(nodes, "ur")
        sid, primary = next(
            (s, nodes["n1"]._primary_node("ur", s)) for s in range(3)
            if nodes["n1"]._primary_node("ur", s) != nodes["n1"].master_id)
        replica = next(c.node_id for c in nodes[primary].routing["ur"][sid]
                       if not c.primary)
        bh_write = ActionBlackhole([ACTION_WRITE_REPLICA], dst=[replica],
                                   max_block_s=30).apply_to(hub)
        from elasticsearch_tpu.cluster.multinode import ACTION_SHARD_FAILED
        bh_report = ActionBlackhole([ACTION_SHARD_FAILED],
                                    max_block_s=30).apply_to(hub)
        try:
            from elasticsearch_tpu.common.errors import (
                ElasticsearchTpuException,
            )

            from elasticsearch_tpu.utils.murmur3 import shard_id_for

            doc_id = next(f"d{i}" for i in range(1000)
                          if shard_id_for(f"d{i}", 3) == sid)
            with pytest.raises(ElasticsearchTpuException,
                               match="not fully replicated"):
                ClusterClient(nodes[primary]).index(
                    "ur", doc_id, {"msg": "must not ack silently"})
        finally:
            bh_write.remove()
            bh_report.remove()

    def test_partial_replica_not_promoted_shard_goes_red(self):
        """An INITIALIZING survivor (recovery never finished) must not
        be promoted to primary, and the shard must not restart as a
        fresh empty primary: it goes RED — writes fail loudly, searches
        report the failed shard."""
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "red", {"index": {"number_of_shards": 1,
                              "number_of_replicas": 1}},
            {"properties": {"msg": {"type": "text"}}})
        wait_started(nodes, "red")
        primary = nodes["n1"]._primary_node("red", 0)
        replica_node = "n2" if primary == "n1" else "n1"
        ClusterClient(nodes[primary]).index("red", "1", {"msg": "kept"})
        # force the replica back to INITIALIZING with recovery unable to
        # complete, then kill the primary's node
        bh = ActionBlackhole(["internal:index/shard/recovery/*"],
                             max_block_s=30).apply_to(hub)
        try:
            master = nodes[nodes["n1"].master_id]

            def demote():
                for c in master.routing["red"][0]:
                    if c.node_id == replica_node:
                        c.state = ShardRoutingState.INITIALIZING
            master._submit_state_update(demote)
            hub.disconnect(primary)
            survivor = nodes[replica_node]
            for _ in range(10):
                try:
                    survivor.check_master()
                    survivor.check_nodes()
                except Exception:  # noqa: BLE001
                    pass
                if survivor.is_master and primary not in \
                        survivor.known_nodes:
                    break
                time.sleep(0.05)
            copies = survivor.routing.get("red", {}).get(0, [])
            # the INITIALIZING survivor was NOT promoted and no fresh
            # empty primary was allocated: the departed primary stays
            # routed on its (dead) node — the shard is RED
            primaries = [c for c in copies if c.primary]
            assert [c.node_id for c in primaries] == [primary]
            from elasticsearch_tpu.common.errors import (
                ElasticsearchTpuException,
            )

            with pytest.raises(ElasticsearchTpuException):
                ClusterClient(survivor).index("red", "2", {"msg": "x"})
            res = ClusterClient(survivor).search(
                "red", {"query": {"match_all": {}}})
            assert res["_shards"]["failed"] >= 1  # loud, not silent
        finally:
            bh.remove()
        # the node comes back: its retained copy resumes WITH its data
        hub.heal()
        nodes[primary].join(replica_node if survivor.is_master
                            else survivor.master_id)
        for _ in range(40):
            try:
                next(n for n in nodes.values() if n.is_master).reroute()
            except Exception:  # noqa: BLE001
                pass
            copies = survivor.routing.get("red", {}).get(0, [])
            if any(c.primary and c.state == ShardRoutingState.STARTED
                   for c in copies):
                break
            time.sleep(0.05)
        client = ClusterClient(survivor)
        client.refresh("red")
        res = client.search("red", {"query": {"match": {"msg": "kept"}}})
        assert res["hits"]["total"] == 1  # resurrection, not empty restart

    def test_stale_term_write_rejected_under_disruption(self):
        """No stale writes: an op routed under a superseded term raises
        ShardNotPrimaryException at the primary's operation permit."""
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "t", {"index": {"number_of_shards": 1,
                            "number_of_replicas": 0}})
        primary = nodes["n1"]._primary_node("t", 0)
        shard = nodes[primary].shards[("t", 0)]
        shard.primary_term = 7  # a promotion happened elsewhere
        with pytest.raises(ShardNotPrimaryException, match="too old"):
            nodes["n1"].transport.send_request(
                primary, ACTION_WRITE_PRIMARY,
                {"op": "index", "index": "t", "shard": 0, "id": "x",
                 "source": {"v": 1}, "routing": None,
                 "wait_for_active_shards": None, "term": 1})


class TestCatRecovery:
    """_cat/recovery (ISSUE 10 satellite): peer-recovery progress per
    shard copy — stage, files/bytes/ops counts, source → target —
    surfaced from the multinode recovery sessions and rendered like the
    other _cat endpoints."""

    def test_peer_recovery_progress_recorded_and_rendered(self):
        from elasticsearch_tpu.cluster.multinode import (
            clear_recovery_progress,
            recovery_progress_rows,
        )

        clear_recovery_progress()
        hub, nodes = cluster(names=("n1", "n2"))
        nodes["n1"].create_index(
            "catrec", {"index": {"number_of_shards": 1,
                                 "number_of_replicas": 0}},
            {"properties": {"msg": {"type": "text"}}})
        client = ClusterClient(nodes["n1"])
        for i in range(15):
            client.index("catrec", str(i), {"msg": f"doc {i}"})
        primary = nodes["n1"]._primary_node("catrec", 0)
        nodes[primary].shards[("catrec", 0)].flush()

        def mutate():
            md = nodes["n1"].indices_meta["catrec"]
            md.settings = md.settings.merged_with(
                Settings({"index.number_of_replicas": 1}))
        nodes["n1"]._submit_state_update(mutate)
        wait_started(nodes, "catrec")
        rows = [r for r in recovery_progress_rows()
                if r["index"] == "catrec"]
        assert rows, "peer recovery left no progress row"
        row = rows[0]
        assert row["stage"] == "done"
        assert row["type"] == "peer"
        assert row["source"] == primary
        assert row["target"] != primary
        # phase1 shipped the committed files; the counters converged
        assert row["files_total"] >= 1
        assert row["files_recovered"] == row["files_total"]
        assert row["bytes_total"] >= 1
        assert row["bytes_recovered"] >= row["bytes_total"]
        assert row["stop_ms"] is not None
        # the REST renderer surfaces the same rows (other _cat idiom)
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        try:
            status, rows_json = Client(node).perform(
                "GET", "/_cat/recovery", params={"format": "json"})
            assert status == 200
            peer = [r for r in rows_json
                    if r["index"] == "catrec" and r["type"] == "peer"]
            assert peer, rows_json
            assert peer[0]["stage"] == "done"
            assert peer[0]["files_percent"] == "100.0%"
            assert peer[0]["bytes_percent"] == "100.0%"
            assert peer[0]["translog_ops_percent"] == "100.0%"
            assert peer[0]["source_node"] == primary
        finally:
            node.close()
        clear_recovery_progress()


@pytest.mark.slow
class TestDisruptionConvergence:
    """The acceptance scenario: 30% drop + 200ms delay on every link.
    Publish, master failover, and replica recovery still converge, with
    retries observable in transport stats."""

    def _disrupted_cluster(self):
        hub, nodes = cluster()
        drop = NetworkDrop(0.3, seed=1234).apply_to(hub)
        delay = NetworkDelay(0.2).apply_to(hub)
        return hub, nodes, drop, delay

    def _retry(self, fn, attempts=30):
        last = None
        for _ in range(attempts):
            try:
                return fn()
            except ShardNotPrimaryException:
                raise  # a fencing rejection is a RESULT, not a fault
            except Exception as e:  # noqa: BLE001 — disruption bites
                last = e
                time.sleep(0.1)
        raise last

    def test_publish_and_recovery_converge_under_drop_delay(self):
        hub, nodes, drop, delay = self._disrupted_cluster()
        try:
            self._retry(lambda: nodes["n1"].create_index(
                "logs", {"index": {"number_of_shards": 2,
                                   "number_of_replicas": 1}},
                {"properties": {"msg": {"type": "text"}}}))
            client = ClusterClient(nodes["n1"])
            for i in range(10):
                self._retry(lambda i=i: client.index(
                    "logs", str(i), {"msg": f"event {i}"}))
            wait_started(nodes, "logs", attempts=240)
            converge(nodes, attempts=120)
        finally:
            drop.remove()
            delay.remove()
        client.refresh("logs")
        res = client.search("logs", {"query": {"match": {"msg": "event"}},
                                     "size": 40})
        assert res["hits"]["total"] == 10
        assert drop.dropped >= 1
        assert sum(n.transport.stats["retries"]
                   for n in nodes.values()) >= 1

    def test_master_failover_converges_under_drop_delay(self):
        hub, nodes, drop, delay = self._disrupted_cluster()
        try:
            self._retry(lambda: nodes["n1"].create_index(
                "logs", {"index": {"number_of_shards": 2,
                                   "number_of_replicas": 1}},
                {"properties": {"msg": {"type": "text"}}}))
            client = ClusterClient(nodes["n1"])
            for i in range(8):
                self._retry(lambda i=i: client.index(
                    "logs", str(i), {"msg": f"event {i}"}))
            wait_started(nodes, "logs", attempts=120)
            old_terms = dict(nodes["n2"].primary_terms)
            hub.disconnect("n1")  # master dies; drop+delay stay active
            survivors = {k: v for k, v in nodes.items() if k != "n1"}
            master = converge(survivors, attempts=80)
            assert master in ("n2", "n3")
            # promoted primaries fence the old term: no stale write can
            # land through a deposed coordinator's routing
            moved = {k for k, t in survivors[master].primary_terms.items()
                     if t > old_terms.get(k, 1)}
            assert moved
            (idx, sid) = next(iter(moved))
            new_primary = next(
                c.node_id for c in survivors[master].routing[idx][sid]
                if c.primary)
            with pytest.raises(ShardNotPrimaryException, match="too old"):
                self._retry(lambda: survivors[master].transport.send_request(
                    new_primary, ACTION_WRITE_PRIMARY,
                    {"op": "index", "index": idx, "shard": sid,
                     "id": "stale", "source": {"msg": "stale"},
                     "routing": None, "wait_for_active_shards": None,
                     "term": old_terms[(idx, sid)]}))
            # no acked write lost across the failover + disruption: the
            # search may see PARTIAL results while drops are still
            # biting (failed shards are reported, not hidden) — retry
            # until a complete refresh+search round succeeds
            survivor_client = ClusterClient(survivors[master])

            def refresh_and_search():
                survivor_client.refresh("logs")
                res = survivor_client.search(
                    "logs", {"query": {"match": {"msg": "event"}},
                             "size": 40})
                if res["_shards"]["failed"] or res["hits"]["total"] < 8:
                    raise NodeNotConnectedException(
                        f"partial result: {res['hits']['total']} hits, "
                        f"{res['_shards']['failed']} failed shards")
                return res

            res = self._retry(refresh_and_search, attempts=30)
            assert res["hits"]["total"] == 8
        finally:
            drop.remove()
            delay.remove()
