"""Foundation tests: settings, units, errors, breakers, murmur3 routing."""

import pytest

from elasticsearch_tpu.common import settings as S
from elasticsearch_tpu.common.breaker import CircuitBreakerService
from elasticsearch_tpu.common.errors import (
    CircuitBreakingException,
    ElasticsearchTpuException,
    IllegalArgumentException,
    IndexNotFoundException,
)
from elasticsearch_tpu.common.settings import Setting, Settings
from elasticsearch_tpu.common.units import (
    format_time_value,
    parse_byte_size,
    parse_ratio_or_bytes,
    parse_time_value,
)
from elasticsearch_tpu.utils.murmur3 import murmur3_32, shard_id_for


class TestSettings:
    def test_flatten_nested(self):
        s = Settings.from_dict({"index": {"number_of_shards": 3, "refresh_interval": "5s"}})
        assert s.get_int("index.number_of_shards") == 3
        assert s.get_time("index.refresh_interval") == 5.0

    def test_nested_roundtrip(self):
        s = Settings({"a.b.c": 1, "a.b.d": 2, "a.e": "x"})
        assert s.as_nested_dict() == {"a": {"b": {"c": 1, "d": 2}, "e": "x"}}

    def test_typed_getters(self):
        s = Settings({"i": "42", "f": "1.5", "b": "true", "l": "a, b,c"})
        assert s.get_int("i") == 42
        assert s.get_float("f") == 1.5
        assert s.get_bool("b") is True
        assert s.get_list("l") == ["a", "b", "c"]
        assert s.get_int("missing", 7) == 7

    def test_bad_bool_raises(self):
        with pytest.raises(IllegalArgumentException):
            Settings({"b": "yes"}).get_bool("b")

    def test_merge_removes_none(self):
        merged = Settings({"a": 1, "b": 2}).merged_with(Settings({"b": None, "c": 3}))
        assert merged.as_dict() == {"a": 1, "c": 3}

    def test_setting_default_and_validation(self):
        shards = S.INDEX_NUMBER_OF_SHARDS
        assert shards.get(Settings.EMPTY) == 5  # the 6.x default
        assert shards.get(Settings({"index.number_of_shards": "4"})) == 4
        with pytest.raises(IllegalArgumentException):
            shards.get(Settings({"index.number_of_shards": 0}))

    def test_scoped_registry_rejects_unknown_and_non_dynamic(self):
        reg = S.index_scoped_settings()
        with pytest.raises(IllegalArgumentException):
            reg.validate(Settings({"index.bogus": 1}))
        with pytest.raises(IllegalArgumentException):
            reg.validate_dynamic_update(Settings({"index.number_of_shards": 2}))
        reg.validate_dynamic_update(Settings({"index.number_of_replicas": 2}))

    def test_update_consumer_fires_on_change(self):
        reg = S.cluster_settings()
        seen = []
        reg.add_settings_update_consumer(S.SEARCH_MAX_BUCKETS, seen.append)
        reg.apply_settings(Settings.EMPTY, Settings({"search.max_buckets": 100}))
        reg.apply_settings(
            Settings({"search.max_buckets": 100}), Settings({"search.max_buckets": 100})
        )
        assert seen == [100]


class TestUnits:
    def test_time_values(self):
        assert parse_time_value("30s") == 30.0
        assert parse_time_value("1m") == 60.0
        assert parse_time_value("500ms") == 0.5
        assert parse_time_value("2h") == 7200.0
        assert parse_time_value("-1") == -1.0
        with pytest.raises(IllegalArgumentException):
            parse_time_value("10 parsecs")
        with pytest.raises(IllegalArgumentException):
            parse_time_value(10)  # bare number needs a unit

    def test_format_time(self):
        assert format_time_value(5.0) == "5s"
        assert format_time_value(0.25) == "250ms"

    def test_byte_sizes(self):
        assert parse_byte_size("1kb") == 1024
        assert parse_byte_size("2mb") == 2 * 1024**2
        assert parse_byte_size("1.5gb") == int(1.5 * 1024**3)
        assert parse_byte_size(123) == 123
        assert parse_ratio_or_bytes("50%", 1000) == 500


class TestErrors:
    def test_error_type_snake_case(self):
        assert IndexNotFoundException("idx").error_type == "index_not_found_exception"

    def test_to_dict_with_cause(self):
        try:
            try:
                raise ValueError("inner")
            except ValueError as e:
                raise IndexNotFoundException("idx") from e
        except ElasticsearchTpuException as outer:
            d = outer.to_dict()
        assert d["status"] == 404
        assert d["error"]["index"] == "idx"
        assert d["error"]["caused_by"]["reason"] == "inner"


class TestBreakers:
    def test_child_trips_at_limit(self):
        svc = CircuitBreakerService(total_limit=1000, request_limit=100)
        b = svc.get_breaker("request")
        b.add_estimate_bytes_and_maybe_break(90, "agg")
        with pytest.raises(CircuitBreakingException) as ei:
            b.add_estimate_bytes_and_maybe_break(20, "agg")
        assert ei.value.status_code == 429
        assert b.used_bytes == 90  # failed reservation rolled back

    def test_parent_trips_on_child_sum(self):
        svc = CircuitBreakerService(total_limit=100, request_limit=80, fielddata_limit=80)
        svc.get_breaker("request").add_estimate_bytes_and_maybe_break(70, "r")
        with pytest.raises(CircuitBreakingException):
            svc.get_breaker("fielddata").add_estimate_bytes_and_maybe_break(50, "f")
        assert svc.get_breaker("fielddata").used_bytes == 0


class TestMurmur3:
    def test_known_vectors(self):
        # Public MurmurHash3_x86_32 test vectors (seed 0).
        assert murmur3_32(b"") == 0
        assert murmur3_32(b"hello") == 0x248BFA47
        assert murmur3_32(b"aaaa") == 0x7EEED987  # 4-byte block path (regression pin)

    def test_shard_distribution_uniform(self):
        counts = [0] * 5
        for i in range(10000):
            counts[shard_id_for(f"doc-{i}", 5)] += 1
        for c in counts:
            assert 1600 < c < 2400

    def test_stable(self):
        assert shard_id_for("user-123", 8) == shard_id_for("user-123", 8)
