"""Mapper tests (ref: index/mapper — DocumentParser, MapperService.merge)."""

import pytest

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
)
from elasticsearch_tpu.mapper.field_types import (
    format_ip,
    parse_date,
    parse_ip,
)
from elasticsearch_tpu.mapper.mapping import MapperService


def make_service(mapping=None, **kw):
    return MapperService(AnalysisRegistry(), mapping, **kw)


class TestFieldTypes:
    def test_date_parsing(self):
        assert parse_date("2017-01-01") == 1483228800000
        assert parse_date("2017-01-01T00:00:00Z") == 1483228800000
        assert parse_date(1483228800000) == 1483228800000
        assert parse_date("1483228800000") == 1483228800000
        with pytest.raises(MapperParsingException):
            parse_date("not a date")

    def test_date_custom_format(self):
        assert parse_date("01/01/2017", ["dd/MM/yyyy"]) == 1483228800000
        with pytest.raises(MapperParsingException):
            parse_date("2017-01-01", ["dd/MM/yyyy"])

    def test_ip(self):
        assert format_ip(parse_ip("192.168.1.1")) == "192.168.1.1"
        assert format_ip(parse_ip("::1")) == "::1"
        assert parse_ip("10.0.0.2") > parse_ip("10.0.0.1")
        with pytest.raises(MapperParsingException):
            parse_ip("not-an-ip")


class TestExplicitMapping:
    MAPPING = {
        "properties": {
            "title": {"type": "text", "fields": {"raw": {"type": "keyword"}}},
            "tags": {"type": "keyword"},
            "views": {"type": "long"},
            "rating": {"type": "double"},
            "published": {"type": "date"},
            "active": {"type": "boolean"},
            "author": {"properties": {"name": {"type": "text"}, "age": {"type": "integer"}}},
        }
    }

    def setup_method(self):
        self.svc = make_service(self.MAPPING)

    def test_parse_full_doc(self):
        doc = self.svc.parse_document("1", {
            "title": "The Quick Fox",
            "tags": ["news", "animals"],
            "views": 42,
            "rating": 4.5,
            "published": "2017-06-01",
            "active": True,
            "author": {"name": "Jane Doe", "age": 34},
        })
        assert doc.terms["title"] == ["the", "quick", "fox"]
        assert doc.terms["title.raw"] == ["The Quick Fox"]
        assert doc.terms["tags"] == ["news", "animals"]
        assert doc.numeric_values["views"] == [42.0]
        assert doc.numeric_values["author.age"] == [34.0]
        assert doc.string_values["tags"] == ["news", "animals"]
        assert doc.terms["author.name"] == ["jane", "doe"]
        assert doc.terms["active"] == ["T"]
        assert "views" in doc.field_names
        assert doc.mapping_update is None

    def test_long_range_check(self):
        with pytest.raises(MapperParsingException):
            self.svc.parse_document("1", {"author": {"age": 2**40}})

    def test_bad_number(self):
        with pytest.raises(MapperParsingException):
            self.svc.parse_document("1", {"views": "many"})

    def test_object_vs_concrete_conflict(self):
        with pytest.raises(MapperParsingException):
            self.svc.parse_document("1", {"author": "just a string"})


class TestDynamicMapping:
    def test_infers_types(self):
        svc = make_service()
        doc = svc.parse_document("1", {
            "name": "Alice", "age": 30, "score": 1.5, "ok": True,
            "joined": "2020-05-01T10:00:00Z", "nested": {"x": 1},
        })
        props = svc.mapping_dict()["properties"]
        assert props["name"]["type"] == "text"
        assert props["name"]["fields"]["keyword"]["type"] == "keyword"
        assert props["age"]["type"] == "long"
        assert props["score"]["type"] == "float"
        assert props["ok"]["type"] == "boolean"
        assert props["joined"]["type"] == "date"
        assert props["nested"]["properties"]["x"]["type"] == "long"
        # text got an automatic .keyword subfield indexed too
        assert doc.terms["name.keyword"] == ["Alice"]

    def test_dynamic_strict_rejects(self):
        svc = make_service({"dynamic": "strict", "properties": {"a": {"type": "long"}}})
        svc.parse_document("1", {"a": 1})
        with pytest.raises(MapperParsingException):
            svc.parse_document("2", {"b": 1})

    def test_dynamic_false_ignores(self):
        svc = make_service({"dynamic": "false", "properties": {"a": {"type": "long"}}})
        doc = svc.parse_document("1", {"a": 1, "b": "ignored"})
        assert "b" not in doc.terms and "b" not in doc.string_values
        assert "b" not in svc.mapping_dict()["properties"]

    def test_field_limit(self):
        svc = make_service(total_fields_limit=3)
        with pytest.raises(IllegalArgumentException):
            svc.parse_document("1", {"a": "x", "b": "y"})  # each text adds .keyword


class TestMerge:
    def test_merge_adds_fields(self):
        svc = make_service({"properties": {"a": {"type": "long"}}})
        svc.merge({"properties": {"b": {"type": "keyword"}}})
        props = svc.mapping_dict()["properties"]
        assert props["a"]["type"] == "long" and props["b"]["type"] == "keyword"

    def test_merge_type_conflict(self):
        svc = make_service({"properties": {"a": {"type": "long"}}})
        with pytest.raises(IllegalArgumentException):
            svc.merge({"properties": {"a": {"type": "keyword"}}})

    def test_merge_nested(self):
        svc = make_service({"properties": {"o": {"properties": {"x": {"type": "long"}}}}})
        svc.merge({"properties": {"o": {"properties": {"y": {"type": "boolean"}}}}})
        props = svc.mapping_dict()["properties"]["o"]["properties"]
        assert set(props) == {"x", "y"}


class TestFieldPatterns:
    def test_simple_match(self):
        svc = make_service({"properties": {
            "user.name": {"type": "text"},
        }})
        svc.merge({"properties": {"username": {"type": "keyword"}, "age": {"type": "long"}}})
        m = svc.mapper
        assert m.simple_match_to_fields("user*") == ["user.name", "username"]
        assert m.simple_match_to_fields("age") == ["age"]
        assert m.simple_match_to_fields("missing") == []
