"""Aggregation tests (ref: search/aggregations — bucket/metric/pipeline)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture(scope="module")
def sales():
    idx = IndexService("sales", Settings({"index.number_of_shards": 2}))
    rows = [
        ("red", "shirt", 10, "2017-01-05"),
        ("red", "pants", 20, "2017-01-15"),
        ("blue", "shirt", 15, "2017-02-03"),
        ("blue", "shirt", 25, "2017-02-20"),
        ("green", "hat", 5, "2017-03-01"),
        ("red", "hat", 8, "2017-03-11"),
        ("blue", "pants", 30, "2017-03-25"),
        ("red", "shirt", 12, "2017-04-02"),
    ]
    for i, (color, kind, price, date) in enumerate(rows):
        idx.index_doc(str(i), {
            "color": color, "kind": kind, "price": price, "sold": date,
        })
    idx.refresh()
    yield idx
    idx.close()


def agg(resp, name):
    return resp["aggregations"][name]


class TestMetrics:
    def test_min_max_sum_avg(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "mn": {"min": {"field": "price"}},
            "mx": {"max": {"field": "price"}},
            "sm": {"sum": {"field": "price"}},
            "av": {"avg": {"field": "price"}},
            "vc": {"value_count": {"field": "price"}},
        }})
        assert agg(r, "mn")["value"] == 5.0
        assert agg(r, "mx")["value"] == 30.0
        assert agg(r, "sm")["value"] == 125.0
        assert agg(r, "av")["value"] == pytest.approx(125 / 8)
        assert agg(r, "vc")["value"] == 8

    def test_stats_extended(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "s": {"stats": {"field": "price"}},
            "es": {"extended_stats": {"field": "price"}},
        }})
        s = agg(r, "s")
        assert s["count"] == 8 and s["min"] == 5.0 and s["max"] == 30.0
        es = agg(r, "es")
        assert es["variance"] == pytest.approx(
            sum((x - 125 / 8) ** 2 for x in [10, 20, 15, 25, 5, 8, 30, 12]) / 8
        )

    def test_metrics_respect_query(self, sales):
        r = sales.search({"size": 0, "query": {"term": {"color": "red"}},
                          "aggs": {"sm": {"sum": {"field": "price"}}}})
        assert agg(r, "sm")["value"] == 50.0  # 10+20+8+12

    def test_cardinality(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"cardinality": {"field": "color"}},
            "kinds": {"cardinality": {"field": "kind"}},
        }})
        assert agg(r, "colors")["value"] == 3
        assert agg(r, "kinds")["value"] == 3

    def test_percentiles(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "p": {"percentiles": {"field": "price", "percents": [50, 100]}},
        }})
        vals = agg(r, "p")["values"]
        assert vals["100.0"] == 30.0
        assert 10 <= vals["50.0"] <= 15

    def test_empty_bucket_metrics(self, sales):
        r = sales.search({"size": 0, "query": {"term": {"color": "nope"}},
                          "aggs": {"mn": {"min": {"field": "price"}}}})
        assert agg(r, "mn")["value"] is None

    def test_top_hits(self, sales):
        r = sales.search({"size": 0, "query": {"match_all": {}}, "aggs": {
            "by_color": {"terms": {"field": "color"}, "aggs": {
                "top": {"top_hits": {"size": 1}},
            }},
        }})
        buckets = agg(r, "by_color")["buckets"]
        for b in buckets:
            assert len(b["top"]["hits"]["hits"]) == 1


class TestBuckets:
    def test_terms_counts(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color"}},
        }})
        got = {b["key"]: b["doc_count"] for b in agg(r, "colors")["buckets"]}
        assert got == {"red": 4, "blue": 3, "green": 1}
        # sorted by count desc
        keys = [b["key"] for b in agg(r, "colors")["buckets"]]
        assert keys == ["red", "blue", "green"]

    def test_terms_size_and_other(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color", "size": 1}},
        }})
        a = agg(r, "colors")
        assert len(a["buckets"]) == 1
        assert a["buckets"][0]["key"] == "red"
        assert a["sum_other_doc_count"] == 4

    def test_terms_order_by_key(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color", "order": {"_key": "asc"}}},
        }})
        assert [b["key"] for b in agg(r, "colors")["buckets"]] == ["blue", "green", "red"]

    def test_terms_with_sub_metric(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color"}, "aggs": {
                "total": {"sum": {"field": "price"}},
            }},
        }})
        got = {b["key"]: b["total"]["value"] for b in agg(r, "colors")["buckets"]}
        assert got == {"red": 50.0, "blue": 70.0, "green": 5.0}

    def test_nested_terms(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color"}, "aggs": {
                "kinds": {"terms": {"field": "kind"}},
            }},
        }})
        red = next(b for b in agg(r, "colors")["buckets"] if b["key"] == "red")
        kinds = {b["key"]: b["doc_count"] for b in red["kinds"]["buckets"]}
        assert kinds == {"shirt": 2, "pants": 1, "hat": 1}

    def test_histogram(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "prices": {"histogram": {"field": "price", "interval": 10}},
        }})
        got = {b["key"]: b["doc_count"] for b in agg(r, "prices")["buckets"]}
        assert got == {0.0: 2, 10.0: 3, 20.0: 2, 30.0: 1}

    def test_date_histogram_month(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "monthly": {"date_histogram": {"field": "sold", "interval": "month"}},
        }})
        buckets = agg(r, "monthly")["buckets"]
        counts = [b["doc_count"] for b in buckets]
        assert counts == [2, 2, 3, 1]
        assert buckets[0]["key_as_string"].startswith("2017-01-01")

    def test_range_agg(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "bands": {"range": {"field": "price", "ranges": [
                {"to": 10}, {"from": 10, "to": 20}, {"from": 20, "key": "big"},
            ]}},
        }})
        buckets = agg(r, "bands")["buckets"]
        assert [b["doc_count"] for b in buckets] == [2, 3, 3]
        assert buckets[2]["key"] == "big"

    def test_filter_agg(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "cheap": {"filter": {"range": {"price": {"lt": 12}}}, "aggs": {
                "avg_p": {"avg": {"field": "price"}},
            }},
        }})
        a = agg(r, "cheap")
        assert a["doc_count"] == 3  # 10, 5, 8
        assert a["avg_p"]["value"] == pytest.approx(23 / 3)

    def test_filters_agg(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "groups": {"filters": {"filters": {
                "red": {"term": {"color": "red"}},
                "cheap": {"range": {"price": {"lt": 10}}},
            }}},
        }})
        buckets = agg(r, "groups")["buckets"]
        assert buckets["red"]["doc_count"] == 4
        assert buckets["cheap"]["doc_count"] == 2

    def test_global_agg(self, sales):
        r = sales.search({"size": 0, "query": {"term": {"color": "red"}}, "aggs": {
            "all": {"global": {}, "aggs": {"n": {"value_count": {"field": "price"}}}},
            "matched": {"value_count": {"field": "price"}},
        }})
        assert agg(r, "all")["doc_count"] == 8
        assert agg(r, "all")["n"]["value"] == 8
        assert agg(r, "matched")["value"] == 4

    def test_missing_agg(self, sales):
        idx = IndexService("m", Settings({"index.number_of_shards": 1}))
        idx.index_doc("1", {"a": 1, "b": "x"})
        idx.index_doc("2", {"a": 2})
        idx.refresh()
        r = idx.search({"size": 0, "aggs": {"no_b": {"missing": {"field": "b"}}}})
        assert agg(r, "no_b")["doc_count"] == 1
        idx.close()


class TestPipeline:
    def test_cumulative_sum_and_derivative(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "monthly": {"date_histogram": {"field": "sold", "interval": "month"},
                        "aggs": {"total": {"sum": {"field": "price"}}}},
            "cum": {"cumulative_sum": {"buckets_path": "monthly>total"}},
            "deriv": {"derivative": {"buckets_path": "monthly>total"}},
        }})
        buckets = agg(r, "monthly")["buckets"]
        totals = [b["total"]["value"] for b in buckets]
        assert totals == [30.0, 40.0, 43.0, 12.0]
        cums = [b["cum"]["value"] for b in buckets]
        assert cums == [30.0, 70.0, 113.0, 125.0]
        assert "deriv" not in buckets[0]
        assert buckets[1]["deriv"]["value"] == 10.0

    def test_bucket_stats(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "monthly": {"date_histogram": {"field": "sold", "interval": "month"},
                        "aggs": {"total": {"sum": {"field": "price"}}}},
            "best": {"max_bucket": {"buckets_path": "monthly>total"}},
            "avg_m": {"avg_bucket": {"buckets_path": "monthly>total"}},
        }})
        assert agg(r, "best")["value"] == 43.0
        assert agg(r, "avg_m")["value"] == pytest.approx(125 / 4)

    def test_bucket_script_and_selector(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color"}, "aggs": {
                "total": {"sum": {"field": "price"}},
            }},
            "ratio": {"bucket_script": {
                "buckets_path": {"t": "colors>total"},
                "script": "params.t / 125.0",
            }},
        }})
        buckets = agg(r, "colors")["buckets"]
        red = next(b for b in buckets if b["key"] == "red")
        assert red["ratio"]["value"] == pytest.approx(50 / 125)

    def test_bucket_selector_drops(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "colors": {"terms": {"field": "color"}, "aggs": {
                "total": {"sum": {"field": "price"}},
            }},
            "keep_big": {"bucket_selector": {
                "buckets_path": {"t": "colors>total"},
                "script": "params.t > 40",
            }},
        }})
        keys = {b["key"] for b in agg(r, "colors")["buckets"]}
        assert keys == {"red", "blue"}


class TestNumericTerms:
    def test_terms_on_numeric(self, sales):
        r = sales.search({"size": 0, "aggs": {
            "prices": {"terms": {"field": "price", "size": 20}},
        }})
        got = {b["key"]: b["doc_count"] for b in agg(r, "prices")["buckets"]}
        assert got[10] == 1 and len(got) == 8
