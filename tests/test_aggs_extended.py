"""Extended aggs: significant_terms, sampler, adjacency_matrix, geo aggs,
matrix_stats."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture(scope="module")
def idx():
    svc = IndexService("ext", Settings({"index.number_of_shards": 1}), {
        "properties": {
            "loc": {"type": "geo_point"},
            "topic": {"type": "keyword"},
            "body": {"type": "text"},
        }
    })
    docs = [
        # crime-related docs mention "theft" disproportionately
        {"body": "report of theft downtown", "topic": "crime",
         "loc": {"lat": 40.0, "lon": -74.0}, "x": 1.0, "y": 2.0},
        {"body": "theft at the market", "topic": "crime",
         "loc": {"lat": 40.1, "lon": -74.1}, "x": 2.0, "y": 4.1},
        {"body": "theft suspect arrested", "topic": "crime",
         "loc": {"lat": 40.2, "lon": -74.2}, "x": 3.0, "y": 5.9},
        {"body": "local bakery opens doors", "topic": "news",
         "loc": {"lat": 50.0, "lon": 8.0}, "x": 4.0, "y": 8.2},
        {"body": "city council votes on budget", "topic": "news",
         "loc": {"lat": 50.1, "lon": 8.1}, "x": 5.0, "y": 9.8},
        {"body": "weather sunny all week", "topic": "news",
         "loc": {"lat": 50.2, "lon": 8.2}, "x": 6.0, "y": 12.1},
    ]
    for i, d in enumerate(docs):
        svc.index_doc(str(i), d)
    svc.refresh()
    yield svc
    svc.close()


def agg(r, name):
    return r["aggregations"][name]


class TestSignificantTerms:
    def test_significant_terms_finds_theft(self, idx):
        r = idx.search({"size": 0, "query": {"term": {"topic": "crime"}},
                        "aggs": {"sig": {"significant_terms": {
                            "field": "body", "min_doc_count": 2}}}})
        # "theft" appears in 3/3 foreground docs but 3/6 background
        keys = [b["key"] for b in agg(r, "sig")["buckets"]]
        assert "theft" in keys
        # generic terms ("the") must not outrank it
        top = agg(r, "sig")["buckets"][0]
        assert top["key"] == "theft"
        assert top["doc_count"] == 3

    def test_significant_terms_on_text_uses_terms(self, idx):
        # terms resolution falls back through text -> term dict
        r = idx.search({"size": 0, "query": {"term": {"topic": "news"}},
                        "aggs": {"sig": {"significant_terms": {
                            "field": "topic", "min_doc_count": 1}}}})
        keys = [b["key"] for b in agg(r, "sig")["buckets"]]
        assert keys == ["news"]


class TestSampler:
    def test_sampler_limits_docs(self, idx):
        r = idx.search({"size": 0, "aggs": {"sample": {
            "sampler": {"shard_size": 2},
            "aggs": {"n": {"value_count": {"field": "x"}}},
        }}})
        assert agg(r, "sample")["doc_count"] == 2
        assert agg(r, "sample")["n"]["value"] == 2


class TestAdjacencyMatrix:
    def test_pairwise_intersections(self, idx):
        r = idx.search({"size": 0, "aggs": {"adj": {"adjacency_matrix": {
            "filters": {
                "crime": {"term": {"topic": "crime"}},
                "theft": {"match": {"body": "theft"}},
                "north": {"range": {"x": {"lte": 3}}},
            }}}}})
        got = {b["key"]: b["doc_count"] for b in agg(r, "adj")["buckets"]}
        assert got["crime"] == 3
        assert got["crime&theft"] == 3
        assert got["crime&north"] == 3
        assert got["theft&north"] == 3
        assert "news" not in got


class TestGeoAggs:
    def test_geo_bounds(self, idx):
        r = idx.search({"size": 0, "aggs": {"b": {"geo_bounds": {"field": "loc"}}}})
        bounds = agg(r, "b")["bounds"]
        assert bounds["top_left"]["lat"] == pytest.approx(50.2)
        assert bounds["top_left"]["lon"] == pytest.approx(-74.2)
        assert bounds["bottom_right"]["lat"] == pytest.approx(40.0)
        assert bounds["bottom_right"]["lon"] == pytest.approx(8.2)

    def test_geo_centroid(self, idx):
        r = idx.search({"size": 0, "query": {"term": {"topic": "crime"}},
                        "aggs": {"c": {"geo_centroid": {"field": "loc"}}}})
        c = agg(r, "c")
        assert c["count"] == 3
        assert c["location"]["lat"] == pytest.approx(40.1, abs=1e-4)

    def test_geohash_grid(self, idx):
        r = idx.search({"size": 0, "aggs": {"g": {"geohash_grid": {
            "field": "loc", "precision": 2}}}})
        buckets = {b["key"]: b["doc_count"] for b in agg(r, "g")["buckets"]}
        assert sum(buckets.values()) == 6
        assert len(buckets) == 2  # NJ cluster vs Frankfurt cluster

    def test_geohash_roundtrip(self):
        from elasticsearch_tpu.utils.geohash import decode, encode

        h = encode(48.8566, 2.3522, 7)
        lat, lon = decode(h)
        assert lat == pytest.approx(48.8566, abs=0.01)
        assert lon == pytest.approx(2.3522, abs=0.01)


class TestMatrixStats:
    def test_correlation(self, idx):
        r = idx.search({"size": 0, "aggs": {"m": {"matrix_stats": {
            "fields": ["x", "y"]}}}})
        m = agg(r, "m")
        assert m["doc_count"] == 6
        fx = next(f for f in m["fields"] if f["name"] == "x")
        # y ~ 2x + noise: correlation near 1
        assert fx["correlation"]["y"] > 0.99
        assert fx["mean"] == pytest.approx(3.5)
