"""Data-integrity matrix (ISSUE 16): corruption-marker lifecycle,
at-rest detection for every corruption kind, device-drift detection for
every staged table kind, the PR-4 partial contract on a quarantined
query path, the scrub-interval knob (dynamic + cluster override), the
snapshot digest satellites, the operator surfaces (_cat/shards,
allocation explain, _stats), and the cluster heal outcomes — corrupt
replica, corrupt primary, last copy retained RED."""

import os
import time

import numpy as np
import pytest

from elasticsearch_tpu.client import Client
from elasticsearch_tpu.cluster.multinode import ClusterClient, ClusterNode
from elasticsearch_tpu.cluster.state import ShardRoutingState
from elasticsearch_tpu.common.errors import SearchPhaseExecutionException
from elasticsearch_tpu.common.integrity import integrity_service
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.store import (
    MARKER_PREFIX,
    CorruptIndexException,
    Store,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.testing.disruption import StoreCorruptionScheme
from elasticsearch_tpu.transport.local import TransportHub

MAPPING = {"properties": {"body": {"type": "text"},
                          "n": {"type": "integer"}}}


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")


def mk_service(tmp_path, name="cx", shards=1, docs=20):
    svc = IndexService(
        name,
        Settings({"index.number_of_shards": shards,
                  "index.search.mesh": False}),
        mapping=MAPPING, data_path=str(tmp_path / name))
    for i in range(docs):
        svc.index_doc(str(i), {"body": f"alpha common doc{i}", "n": i})
    svc.refresh()
    svc.flush()
    return svc


def _wait(predicate, attempts=200, delay=0.05):
    for _ in range(attempts):
        if predicate():
            return True
        time.sleep(delay)
    return predicate()


# ---------------------------------------------------------------------------
# Marker lifecycle (Store.markStoreCorrupted parity)
# ---------------------------------------------------------------------------


class TestMarkerLifecycle:
    def test_written_once_first_cause_wins(self, tmp_path):
        store = Store(str(tmp_path / "s"))
        first = store.mark_corrupted("cause A", site="load")
        second = store.mark_corrupted("cause B", site="query")
        assert second["marker"] == first["marker"]
        markers = store.corruption_markers()
        assert len(markers) == 1
        assert markers[0]["reason"] == "cause A"
        assert markers[0]["site"] == "load"
        assert markers[0]["marker"].startswith(MARKER_PREFIX)

    def test_marker_blocks_load_and_read(self, tmp_path):
        svc = mk_service(tmp_path, "mb", docs=8)
        try:
            store = svc.shards[0].engine.store
            seg_names = (store.read_commit() or {}).get("segments", [])
            assert seg_names, "flush must have committed a segment"
            store.mark_corrupted("bit rot", site="scrub")
            with pytest.raises(CorruptIndexException):
                store.load_segments()
            with pytest.raises(CorruptIndexException):
                store.read_segment(seg_names[0])
        finally:
            svc.close()

    def test_torn_marker_still_counts(self, tmp_path):
        store = Store(str(tmp_path / "torn"))
        torn = os.path.join(store.directory, MARKER_PREFIX + "torn.json")
        with open(torn, "w", encoding="utf-8") as f:
            f.write('{"reason": "trunc')  # unparseable: still a marker
        assert store.is_corrupted()
        markers = store.corruption_markers()
        assert markers[0]["marker"] == MARKER_PREFIX + "torn.json"
        with pytest.raises(CorruptIndexException):
            store._check_not_corrupted()

    def test_clear_reopens_the_store(self, tmp_path):
        svc = mk_service(tmp_path, "cl", docs=8)
        try:
            store = svc.shards[0].engine.store
            store.mark_corrupted("transient", site="load")
            assert store.is_corrupted()
            assert store.clear_corruption_markers() == 1
            assert not store.is_corrupted()
            assert store.load_segments()  # legal again after clear
        finally:
            svc.close()

    def test_marker_survives_later_commits(self, tmp_path):
        """Commit GC only prunes segment DIRECTORIES — the marker file
        sitting next to them must survive every later commit cycle."""
        svc = mk_service(tmp_path, "gc", docs=8)
        try:
            store = svc.shards[0].engine.store
            marker = store.mark_corrupted("at-rest rot", site="scrub")
            for i in range(8, 16):
                svc.index_doc(str(i), {"body": f"beta {i}", "n": i})
            svc.refresh()
            svc.flush()
            markers = store.corruption_markers()
            assert [m["marker"] for m in markers] == [marker["marker"]]
        finally:
            svc.close()

    def test_unquarantine_is_the_only_exit(self, tmp_path):
        svc = mk_service(tmp_path, "uq", docs=8)
        try:
            before = integrity_service().stats()
            svc._quarantine_shard(0, CorruptIndexException("injected"),
                                  site="query")
            shard = svc.shards[0]
            assert shard.store_corrupted
            assert shard.engine.store.is_corrupted()
            svc.unquarantine_shard(0)
            assert not shard.store_corrupted
            assert not shard.engine.store.is_corrupted()
            after = integrity_service().stats()
            assert after["markers_written_total"] \
                == before["markers_written_total"] + 1
            assert after["markers_cleared_total"] \
                == before["markers_cleared_total"] + 1
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# Background scrubber: at-rest detection, one kind at a time
# ---------------------------------------------------------------------------


class TestScrubAtRest:
    @pytest.mark.parametrize(
        "kind", ["bitflip", "truncate", "torn_checksums",
                 "missing_checksums"])
    def test_each_kind_detected_and_quarantined(self, tmp_path, kind):
        svc = mk_service(tmp_path, f"ar_{kind}"[:14], shards=2, docs=24)
        try:
            store = svc.shards[0].engine.store
            assert (store.read_commit() or {}).get("segments")
            StoreCorruptionScheme(kind, seed=11).corrupt_store(store)
            before = integrity_service().stats()
            rep = svc.scrub_now()
            assert rep["checksum_failures"] >= 1
            assert svc.shards[0].store_corrupted
            assert store.is_corrupted()
            after = integrity_service().stats()
            assert (after["corruption_detected_by_site"].get("scrub", 0)
                    - before["corruption_detected_by_site"]
                    .get("scrub", 0)) >= 1
            assert after["markers_written_total"] \
                > before["markers_written_total"]
            # a quarantined copy pins no HBM (PR-9 ledger exactness)
            assert all(not getattr(s, "_device", None)
                       for s in svc.shards[0].engine.segments)
            # the next pass skips the quarantined copy: heal, don't
            # re-verify — detection is counted exactly once
            rep2 = svc.scrub_now()
            assert rep2["checksum_failures"] == 0
            final = integrity_service().stats()
            assert final["corruption_detected_total"] \
                == after["corruption_detected_total"]
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# Background scrubber: device drift, one staged table kind at a time
# ---------------------------------------------------------------------------


class TestScrubDeviceDrift:
    @pytest.mark.parametrize("key", ["block_docs", "block_tfs", "norms"])
    def test_each_staged_table_kind(self, tmp_path, key):
        import jax.numpy as jnp

        svc = mk_service(tmp_path, f"dr_{key[:7]}", docs=16)
        try:
            probe = {"query": {"match": {"body": "alpha"}}}
            want = svc._search_uncached(dict(probe), skip_mesh=True)
            want_hits = [(h["_id"], h["_score"])
                         for h in want["hits"]["hits"]]
            assert want_hits
            seg = next((s for sh in svc.shards.values()
                        for s in sh.engine.segments
                        if getattr(s, "_device", None)), None)
            assert seg is not None, "host path did not stage tables"
            drifted = np.asarray(seg._device[key]).copy()
            drifted.flat[0] += 1
            seg._device[key] = jnp.asarray(drifted)
            before = integrity_service().stats()
            rep = svc.scrub_now()
            assert rep["drift"] >= 1
            after = integrity_service().stats()
            assert after["scrub_drift_total"] \
                - before["scrub_drift_total"] >= 1
            assert after["scrub_runs_total"] > before["scrub_runs_total"]
            assert after["scrub_bytes_verified_total"] \
                > before["scrub_bytes_verified_total"]
            # drift is a staging fault, not store corruption: no marker,
            # no detected-total bump, the copy keeps serving
            assert after["corruption_detected_total"] \
                == before["corruption_detected_total"]
            assert not svc.shards[0].store_corrupted
            assert not svc.shards[0].engine.store.is_corrupted()
            # the staging was invalidated + the restage is classified
            assert seg.stage_reason_initial == "scrub"
            assert not seg._device
            got = svc._search_uncached(dict(probe), skip_mesh=True)
            got_hits = [(h["_id"], h["_score"])
                        for h in got["hits"]["hits"]]
            assert got_hits == want_hits  # host truth re-adopted
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# Query path: the PR-4 partial contract under quarantine
# ---------------------------------------------------------------------------


def _always_corrupt(*a, **k):
    raise CorruptIndexException("injected: torn posting block")


class TestQueryPartialContract:
    def test_corrupt_shard_becomes_failures_entry(self, tmp_path):
        svc = mk_service(tmp_path, "qp", shards=2, docs=24)
        try:
            svc.shards[0].searcher.query = _always_corrupt
            before = integrity_service().stats()
            r = svc.search({"query": {"match": {"body": "alpha"}}})
            assert r["_shards"]["failed"] >= 1
            assert r["_shards"]["successful"] >= 1
            assert r["hits"]["hits"]  # the healthy shard still answers
            reasons = str(r["_shards"]["failures"]).lower()
            assert "corrupt" in reasons
            # first detection quarantined the copy: marker, site=query
            assert svc.shards[0].store_corrupted
            assert svc.shards[0].engine.store.is_corrupted()
            after = integrity_service().stats()
            assert after["corruption_detected_total"] \
                == before["corruption_detected_total"] + 1
            assert (after["corruption_detected_by_site"].get("query", 0)
                    - before["corruption_detected_by_site"]
                    .get("query", 0)) == 1
            # repeated searches fail fast on the flag: still partial,
            # never recounted, never a re-read of the marked bytes
            r2 = svc.search({"query": {"match": {"body": "alpha"}}})
            assert r2["_shards"]["failed"] >= 1
            final = integrity_service().stats()
            assert final["corruption_detected_total"] \
                == after["corruption_detected_total"]
        finally:
            svc.close()

    def test_all_copies_failed_is_search_phase_exception(self, tmp_path):
        svc = mk_service(tmp_path, "qp1", shards=1, docs=8)
        try:
            svc.shards[0].searcher.query = _always_corrupt
            with pytest.raises(SearchPhaseExecutionException):
                svc.search({"query": {"match": {"body": "alpha"}}})
        finally:
            svc.close()

    def test_allow_partial_false_raises(self, tmp_path):
        svc = mk_service(tmp_path, "qp2", shards=2, docs=24)
        try:
            svc.shards[0].searcher.query = _always_corrupt
            with pytest.raises(SearchPhaseExecutionException):
                svc.search({"query": {"match": {"body": "alpha"}},
                            "allow_partial_search_results": False})
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# index.scrub.interval: off by default, dynamic, cluster override wins
# ---------------------------------------------------------------------------


class TestScrubIntervalKnob:
    def test_dynamic_update_and_cluster_override(self):
        node = Node(Settings.EMPTY)
        try:
            node.create_index("si", {"settings": {"number_of_shards": 1},
                                     "mappings": MAPPING})
            svc = node.indices["si"]
            assert svc._scrub_effective_interval() is None  # off
            node.update_index_settings(
                "si", {"index.scrub.interval": "30s"})
            assert svc._scrub_effective_interval() == 30.0
            # an explicit cluster value overrides the index setting
            node.put_cluster_settings(
                {"persistent": {"index.scrub.interval": "5s"}})
            assert svc.scrub_interval_override == 5.0
            assert svc._scrub_effective_interval() == 5.0
            # clearing hands control back to the index setting
            node.put_cluster_settings(
                {"persistent": {"index.scrub.interval": None}})
            assert svc.scrub_interval_override is None
            assert svc._scrub_effective_interval() == 30.0
        finally:
            node.close()


# ---------------------------------------------------------------------------
# Snapshot satellites: digests on create, _status + restore verification
# ---------------------------------------------------------------------------


def _corrupt_snapshot_blob(repo, snapshot, index):
    """Flip one bit in the first digest-covered blob of one index."""
    m = repo.read_manifest(snapshot)
    sid, sinfo = next(iter(m["indices"][index]["shards"].items()))
    rel = next(iter(sinfo["digests"]))
    full = os.path.join(repo.snapshot_path(snapshot),
                        "indices", index, str(sid), rel)
    with open(full, "r+b") as f:
        data = bytearray(f.read())
        data[0] ^= 0x01
        f.seek(0)
        f.write(data)


class TestSnapshotIntegrity:
    @pytest.fixture()
    def node(self, tmp_path):
        n = Node(Settings.EMPTY)
        for name in ("snap_a", "snap_b"):
            n.create_index(name, {"settings": {"number_of_shards": 1},
                                  "mappings": MAPPING})
            for i in range(8):
                n.index_doc(name, str(i), {"body": f"alpha {i}", "n": i})
            n.indices[name].refresh()
        n.snapshots.put_repository(
            "ri", {"type": "fs",
                   "settings": {"location": str(tmp_path / "repo")}})
        yield n
        n.close()

    def test_create_records_digests_status_verifies(self, node):
        node.snapshots.create_snapshot("ri", "s1")
        m = node.snapshots._repo("ri").read_manifest("s1")
        digests = m["indices"]["snap_a"]["shards"]["0"]["digests"]
        assert digests and all(len(d) == 64 for d in digests.values())
        st = node.snapshots.snapshot_status("ri", "s1")["snapshots"][0]
        ver = st["indices"]["snap_a"]["0"]["verification"]
        assert ver["verified"]
        assert ver["files_verified"] == ver["files_total"] > 0

    def test_status_flags_corrupt_blob(self, node):
        node.snapshots.create_snapshot("ri", "s2")
        _corrupt_snapshot_blob(node.snapshots._repo("ri"), "s2", "snap_a")
        st = node.snapshots.snapshot_status("ri", "s2")["snapshots"][0]
        ver = st["indices"]["snap_a"]["0"]["verification"]
        assert not ver["verified"]
        assert ver["files_verified"] < ver["files_total"]

    def test_restore_fails_only_the_corrupt_index(self, node):
        node.snapshots.create_snapshot("ri", "s3")
        _corrupt_snapshot_blob(node.snapshots._repo("ri"), "s3", "snap_a")
        node.delete_index("snap_a")
        node.delete_index("snap_b")
        before = integrity_service().stats()
        r = node.snapshots.restore_snapshot("ri", "s3")
        snap = r["snapshot"]
        assert snap["indices"] == ["snap_b"]
        assert snap["shards"]["failed"] == 1
        fail = snap["failures"][0]
        assert fail["index"] == "snap_a"
        assert fail["type"] == "corrupted_snapshot_exception"
        # the corrupt index was never half-created; the healthy one is up
        assert "snap_a" not in node.indices
        assert "snap_b" in node.indices
        assert node.indices["snap_b"].search(
            {"query": {"match_all": {}}})["hits"]["total"] == 8
        after = integrity_service().stats()
        assert (after["corruption_detected_by_site"].get("restore", 0)
                - before["corruption_detected_by_site"]
                .get("restore", 0)) >= 1

    def test_verify_repository_rest(self, node):
        client = Client(node)
        status, out = client.perform("POST", "/_snapshot/ri/_verify")
        assert status == 200
        assert out["nodes"]


# ---------------------------------------------------------------------------
# Operator surfaces: _cat/shards, allocation explain, _stats integrity
# ---------------------------------------------------------------------------


class TestOperatorSurfaces:
    @pytest.fixture()
    def noderef(self):
        n = Node(Settings.EMPTY)
        n.create_index("rx", {"settings": {"number_of_shards": 2},
                              "mappings": MAPPING})
        for i in range(10):
            n.index_doc("rx", str(i), {"body": f"alpha {i}", "n": i})
        n.indices["rx"].refresh()
        n.indices["rx"].flush()
        yield n
        n.close()

    def test_cat_shards_integrity_column(self, noderef):
        client = Client(noderef)
        status, text = client.perform("GET", "/_cat/shards")
        assert status == 200
        assert MARKER_PREFIX not in text  # healthy: "-" in the column
        noderef.indices["rx"].shards[0].engine.store.mark_corrupted(
            "bit rot", site="scrub")
        status, text = client.perform("GET", "/_cat/shards")
        assert MARKER_PREFIX in text

    def test_allocation_explain_surfaces_markers(self, noderef):
        client = Client(noderef)
        status, out = client.perform("GET", "/_cluster/allocation/explain")
        assert out["can_allocate"] == "yes"
        noderef.indices["rx"].shards[1].engine.store.mark_corrupted(
            "torn checksums", site="load")
        status, out = client.perform("GET", "/_cluster/allocation/explain")
        assert out["can_allocate"] == "no"
        copies = out["corrupted_copies"]
        assert copies[0]["index"] == "rx"
        assert copies[0]["shard"] == 1
        assert copies[0]["site"] == "load"
        assert copies[0]["marker"].startswith(MARKER_PREFIX)

    def test_stats_integrity_block(self, noderef):
        block = noderef.indices["rx"].search_stats()["integrity"]
        for key in ("corruption_detected_total",
                    "corruption_detected_by_site", "scrub_runs_total",
                    "scrub_bytes_verified_total", "scrub_drift_total",
                    "markers_written_total", "markers_cleared_total",
                    "marker_events", "events_dropped"):
            assert key in block


# ---------------------------------------------------------------------------
# Cluster heal outcomes: corrupt replica / corrupt primary / last copy
# ---------------------------------------------------------------------------


class TestClusterHealOutcomes:
    def _cluster(self, tmp_path, n=2):
        hub = TransportHub()
        nodes = [ClusterNode(f"cn-{i}", hub,
                             data_path=str(tmp_path / f"cn{i}"))
                 for i in range(n)]
        nodes[0].bootstrap_cluster()
        for nd in nodes[1:]:
            nd.join("cn-0")
        return hub, nodes

    @staticmethod
    def _seed(client, index, docs=10):
        for i in range(docs):
            client.index(index, str(i), {"body": f"alpha {i}", "n": i})
        client.refresh(index)

    @staticmethod
    def _started(master, index, want):
        copies = master.routing.get(index, {}).get(0, [])
        return (len(copies) == want
                and all(c.state == ShardRoutingState.STARTED
                        for c in copies))

    @staticmethod
    def _node_of(nodes, node_id):
        return next(n for n in nodes if n.node_id == node_id)

    def _healed(self, master, nodes, index):
        copies = master.routing.get(index, {}).get(0, [])
        if len(copies) != 2 or any(
                c.state != ShardRoutingState.STARTED for c in copies):
            return False
        for copy in copies:
            shard = self._node_of(nodes, copy.node_id).shards.get(
                (index, 0))
            if shard is None or getattr(shard, "store_corrupted", False) \
                    or shard.engine.store.is_corrupted():
                return False
        return True

    def test_corrupt_replica_re_recovers_from_primary(self, tmp_path):
        hub, nodes = self._cluster(tmp_path)
        try:
            master = nodes[0]
            master.create_index("hr", {"index": {
                "number_of_shards": 1, "number_of_replicas": 1}})
            client = ClusterClient(nodes[0])
            self._seed(client, "hr")
            assert _wait(lambda: self._started(master, "hr", 2))
            replica = next(c for c in master.routing["hr"][0]
                           if not c.primary)
            rnode = self._node_of(nodes, replica.node_id)
            shard = rnode.shards[("hr", 0)]
            shard.searcher.query = _always_corrupt
            before = integrity_service().stats()
            with pytest.raises(CorruptIndexException):
                rnode._on_query({"index": "hr", "shard": 0,
                                 "body": {"query": {"match_all": {}}},
                                 "k": 10}, "test")
            after = integrity_service().stats()
            assert (after["corruption_detected_by_site"].get("query", 0)
                    - before["corruption_detected_by_site"]
                    .get("query", 0)) >= 1
            assert after["markers_written_total"] \
                > before["markers_written_total"]
            # the master removes the corrupt copy; a fresh replica
            # re-recovers from the primary and clears the marker
            assert _wait(lambda: self._healed(master, nodes, "hr"))
            final = integrity_service().stats()
            assert final["markers_cleared_total"] \
                > before["markers_cleared_total"]
            r = client.search("hr", {"query": {"match_all": {}},
                                     "size": 20})
            assert r["_shards"]["failed"] == 0
            assert r["hits"]["total"] == 10
        finally:
            for nd in nodes:
                nd.close()

    def test_corrupt_primary_fails_over_then_rebuilds(self, tmp_path):
        hub, nodes = self._cluster(tmp_path)
        try:
            master = nodes[0]
            master.create_index("hp", {"index": {
                "number_of_shards": 1, "number_of_replicas": 1}})
            client = ClusterClient(nodes[0])
            self._seed(client, "hp")
            assert _wait(lambda: self._started(master, "hp", 2))
            old_primary = next(c for c in master.routing["hp"][0]
                               if c.primary)
            pnode = self._node_of(nodes, old_primary.node_id)
            pnode.shards[("hp", 0)].searcher.query = _always_corrupt
            with pytest.raises(CorruptIndexException):
                pnode._on_query({"index": "hp", "shard": 0,
                                 "body": {"query": {"match_all": {}}},
                                 "k": 10}, "test")

            def failed_over():
                if not self._healed(master, nodes, "hp"):
                    return False
                newp = next(c for c in master.routing["hp"][0]
                            if c.primary)
                return newp.node_id != old_primary.node_id

            assert _wait(failed_over)
            r = client.search("hp", {"query": {"match_all": {}},
                                     "size": 20})
            assert r["_shards"]["failed"] == 0
            assert r["hits"]["total"] == 10
        finally:
            for nd in nodes:
                nd.close()

    def test_last_copy_retained_red_never_resurrected(self, tmp_path):
        hub, nodes = self._cluster(tmp_path)
        try:
            master = nodes[0]
            master.create_index("lc", {"index": {
                "number_of_shards": 1, "number_of_replicas": 0}})
            client = ClusterClient(nodes[0])
            self._seed(client, "lc", docs=6)
            assert _wait(lambda: self._started(master, "lc", 1))
            copy = master.routing["lc"][0][0]
            pnode = self._node_of(nodes, copy.node_id)
            shard = pnode.shards[("lc", 0)]
            shard.searcher.query = _always_corrupt
            # degraded 200 (PR-4 contract), never a raw 500
            r = client.search("lc", {"query": {"match_all": {}}})
            assert r["_shards"]["failed"] == 1
            assert r["hits"]["hits"] == []
            # the last copy is retained quarantined: RED, still routed
            # to its node, never replaced by a fresh empty primary
            assert _wait(lambda: ("lc", 0) in master.corrupt_retained)
            assert shard.engine.store.is_corrupted()
            time.sleep(0.3)  # give reroute passes a chance to misbehave
            copies = master.routing["lc"][0]
            assert len(copies) == 1
            assert copies[0].node_id == pnode.node_id
            # repeat: still a loud partial failure, no silent resurrect
            r2 = client.search("lc", {"query": {"match_all": {}}})
            assert r2["_shards"]["failed"] == 1
            assert r2["hits"]["hits"] == []
        finally:
            for nd in nodes:
                nd.close()
