"""Subprocess worker for the 3-process TCP cluster test.

Hosts one ClusterNode over TcpTransportHub and executes JSON commands from
stdin (one per line), answering on stdout — the test framework's analog of
driving a real node over its API while discovery/replication run over
sockets. Exercised by tests/test_tcp_transport.py.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from elasticsearch_tpu.cluster.multinode import ClusterClient, ClusterNode  # noqa: E402
from elasticsearch_tpu.transport.tcp import TcpTransportHub  # noqa: E402


def main():
    name = sys.argv[1]
    port = int(sys.argv[2])
    # optional durable data path: shards persist translog + store there,
    # so a SIGKILLed worker restarted over the same path recovers every
    # acked write (crash-recovery tests)
    data_path = sys.argv[3] if len(sys.argv) > 3 else None
    hub = TcpTransportHub(port=port)
    node = ClusterNode(name, hub, data_path=data_path)
    client = ClusterClient(node)
    out = sys.stdout

    def reply(obj):
        out.write(json.dumps(obj) + "\n")
        out.flush()

    reply({"ready": True, "port": hub.port})
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        cmd = json.loads(line)
        op = cmd.pop("op")
        try:
            if op == "add_peer":
                hub.add_peer(cmd["node"], "127.0.0.1", cmd["port"])
                reply({"ok": True})
            elif op == "bootstrap":
                node.bootstrap_cluster()
                reply({"ok": True})
            elif op == "join":
                node.join(cmd["seed"])
                reply({"ok": True})
            elif op == "create_index":
                node.create_index(cmd["index"], cmd.get("settings"),
                                  cmd.get("mappings"))
                reply({"ok": True})
            elif op == "index":
                reply({"ok": True,
                       "result": client.index(cmd["index"], cmd["id"],
                                              cmd["doc"])})
            elif op == "get":
                reply({"ok": True, "result": client.get(cmd["index"],
                                                        cmd["id"])})
            elif op == "refresh":
                client.refresh(cmd["index"])
                reply({"ok": True})
            elif op == "search":
                reply({"ok": True,
                       "result": client.search(cmd["index"],
                                               cmd.get("body"))})
            elif op == "seq_stats":
                stats = {
                    f"{idx}:{sh}": shard.seq_no_stats()
                    for (idx, sh), shard in node.shards.items()}
                reply({"ok": True, "result": stats})
            elif op == "check_nodes":
                reply({"ok": True, "departed": node.check_nodes()})
            elif op == "check_master":
                reply({"ok": True, "master": node.check_master()})
            elif op == "state":
                reply({"ok": True, "master": node.master_id,
                       "nodes": node.known_nodes,
                       "version": node.state_version})
            elif op == "routing":
                from elasticsearch_tpu.cluster.allocation import (
                    routing_to_dict,
                )
                routing = {
                    f"{idx}:{sh}": copies
                    for idx, shards in routing_to_dict(node.routing).items()
                    for sh, copies in shards.items()}
                reply({"ok": True, "routing": routing})
            elif op == "exit":
                reply({"ok": True})
                break
            else:
                reply({"ok": False, "error": f"unknown op {op}"})
        except Exception as e:  # noqa: BLE001
            reply({"ok": False, "error": f"{type(e).__name__}: {e}"})
    node.close()
    hub.close()


if __name__ == "__main__":
    main()
