"""Shard request cache tests (IndicesRequestCache.java:64 analog):
size==0 agg/count responses cached against the shards' visibility epoch,
invalidated by any visible write, with stats in _stats."""

import numpy as np

from elasticsearch_tpu.node import Node


def make_node():
    node = Node()
    node.create_index("logs", {
        "settings": {"number_of_shards": 1},
        "mappings": {"_doc": {"properties": {
            "host": {"type": "keyword"},
            "msg": {"type": "text"},
        }}}})
    for i in range(40):
        node.index_doc("logs", str(i), {
            "host": f"web-{i % 4}", "msg": f"event {i}"},
            refresh=(i == 39))
    return node


AGG_BODY = {
    "query": {"match": {"msg": "event"}},
    "size": 0,
    "aggs": {"hosts": {"terms": {"field": "host"}}},
}


def cache_stats(node):
    return node.indices["logs"].request_cache.stats()


class TestRequestCache:
    def test_repeat_agg_request_hits(self):
        node = make_node()
        r1 = node.search("logs", dict(AGG_BODY))
        s = cache_stats(node)
        assert s["miss_count"] == 1 and s["hit_count"] == 0
        r2 = node.search("logs", dict(AGG_BODY))
        s = cache_stats(node)
        assert s["hit_count"] == 1
        assert r2["hits"]["total"] == r1["hits"]["total"] == 40
        assert r2["aggregations"] == r1["aggregations"]
        assert s["entries"] == 1 and s["memory_size_in_bytes"] > 0

    def test_write_invalidates_before_refresh(self):
        node = make_node()
        node.search("logs", dict(AGG_BODY))
        # update an existing doc: per NRT semantics NOTHING changes for
        # search until refresh (the old copy's delete is buffered, the
        # new copy sits in the indexing buffer) — the cached entry stays
        # valid and the refresh flips visibility + epoch together
        node.index_doc("logs", "7", {"host": "web-9", "msg": "changed"})
        r = node.search("logs", dict(AGG_BODY))
        assert r["hits"]["total"] == 40  # unchanged reader, cache valid
        node.indices["logs"].refresh()
        r = node.search("logs", dict(AGG_BODY))
        # old copy out; the replacement doc no longer matches the query
        assert r["hits"]["total"] == 39

    def test_delete_invalidates(self):
        node = make_node()
        node.search("logs", dict(AGG_BODY))
        node.delete_doc("logs", "3", refresh=True)
        r = node.search("logs", dict(AGG_BODY))
        assert r["hits"]["total"] == 39
        assert cache_stats(node)["hit_count"] == 0

    def test_refresh_with_new_docs_invalidates(self):
        node = make_node()
        node.search("logs", dict(AGG_BODY))
        node.index_doc("logs", "new", {"host": "web-0", "msg": "event new"},
                       refresh=True)
        r = node.search("logs", dict(AGG_BODY))
        assert r["hits"]["total"] == 41
        assert cache_stats(node)["hit_count"] == 0

    def test_empty_refresh_keeps_cache_valid(self):
        node = make_node()
        node.search("logs", dict(AGG_BODY))
        node.indices["logs"].refresh()  # nothing new: same reader identity
        node.search("logs", dict(AGG_BODY))
        assert cache_stats(node)["hit_count"] == 1

    def test_hit_requests_never_cached(self):
        node = make_node()
        body = {"query": {"match": {"msg": "event"}}, "size": 5}
        node.search("logs", body)
        node.search("logs", body)
        s = cache_stats(node)
        assert s["hit_count"] == 0 and s["miss_count"] == 0

    def test_profile_not_cached(self):
        node = make_node()
        body = dict(AGG_BODY)
        body["profile"] = True
        node.search("logs", body)
        node.search("logs", body)
        assert cache_stats(node)["hit_count"] == 0

    def test_cache_disabled_by_setting(self):
        node = Node()
        node.create_index("quiet", {
            "settings": {"index": {"requests": {"cache": {"enable": False}}}},
            "mappings": {"_doc": {"properties": {
                "msg": {"type": "text"}}}}})
        node.index_doc("quiet", "1", {"msg": "hello"}, refresh=True)
        body = {"query": {"match_all": {}}, "size": 0}
        node.search("quiet", body)
        node.search("quiet", body)
        s = node.indices["quiet"].request_cache.stats()
        assert s["miss_count"] == 0 and s["hit_count"] == 0

    def test_stats_exposed_in_index_stats(self):
        node = make_node()
        node.search("logs", dict(AGG_BODY))
        node.search("logs", dict(AGG_BODY))
        st = node.indices["logs"].stats()
        rc = st["total"]["request_cache"]
        assert rc["hit_count"] == 1 and rc["miss_count"] == 1

    def test_lru_eviction_by_bytes(self):
        from elasticsearch_tpu.index.request_cache import RequestCache

        cache = RequestCache(max_bytes=3000)
        for i in range(50):
            cache.put(f"k{i}", {"payload": "x" * 100, "i": i})
        s = cache.stats()
        assert s["evictions"] > 0
        assert s["memory_size_in_bytes"] <= 3000
        # most recent entries survive
        assert cache.get("k49") is not None
