"""End-to-end search tests: index -> refresh -> query DSL -> hits.

Mirrors the reference's REST-level search semantics (rest-api-spec tests)
at the IndexService level.
"""

import pytest

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def books():
    idx = IndexService("books", Settings({"index.number_of_shards": 1}))
    docs = [
        {"title": "The Quick Brown Fox", "body": "the quick brown fox jumps over the lazy dog",
         "price": 10, "tag": "animals", "published": "2017-01-15", "in_stock": True},
        {"title": "Fox Hunting History", "body": "a history of fox hunting in england",
         "price": 25, "tag": "history", "published": "2016-06-01", "in_stock": False},
        {"title": "Quick Cooking", "body": "quick and easy recipes for busy people",
         "price": 15, "tag": "cooking", "published": "2017-11-20", "in_stock": True},
        {"title": "The Lazy Gardener", "body": "gardening for people who hate gardening",
         "price": 30, "tag": "hobby", "published": "2015-03-10", "in_stock": True},
        {"title": "Dog Training", "body": "train your dog quickly with positive methods",
         "price": 20, "tag": "animals", "published": "2016-12-25", "in_stock": False},
    ]
    for i, d in enumerate(docs):
        idx.index_doc(str(i + 1), d)
    idx.refresh()
    yield idx
    idx.close()


def hit_ids(resp):
    return [h["_id"] for h in resp["hits"]["hits"]]


class TestMatch:
    def test_match_basic(self, books):
        r = books.search({"query": {"match": {"body": "fox"}}})
        assert set(hit_ids(r)) == {"1", "2"}
        assert r["hits"]["total"] == 2
        assert r["hits"]["hits"][0]["_score"] > 0
        assert r["hits"]["max_score"] == r["hits"]["hits"][0]["_score"]

    def test_match_or_vs_and(self, books):
        r_or = books.search({"query": {"match": {"body": "quick dog"}}})
        assert set(hit_ids(r_or)) == {"1", "3", "5"}
        # standard analyzer does not stem: doc5 has "quickly", not "quick"
        r_and = books.search({"query": {"match": {"body": {"query": "quick dog", "operator": "and"}}}})
        assert set(hit_ids(r_and)) == {"1"}

    def test_match_analyzes_query(self, books):
        r = books.search({"query": {"match": {"body": "FOX!"}}})
        assert set(hit_ids(r)) == {"1", "2"}

    def test_match_all_and_none(self, books):
        assert books.search({"query": {"match_all": {}}})["hits"]["total"] == 5
        assert books.search({"query": {"match_none": {}}})["hits"]["total"] == 0
        assert books.search({})["hits"]["total"] == 5

    def test_match_on_numeric_field(self, books):
        r = books.search({"query": {"match": {"price": 25}}})
        assert hit_ids(r) == ["2"]

    def test_match_phrase(self, books):
        r = books.search({"query": {"match_phrase": {"body": "quick brown fox"}}})
        assert hit_ids(r) == ["1"]
        r2 = books.search({"query": {"match_phrase": {"body": "brown quick"}}})
        assert r2["hits"]["total"] == 0

    def test_multi_match(self, books):
        r = books.search({"query": {"multi_match": {
            "query": "fox", "fields": ["title", "body"]}}})
        assert set(hit_ids(r)) == {"1", "2"}
        r2 = books.search({"query": {"multi_match": {
            "query": "quick", "fields": ["title^3", "body"]}}})
        # title match boosted: docs 1,3 have quick in title
        assert set(hit_ids(r2)) >= {"1", "3"}


class TestTermLevel:
    def test_term_keyword(self, books):
        r = books.search({"query": {"term": {"tag": "animals"}}})
        assert set(hit_ids(r)) == {"1", "5"}

    def test_terms(self, books):
        r = books.search({"query": {"terms": {"tag": ["history", "hobby"]}}})
        assert set(hit_ids(r)) == {"2", "4"}

    def test_term_numeric(self, books):
        r = books.search({"query": {"term": {"price": 15}}})
        assert hit_ids(r) == ["3"]

    def test_term_boolean(self, books):
        r = books.search({"query": {"term": {"in_stock": True}}})
        assert set(hit_ids(r)) == {"1", "3", "4"}

    def test_range_numeric(self, books):
        r = books.search({"query": {"range": {"price": {"gte": 15, "lte": 25}}}})
        assert set(hit_ids(r)) == {"2", "3", "5"}
        r2 = books.search({"query": {"range": {"price": {"gt": 15, "lt": 25}}}})
        assert set(hit_ids(r2)) == {"5"}

    def test_range_date(self, books):
        r = books.search({"query": {"range": {"published": {"gte": "2017-01-01"}}}})
        assert set(hit_ids(r)) == {"1", "3"}

    def test_exists(self, books):
        books.index_doc("6", {"title": "no body here"})
        books.refresh()
        r = books.search({"query": {"exists": {"field": "body"}}})
        assert "6" not in hit_ids(r)
        assert r["hits"]["total"] == 5

    def test_ids(self, books):
        r = books.search({"query": {"ids": {"values": ["2", "4", "404"]}}})
        assert set(hit_ids(r)) == {"2", "4"}

    def test_prefix(self, books):
        r = books.search({"query": {"prefix": {"body": "gard"}}})
        assert set(hit_ids(r)) == {"4"}

    def test_wildcard(self, books):
        r = books.search({"query": {"wildcard": {"body": "rec*es"}}})
        assert hit_ids(r) == ["3"]

    def test_regexp(self, books):
        r = books.search({"query": {"regexp": {"tag": "h.*y"}}})
        assert set(hit_ids(r)) == {"2", "4"}

    def test_fuzzy(self, books):
        r = books.search({"query": {"fuzzy": {"body": "quik"}}})
        assert set(hit_ids(r)) >= {"3"}


class TestBool:
    def test_bool_must_filter(self, books):
        r = books.search({"query": {"bool": {
            "must": [{"match": {"body": "quick"}}],
            "filter": [{"range": {"price": {"lte": 15}}}],
        }}})
        assert set(hit_ids(r)) == {"1", "3"}

    def test_bool_must_not(self, books):
        r = books.search({"query": {"bool": {
            "must": [{"match_all": {}}],
            "must_not": [{"term": {"tag": "animals"}}],
        }}})
        assert set(hit_ids(r)) == {"2", "3", "4"}

    def test_bool_should_msm(self, books):
        r = books.search({"query": {"bool": {
            "should": [
                {"match": {"body": "quick"}},
                {"match": {"body": "dog"}},
                {"term": {"tag": "cooking"}},
            ],
            "minimum_should_match": 2,
        }}})
        # doc1: quick+dog; doc3: quick+cooking; doc5: quick(body? 'quickly'->stem?)+dog
        assert "1" in hit_ids(r) and "3" in hit_ids(r)

    def test_filter_only_scores_zero(self, books):
        r = books.search({"query": {"bool": {"filter": [{"term": {"tag": "history"}}]}}})
        assert hit_ids(r) == ["2"]
        assert r["hits"]["hits"][0]["_score"] == 0.0

    def test_constant_score(self, books):
        r = books.search({"query": {"constant_score": {
            "filter": {"term": {"tag": "history"}}, "boost": 3.0}}})
        assert r["hits"]["hits"][0]["_score"] == 3.0


class TestSortPagination:
    def test_sort_numeric_asc(self, books):
        r = books.search({"query": {"match_all": {}}, "sort": [{"price": "asc"}]})
        assert hit_ids(r) == ["1", "3", "5", "2", "4"]
        assert r["hits"]["hits"][0]["sort"] == [10.0]
        assert r["hits"]["hits"][0]["_score"] is None

    def test_sort_desc_with_from_size(self, books):
        r = books.search({
            "query": {"match_all": {}}, "sort": [{"price": "desc"}],
            "from": 1, "size": 2,
        })
        assert hit_ids(r) == ["2", "5"]

    def test_sort_keyword(self, books):
        r = books.search({"query": {"match_all": {}}, "sort": [{"tag": "asc"}]})
        # animals(1,5) < cooking(3) < history(2) < hobby(4)
        assert hit_ids(r)[:2] == ["1", "5"] or hit_ids(r)[:2] == ["5", "1"]
        assert hit_ids(r)[2:] == ["3", "2", "4"]

    def test_sort_date(self, books):
        r = books.search({"query": {"match_all": {}}, "sort": [{"published": "desc"}]})
        assert hit_ids(r) == ["3", "1", "5", "2", "4"]

    def test_search_after(self, books):
        r1 = books.search({"query": {"match_all": {}}, "sort": [{"price": "asc"}], "size": 2})
        after = r1["hits"]["hits"][-1]["sort"]
        r2 = books.search({
            "query": {"match_all": {}}, "sort": [{"price": "asc"}],
            "size": 2, "search_after": after,
        })
        assert hit_ids(r2) == ["5", "2"]

    def test_size_zero(self, books):
        r = books.search({"query": {"match": {"body": "fox"}}, "size": 0})
        assert r["hits"]["hits"] == []
        assert r["hits"]["total"] == 2


class TestSourceFiltering:
    def test_source_false(self, books):
        r = books.search({"query": {"ids": {"values": ["1"]}}, "_source": False})
        assert "_source" not in r["hits"]["hits"][0]

    def test_source_includes_excludes(self, books):
        r = books.search({
            "query": {"ids": {"values": ["1"]}},
            "_source": {"includes": ["title", "price"]},
        })
        assert set(r["hits"]["hits"][0]["_source"]) == {"title", "price"}
        r2 = books.search({
            "query": {"ids": {"values": ["1"]}},
            "_source": {"excludes": ["body", "tag"]},
        })
        src = r2["hits"]["hits"][0]["_source"]
        assert "body" not in src and "title" in src

    def test_docvalue_fields(self, books):
        r = books.search({
            "query": {"ids": {"values": ["2"]}},
            "docvalue_fields": ["price", "tag"],
        })
        f = r["hits"]["hits"][0]["fields"]
        assert f["price"] == [25.0]
        assert f["tag"] == ["history"]


class TestOtherQueries:
    def test_dis_max(self, books):
        r = books.search({"query": {"dis_max": {"queries": [
            {"match": {"title": "fox"}}, {"match": {"body": "fox"}},
        ]}}})
        assert set(hit_ids(r)) == {"1", "2"}

    def test_function_score_field_value_factor(self, books):
        r = books.search({"query": {"function_score": {
            "query": {"match_all": {}},
            "field_value_factor": {"field": "price", "factor": 1.0},
            "boost_mode": "replace",
        }}})
        assert hit_ids(r) == ["4", "2", "5", "3", "1"]  # sorted by price

    def test_query_string(self, books):
        r = books.search({"query": {"query_string": {
            "query": "body:fox AND tag:history"}}})
        assert hit_ids(r) == ["2"]

    def test_query_string_default_fields(self, books):
        r = books.search({"query": {"query_string": {"query": "gardening"}}})
        assert hit_ids(r) == ["4"]

    def test_more_like_this(self, books):
        r = books.search({"query": {"more_like_this": {
            "fields": ["body"], "like": [{"_id": "1"}],
            "min_term_freq": 1, "minimum_should_match": "1%",
        }}})
        assert "5" in hit_ids(r) or "2" in hit_ids(r)  # dog / fox overlap

    def test_unknown_query_rejected(self, books):
        with pytest.raises(ParsingException):
            books.search({"query": {"bogus_query": {}}})

    def test_min_score(self, books):
        r_all = books.search({"query": {"match": {"body": "fox"}}})
        low = min(h["_score"] for h in r_all["hits"]["hits"])
        hi = max(h["_score"] for h in r_all["hits"]["hits"])
        r = books.search({"query": {"match": {"body": "fox"}},
                          "min_score": (low + hi) / 2})
        assert r["hits"]["total"] == 1

    def test_post_filter(self, books):
        r = books.search({
            "query": {"match": {"body": "quick"}},
            "post_filter": {"term": {"tag": "cooking"}},
            "aggs": {"tags": {"terms": {"field": "tag"}}},
        })
        assert hit_ids(r) == ["3"]
        # aggs ignore post_filter (see FilteredSearchIT semantics)
        agg_tags = {b["key"] for b in r["aggregations"]["tags"]["buckets"]}
        assert agg_tags == {"animals", "cooking"}


class TestHighlight:
    def test_highlight_basic(self, books):
        r = books.search({
            "query": {"match": {"body": "fox"}},
            "highlight": {"fields": {"body": {}}},
        })
        h = r["hits"]["hits"][0]
        assert "<em>fox</em>" in h["highlight"]["body"][0]


class TestMultiShard:
    def test_results_merge_across_shards(self):
        idx = IndexService("multi", Settings({"index.number_of_shards": 4}))
        for i in range(50):
            idx.index_doc(str(i), {"n": i, "text": "common term here"})
        idx.refresh()
        r = idx.search({"query": {"match": {"text": "common"}},
                        "sort": [{"n": "asc"}], "size": 10})
        assert hit_ids(r) == [str(i) for i in range(10)]
        assert r["hits"]["total"] == 50
        assert r["_shards"]["total"] == 4
        idx.close()

    def test_scores_comparable_across_shards(self):
        # refresh pinned off: the 1s background refresh firing MID-LOOP
        # (slow machine) splits segments, per-segment avgdl diverges, and
        # the cross-shard score comparison this test makes goes flaky
        idx = IndexService("multi2", Settings({
            "index.number_of_shards": 2, "index.refresh_interval": -1}))
        for i in range(20):
            idx.index_doc(str(i), {"text": "alpha beta" if i % 2 else "alpha"})
        idx.refresh()
        r = idx.search({"query": {"match": {"text": "alpha"}}, "size": 20})
        assert r["hits"]["total"] == 20
        # shorter docs (just "alpha") score higher regardless of shard
        top_half = hit_ids(r)[:10]
        assert all(int(i) % 2 == 0 for i in top_half)
        idx.close()


class TestUpdateAndGet:
    def test_update_merge(self, books):
        books.update_doc("1", {"doc": {"price": 11}})
        g = books.get_doc("1")
        assert g.source["price"] == 11
        assert g.source["title"] == "The Quick Brown Fox"

    def test_update_noop(self, books):
        r = books.update_doc("1", {"doc": {"price": 10}})
        assert r["result"] == "noop"

    def test_upsert(self, books):
        r = books.update_doc("99", {"doc": {"x": 1}, "doc_as_upsert": True})
        assert r["result"] == "created"

    def test_count(self, books):
        assert books.count({"query": {"match": {"body": "fox"}}})["count"] == 2
