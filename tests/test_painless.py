"""Painless-class scripting engine (script/painless.py).

Role model: modules/lang-painless (Compiler.java) — same surface
(statements, Java-ish method whitelists, doc values, ctx mutation, loop
budget), interpreted host-side; the numeric subset keeps routing to the
expression engine's vectorized path (script/expression.py), asserted here
too."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.script.expression import CompiledScript, compile_script
from elasticsearch_tpu.script.painless import (
    PainlessScript,
    ScriptException,
    execute_update_script,
)


class TestLanguage:
    def run(self, src, **bindings):
        return PainlessScript(src).run(bindings)

    def test_arithmetic_and_types(self):
        assert self.run("return 7 / 2") == 3          # java int division
        assert self.run("return 7.0 / 2") == 3.5
        assert self.run("return -7 / 2") == -3        # truncate toward zero
        assert self.run("return -7 % 3") == -1        # sign of dividend
        assert self.run("return 2 + 3 * 4") == 14
        assert self.run("return (int) 3.9") == 3
        assert self.run("return 'a' + 1 + 2") == "a12"

    def test_control_flow(self):
        src = """
        int total = 0;
        for (int i = 0; i < 10; i++) {
          if (i % 2 == 0) { continue }
          if (i > 7) { break }
          total += i;
        }
        return total;
        """
        assert self.run(src) == 1 + 3 + 5 + 7

    def test_while_and_ternary(self):
        src = "int n = 0; while (n < 5) { n++ } return n > 4 ? 'big' : 'small'"
        assert self.run(src) == "big"

    def test_foreach_list_and_map(self):
        src = """
        def m = ['a': 1, 'b': 2];
        def keys = '';
        for (def k : m) { keys += k }
        def total = 0;
        for (def v : m.values()) { total += v }
        return keys + total;
        """
        assert self.run(src) == "ab3"

    def test_collections_methods(self):
        src = """
        List l = new ArrayList();
        l.add(3); l.add(1); l.add(2);
        l.sort();
        Map m = new HashMap();
        m.put('first', l.get(0));
        m.put('n', l.size());
        return m['first'] + m.getOrDefault('n', 0) + l.indexOf(2);
        """
        assert self.run(src) == 1 + 3 + 1

    def test_string_methods(self):
        src = """
        String s = ' Hello,World ';
        def t = s.trim();
        def parts = t.split(',');
        return parts[0].toLowerCase() + '|' + parts[1].substring(0, 3)
               + '|' + t.length();
        """
        assert self.run(src) == "hello|Wor|11"

    def test_math_and_statics(self):
        assert self.run("return Math.max(2, Math.abs(-5))") == 5
        assert self.run("return Math.floor(Math.PI)") == 3
        assert self.run("return Integer.parseInt('42') + 1") == 43
        assert self.run("return String.valueOf(1.5)") == "1.5"

    def test_null_and_safe_navigation(self):
        assert self.run("def x = null; return x ?: 'd'") == "d"
        assert self.run("def x = null; return x?.length()") is None
        with pytest.raises(ScriptException):
            self.run("def x = null; return x.length()")

    def test_elvis_chains_and_instanceof(self):
        assert self.run("def x = 'a'; return x instanceof String") is True
        assert self.run("def x = [1]; return x instanceof Map") is False

    def test_loop_budget_guard(self):
        with pytest.raises(ScriptException, match="budget"):
            self.run("while (true) { }")
        with pytest.raises(ScriptException, match="budget"):
            self.run("for (int i = 0; i >= 0; i) { def x = 1 }")

    def test_compile_errors(self):
        with pytest.raises(ScriptException):
            PainlessScript("def x = ")
        with pytest.raises(ScriptException):
            PainlessScript("return 'unterminated")
        with pytest.raises(ScriptException):
            PainlessScript("x +++")

    def test_no_python_internals_reachable(self):
        for src in (
            "return ''.__class__",
            "def x = [1]; return x.__len__()",
            "return params.size.__globals__",
        ):
            with pytest.raises(ScriptException):
                self.run(src, params={})
        # map field access is painless get() shorthand: missing -> null,
        # never a python attribute
        assert self.run("return params.__globals__", params={}) is None

    def test_doc_values_semantics(self):
        s = PainlessScript(
            "if (doc['p'].size() == 0) { return -1 } return doc['p'].value")
        assert s.execute({"p": 4.0}) == 4.0
        assert s.execute({}) == -1
        # .value on a missing field raises, like the reference
        with pytest.raises(ScriptException, match="doesn't have a value"):
            PainlessScript("return doc['p'].value").execute({})


class TestDispatch:
    def test_numeric_source_uses_expression_engine(self):
        s = compile_script("doc['a'].value * 2")
        assert isinstance(s, CompiledScript)

    def test_painless_source_uses_interpreter(self):
        s = compile_script({"source": "def x = 1; return x"})
        assert isinstance(s, PainlessScript)

    def test_lang_expression_rejects_statements(self):
        with pytest.raises(ParsingException):
            compile_script({"lang": "expression",
                            "source": "def x = 1; return x"})


class TestContexts:
    @pytest.fixture()
    def idx(self):
        idx = IndexService("scripted", Settings.EMPTY, {
            "properties": {
                "title": {"type": "text"},
                "tag": {"type": "keyword"},
                "n": {"type": "integer"},
                "price": {"type": "float"},
            }})
        for i in range(8):
            idx.index_doc(str(i), {
                "title": f"doc {i}", "tag": "even" if i % 2 == 0 else "odd",
                "n": i, "price": i * 2.0})
        idx.refresh()
        yield idx
        idx.close()

    def test_scripted_update(self, idx):
        r = idx.update_doc("3", {"script": {
            "source": "ctx._source.n += params.by; "
                      "ctx._source.tags = ['updated']",
            "params": {"by": 10}}})
        assert r["result"] == "updated"
        g = idx.get_doc("3")
        assert g.source["n"] == 13
        assert g.source["tags"] == ["updated"]

    def test_scripted_update_noop_and_delete(self, idx):
        r = idx.update_doc("2", {"script": {"source": "ctx.op = 'none'"}})
        assert r["result"] == "noop"
        r = idx.update_doc("2", {"script": {
            "source": "if (ctx._source.n == 2) { ctx.op = 'delete' }"}})
        assert r["result"] == "deleted"
        assert not idx.get_doc("2").found

    def test_noop_script_cannot_corrupt_live_source(self, idx):
        """A script that mutates a NESTED object then sets ctx.op='none'
        must leave the stored doc untouched: ctx._source is a deep copy,
        not the live buffer/segment dict (a shallow copy would let the
        mutation bypass versioning and the translog, so flush/recovery
        would silently revert the visible data)."""
        idx.index_doc("nested1", {"obj": {"inner": 1}, "n": 0})
        before = idx.get_doc("nested1")
        r = idx.update_doc("nested1", {"script": {
            "source": "ctx._source.obj.inner = 999; ctx.op = 'none'"}})
        assert r["result"] == "noop"
        after = idx.get_doc("nested1")
        assert after.source["obj"]["inner"] == 1
        assert after.version == before.version

    def test_scripted_upsert(self, idx):
        r = idx.update_doc("99", {
            "scripted_upsert": True,
            "upsert": {"n": 0},
            "script": {"source": "ctx._source.n += 5"}})
        assert r["result"] == "created"
        assert idx.get_doc("99").source["n"] == 5

    def test_script_fields_painless_strings(self, idx):
        r = idx.search({
            "query": {"term": {"tag": "even"}},
            "script_fields": {
                "label": {"script": {
                    "source": "return doc['tag'].value.toUpperCase() + '-' "
                              "+ (int) doc['n'].value",
                }},
            }, "size": 1, "sort": [{"n": "asc"}]})
        hit = r["hits"]["hits"][0]
        assert hit["fields"]["label"] == ["EVEN-0"]

    def test_script_query_painless(self, idx):
        r = idx.search({"query": {"bool": {"filter": [{"script": {"script": {
            "source": "if (doc['n'].size() == 0) { return false } "
                      "def v = doc['n'].value; return v % 3 == 0"
        }}}]}}, "size": 10})
        ids = sorted(h["_id"] for h in r["hits"]["hits"])
        assert ids == ["0", "3", "6"]

    def test_ingest_script_processor(self):
        from elasticsearch_tpu.ingest.pipeline import IngestDocument, PROCESSORS

        doc = IngestDocument({"a": 2, "tags": ["x"]}, "1", "i")
        PROCESSORS["script"](
            {"source": "ctx.b = ctx.a * 3; ctx.tags.add('scripted')",
             "params": {}}, doc)
        assert doc.source["b"] == 6
        assert doc.source["tags"] == ["x", "scripted"]


class TestUpdateScriptHelper:
    def test_invalid_op_rejected(self):
        with pytest.raises(ScriptException, match="not allowed"):
            execute_update_script(
                PainlessScript("ctx.op = 'explode'"), {"a": 1})


class TestByQueryScripts:
    @pytest.fixture()
    def node(self):
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from elasticsearch_tpu.node import Node

        n = Node()
        n.create_index("src", {"mappings": {"properties": {
            "n": {"type": "integer"}, "kind": {"type": "keyword"}}}})
        for i in range(6):
            n.index_doc("src", str(i), {"n": i, "kind": "even" if i % 2 == 0
                                        else "odd"})
        n.indices["src"].refresh()
        yield n
        n.close()

    def test_update_by_query_with_script(self, node):
        r = node.indices["src"]  # noqa: F841 — warm reference
        from elasticsearch_tpu.index.reindex import update_by_query

        out = update_by_query(node, "src", {
            "query": {"term": {"kind": "odd"}},
            "script": {"source": "ctx._source.n += params.by",
                       "params": {"by": 100}}})
        assert out["updated"] == 3 and out["noops"] == 0
        assert node.get_doc("src", "1")["_source"]["n"] == 101
        assert node.get_doc("src", "0")["_source"]["n"] == 0  # untouched

    def test_update_by_query_ctx_op(self, node):
        from elasticsearch_tpu.index.reindex import update_by_query

        out = update_by_query(node, "src", {"script": {"source": """
            if (ctx._source.n == 0) { ctx.op = 'delete' }
            else if (ctx._source.kind == 'odd') { ctx.op = 'none' }
            else { ctx._source.touched = true }
        """}})
        assert out["deleted"] == 1
        assert out["noops"] == 3
        assert out["updated"] == 2
        assert not node.get_doc("src", "0")["found"]
        assert node.get_doc("src", "2")["_source"]["touched"] is True

    def test_reindex_with_script(self, node):
        from elasticsearch_tpu.index.reindex import reindex

        out = reindex(node, {
            "source": {"index": "src"},
            "dest": {"index": "dst"},
            "script": {"source": "if (ctx._source.kind == 'odd') "
                                 "{ ctx.op = 'none' } "
                                 "else { ctx._source.copied = true }"}})
        assert out["created"] == 3
        node.indices["dst"].refresh()
        r = node.search("dst", {"query": {"match_all": {}}, "size": 10})
        assert r["hits"]["total"] == 3
        assert all(h["_source"]["copied"] is True for h in r["hits"]["hits"])

    def test_reindex_script_counts_and_multibatch(self, node):
        """A batch whose docs ALL noop must not end the scan, and
        noops/deleted must be reported (total == created+updated+noops
        +deleted)."""
        from elasticsearch_tpu.index.reindex import reindex

        out = reindex(node, {
            "source": {"index": "src", "size": 2},  # 3 batches of 2
            "dest": {"index": "dst2"},
            "script": {"source": "if (ctx._source.n < 4) "
                                 "{ ctx.op = 'noop' }"}})
        # docs 0-3 noop (incl. the ENTIRE first two batches); 4,5 copy
        assert out["noops"] == 4
        assert out["created"] == 2
        assert out["total"] == 6
        assert out["total"] == (out["created"] + out["updated"]
                                + out["noops"] + out["deleted"])

    def test_reindex_script_ctx_op_create(self, node):
        """ctx.op='create' in a reindex script must emit a CREATE bulk
        action even when dest.op_type is the default 'index': existing
        dest docs become conflicts instead of being overwritten
        (AbstractAsyncBulkByScrollAction honors the script-returned op)."""
        from elasticsearch_tpu.index.reindex import reindex

        node.create_index("dstc", {"mappings": {"properties": {
            "n": {"type": "integer"}}}})
        node.index_doc("dstc", "0", {"n": -777})  # pre-existing dest doc
        node.indices["dstc"].refresh()
        out = reindex(node, {
            "source": {"index": "src"},
            "dest": {"index": "dstc"},  # op_type defaults to 'index'
            "script": {"source": "ctx.op = 'create'"}})
        # doc 0 conflicts (already present), the other 5 are created
        assert out["created"] == 5
        assert len(out["failures"]) == 1
        assert node.get_doc("dstc", "0")["_source"]["n"] == -777

    def test_reindex_script_id_rewrite(self, node):
        from elasticsearch_tpu.index.reindex import reindex

        reindex(node, {
            "source": {"index": "src"},
            "dest": {"index": "dst3"},
            "script": {"source": "ctx._id = ctx._id + '-v2'"}})
        node.indices["dst3"].refresh()
        assert node.get_doc("dst3", "0-v2")["found"]
        assert not node.get_doc("dst3", "0")["found"]

    def test_script_noop_does_not_corrupt_source(self, node):
        """A script mutating a NESTED object then nooping must not alter
        the stored source (deep-copy contract)."""
        from elasticsearch_tpu.index.reindex import update_by_query

        node.index_doc("src", "nested", {"n": 50, "meta": {"flag": False}})
        node.indices["src"].refresh()
        update_by_query(node, "src", {
            "query": {"term": {"n": 50}},
            "script": {"source": "ctx._source.meta.flag = true; "
                                 "ctx.op = 'none'"}})
        assert node.get_doc("src", "nested")["_source"]["meta"]["flag"] \
            is False
