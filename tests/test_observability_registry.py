"""Observability-registry lint: every counter/histogram key exported by
the ``_stats`` / ``_nodes/stats`` search sections must be documented in
docs/OBSERVABILITY.md.

Mirror of test_settings_registry.py: an undocumented stats key silently
ships an operator surface nobody can discover or rely on — this tier-1
lint walks the REAL response shapes and fails on drift, so new
telemetry must land in the doc first.
"""

import os

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService

DOC_PATH = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                        "docs", "OBSERVABILITY.md")


def _doc_text():
    with open(DOC_PATH, encoding="utf-8") as f:
        return f.read()


def _walk_keys(obj, out, skip_subtrees=("groups", "tenants"),
               split_subtrees=("decisions",)):
    """Collect every dict key in the response, skipping log2 bucket
    labels (``le_*``), numeric keys (batch-size histogram buckets), and
    the user-named ``groups``/``tenants`` subtrees (tenant keys are
    client-chosen X-Opaque-Id values — docs/OVERLOAD.md); ``decisions``
    keys are ``<plane>.<reason>`` compounds — each part collects
    separately."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            ks = str(k)
            if ks.isdigit() or ks.startswith("le_"):
                continue
            if ks in split_subtrees:
                out.add(ks)
                for ck in v:
                    out.update(str(ck).split("."))
                continue
            out.add(ks)
            if ks in skip_subtrees:
                continue
            _walk_keys(v, out, skip_subtrees, split_subtrees)
    elif isinstance(obj, list):
        for v in obj:
            _walk_keys(v, out, skip_subtrees, split_subtrees)


@pytest.fixture(scope="module")
def exercised_index():
    idx = IndexService("obslint", Settings({
        "index.number_of_shards": 2,
        "index.refresh_interval": -1,
    }), mapping={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    for d in range(12):
        idx.index_doc(str(d), {"body": f"w{d % 3} common"})
    idx.refresh()
    # populate the phase histograms / decision counters with real
    # traffic (whatever plane serves on this backend)
    idx.search({"query": {"match": {"body": "common"}}, "size": 3})
    idx.search({"query": {"match": {"body": "w1"}}, "size": 3,
                "profile": True})
    yield idx
    idx.close()


class TestObservabilityRegistryLint:
    def test_index_search_stats_keys_documented(self, exercised_index):
        doc = _doc_text()
        keys: set = set()
        _walk_keys(exercised_index.search_stats(), keys)
        missing = sorted(k for k in keys if k not in doc)
        assert not missing, (
            f"_stats search keys absent from docs/OBSERVABILITY.md: "
            f"{missing} — document every exported counter/histogram "
            f"(phase taxonomy, plane names, and ladder-decision reasons "
            f"included) before shipping it")

    def test_node_stats_search_keys_documented(self, exercised_index):
        from elasticsearch_tpu.search.telemetry import merge_phase_stats

        doc = _doc_text()
        merged = merge_phase_stats([exercised_index.search_stats()])
        keys: set = set()
        _walk_keys(merged, keys)
        missing = sorted(k for k in keys if k not in doc)
        assert not missing, (
            f"_nodes/stats search keys absent from docs/OBSERVABILITY.md:"
            f" {missing}")

    def test_lint_actually_sees_known_keys(self, exercised_index):
        # the lint is only trustworthy if the walk reaches the real
        # structure: anchor on keys known to exist today
        keys: set = set()
        _walk_keys(exercised_index.search_stats(), keys)
        for known in ("phases", "histogram_us", "counters", "decisions",
                      "taxonomy", "queries_recorded", "planes", "batch",
                      "quarantine_events", "plane_failures_total",
                      "admission", "brownout_level"):
            assert known in keys, f"lint walk no longer reaches [{known}]"

    def test_admission_block_exported_and_documented(self, exercised_index):
        # ISSUE 12 (docs/OVERLOAD.md): the `search.admission` block —
        # queue gauges, admitted/rejected/expired counters, brownout
        # ladder state + per-step shed counts, Retry-After — exported in
        # _stats and merged into _nodes/stats, every key documented
        doc = _doc_text()
        adm = exercised_index.search_stats()["admission"]
        for key in ("queue_capacity", "queued", "in_flight",
                    "admitted_total", "rejected_total",
                    "expired_in_queue_total", "brownout_level",
                    "brownout", "brownout_transitions", "retry_after_s",
                    "drain_rate_qps", "tenants"):
            assert key in adm, adm.keys()
            assert key in doc, f"[{key}] undocumented"
        for step in ("forced_pruned_total", "shed_rescore_total",
                     "shed_features_total"):
            assert step in adm["brownout"], adm["brownout"]
            assert step in doc, f"[{step}] undocumented"
        # the exercised traffic was admitted and accounted
        assert adm["admitted_total"] >= 2
        assert "_anonymous" in adm["tenants"]
        # batch block: the adaptive-window gauge rides beside the
        # batch-size histogram
        batch = exercised_index.search_stats()["batch"]
        assert "batch_window_effective_ms" in batch
        assert "batch_window_effective_ms" in doc

    def test_fused_agg_counters_exported_and_documented(
            self, exercised_index):
        # ISSUE 13 (docs/AGGS.md): the fused-aggregation plane's
        # adoption counters — and the fallback-reason vocabulary — are
        # part of the documented operator surface
        doc = _doc_text()
        planes = exercised_index.search_stats()["planes"]
        for key in ("agg_fused_query_total", "agg_host_fallback_total",
                    "agg_host_fallback_by_reason"):
            assert key in planes, planes.keys()
            assert key in doc, f"[{key}] undocumented"
        # the `aggregate` phase joined the taxonomy ring
        phases = exercised_index.search_stats()["phases"]
        assert "aggregate" in phases["taxonomy"]
        assert "aggregate" in doc
        for reason in ("disabled", "unsupported_agg", "sub_aggs",
                       "multi_valued", "values_not_fusable",
                       "bucket_range", "unsupported_params",
                       "field_ineligible", "resolve_error"):
            assert reason in doc, f"fallback reason [{reason}] undocumented"

    def test_compile_block_exported_and_documented(self, exercised_index):
        # ISSUE 14 (docs/RESILIENCE.md "Rollout & drain"): the compile
        # plane's counters — persistent-cache hit/miss, warmed
        # programs, query-path first compiles, the stall histogram —
        # are part of the documented operator surface, as are the
        # admission drain keys
        doc = _doc_text()
        comp = exercised_index.search_stats()["compile"]
        for key in ("cache_enabled", "cache_path", "variants_recorded",
                    "compile_cache_hit_total", "compile_cache_miss_total",
                    "programs_warmed_total",
                    "query_path_first_compile_total",
                    "first_compile_stall_ms", "first_compile_events"):
            assert key in comp, comp.keys()
            assert key in doc, f"[{key}] undocumented"
        adm = exercised_index.search_stats()["admission"]
        for key in ("draining", "drain_rejected_total"):
            assert key in adm, adm.keys()
            assert key in doc, f"[{key}] undocumented"

    def test_lint_catches_undocumented_key(self):
        doc = _doc_text()
        keys: set = set()
        _walk_keys({"phases": {"totally_undocumented_key_xyz": 1}}, keys)
        assert "totally_undocumented_key_xyz" in keys
        assert "totally_undocumented_key_xyz" not in doc

    def test_device_memory_stats_keys_documented(self, exercised_index):
        # the search.memory block (ISSUE 9): ledger byte sums, staging/
        # eviction event rings, restage amplification — every exported
        # key (kind names included) must be in docs/OBSERVABILITY.md
        doc = _doc_text()
        mem = exercised_index.search_stats()["memory"]
        keys: set = set()
        _walk_keys(mem, keys)
        missing = sorted(k for k in keys if k not in doc)
        assert not missing, (
            f"search.memory keys absent from docs/OBSERVABILITY.md: "
            f"{missing}")
        from elasticsearch_tpu.common.memory import KINDS

        for kind in KINDS:
            assert kind in mem["staged_bytes"], mem["staged_bytes"]
            assert kind in doc, f"ledger kind [{kind}] undocumented"

    def test_integrity_stats_keys_documented(self, exercised_index):
        # ISSUE 16: the `search.integrity` block — detection counters
        # with the per-site split, marker lifecycle counters + event
        # ring, scrub counters — every exported key (site names
        # included) must be in docs/OBSERVABILITY.md
        doc = _doc_text()
        integ = exercised_index.search_stats()["integrity"]
        keys: set = set()
        _walk_keys(integ, keys)
        missing = sorted(k for k in keys if k not in doc)
        assert not missing, (
            f"search.integrity keys absent from docs/OBSERVABILITY.md: "
            f"{missing}")
        from elasticsearch_tpu.common.integrity import SITES

        for site in SITES:
            assert site in integ["corruption_detected_by_site"], integ
            assert site in doc, f"detection site [{site}] undocumented"
        # the marker-event vocabulary (action values + event fields) is
        # part of the documented operator surface
        for word in ("detected", "marked", "cleared", "drift",
                     "action", "marker", "reason", "timestamp_ms"):
            assert word in doc, f"event vocabulary [{word}] undocumented"

    def test_staging_fault_counters_documented_and_exported(
            self, exercised_index):
        # ISSUE 10: the classified staging-fault model must export its
        # counters (search.memory) and the plane-probe/reason split
        # (search.planes) — and every key must be documented
        doc = _doc_text()
        mem = exercised_index.search_stats()["memory"]
        for key in ("staging_retries_total",
                    "staging_faults_transient_total",
                    "staging_faults_deterministic_total",
                    "staging_fault_events"):
            assert key in mem, mem.keys()
            assert key in doc, f"[{key}] undocumented"
        planes = exercised_index.search_stats()["planes"]
        for key in ("plane_failures_by_reason", "plane_probes_total"):
            assert key in planes, planes.keys()
            assert key in doc, f"[{key}] undocumented"
        # the quarantine reasons + decision reason are part of the
        # documented vocabulary
        for reason in ("kernel_fault", "staging_fault"):
            assert reason in doc, f"reason [{reason}] undocumented"

    def test_node_breakers_and_transport_keys_documented(self):
        # _nodes/stats breakers (the accounting child mirrors the device
        # ledger) and the PR-2 transport resilience counters must stay
        # documented — OBSERVABILITY.md for the blocks, RESILIENCE.md
        # carries the transport row-level table
        from elasticsearch_tpu.common.breaker import breaker_service
        from elasticsearch_tpu.transport.local import (
            aggregate_transport_stats,
        )

        doc = _doc_text()
        keys: set = set()
        _walk_keys(breaker_service().stats(), keys)
        _walk_keys(aggregate_transport_stats(), keys)
        missing = sorted(k for k in keys if k not in doc)
        assert not missing, (
            f"_nodes/stats breakers/transport keys absent from "
            f"docs/OBSERVABILITY.md: {missing}")
