"""Bit-packed postings codec + block-max pruned scoring (ISSUE 6).

Three layers, all in interpret mode on the CPU backend (the
tests/test_pallas_scoring idiom — identical semantics to the compiled
TPU path):

- codec: pack/quantize round-trip invariants; ``score_tiles`` with
  codec="packed" matches the numpy oracle EXACTLY over the dequantized
  impact factors (the kernel's in-VMEM decode is deterministic f32),
  and within quantization tolerance of the raw oracle; match COUNTS are
  bit-exact (quantization preserves the frac > 0 posting-validity rule).
- pruning: the per-(tile, query) block-max bound dominates every in-tile
  doc score (property-tested over random corpora), so the pruned top-k
  equals the exhaustive top-k while skipping tiles; batched pruning
  isolates members (per-query thresholds over union lanes).
- service: the mesh_pallas pruned path matches the exhaustive path,
  exports the ``_pruned`` marker + ``_stats`` counters, falls back to
  exhaustive execution for aggs / minimum_should_match / sort requests,
  and a plane fault under pruning still quarantines exactly once.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.ops import pallas_scoring as psc
from elasticsearch_tpu.ops.pallas_scoring import (
    PACK_FRAC_MASK,
    PACK_FRAC_SCALE,
    QueryLane,
    block_frac_max,
    block_min_max,
    build_live_t,
    build_tile_tables,
    build_tile_tables_batched,
    compute_block_frac,
    dequantize_frac,
    merge_tile_topk,
    merge_tile_topk_batched,
    pack_segment_blocks,
    pad_segment_blocks,
    plan_pruned_tiles,
    quantize_frac,
    reference_scores,
    score_tiles,
    score_tiles_pruned,
    tile_geometry,
    tile_lane_ub,
)
from elasticsearch_tpu.testing.disruption import (
    PlaneFailScheme,
    clear_search_disruptions,
)

from test_pallas_scoring import assert_topk_valid, build_corpus


@pytest.fixture(autouse=True)
def _interpret_kernel(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
    yield
    clear_search_disruptions()


def _staged(bd, frac, live, geom, nd_pad):
    dp, fp = pad_segment_blocks(bd, frac, nd_pad)
    pk = pack_segment_blocks(bd, frac, nd_pad)
    lt = build_live_t(live, geom)
    return (jnp.asarray(dp), jnp.asarray(fp), jnp.asarray(pk),
            jnp.asarray(lt))


class TestPackedCodec:
    def test_quantize_roundtrip_invariants(self):
        rng = np.random.RandomState(0)
        frac = np.where(rng.rand(64, 128) < 0.3, 0.0,
                        rng.rand(64, 128) * psc.PACK_MAX_FRAC * 0.999
                        ).astype(np.float32)
        q = quantize_frac(frac)
        # validity survives the round trip exactly: frac > 0 <-> q > 0
        np.testing.assert_array_equal(q > 0, frac > 0)
        assert q.max() <= PACK_FRAC_MASK
        dq = dequantize_frac(q)
        # lossiness bound: half a quantization step (real postings only;
        # sub-step fracs clamp UP to code 1 so they stay valid)
        real = frac > PACK_FRAC_SCALE
        assert np.abs(dq[real] - frac[real]).max() <= PACK_FRAC_SCALE

    def test_pack_rejects_oversized_doc_space(self):
        docs = np.zeros((1, 128), np.int32)
        frac = np.ones((1, 128), np.float32)
        with pytest.raises(ValueError):
            pack_segment_blocks(docs, frac, psc.PACKED_DOC_CAP * 2)

    def test_codec_resolution(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS_CODEC", "packed")
        assert psc.resolve_postings_codec(None, 1 << 20) == "packed"
        # doc space beyond the packed word's doc bits demotes to raw
        assert psc.resolve_postings_codec(None, 1 << 21) == "raw"
        assert psc.resolve_postings_codec("raw", 1 << 10) == "raw"
        monkeypatch.delenv("ES_TPU_PALLAS_CODEC")
        assert psc.resolve_postings_codec(None, 1 << 10) == "raw"
        assert psc.resolve_postings_codec("packed", 1 << 10) == "packed"

    def test_packed_kernel_parity(self):
        """Dense + top-k outputs over the packed corpus equal the oracle
        over DEQUANTIZED fracs exactly, and the raw oracle approximately
        (the documented quantization tolerance)."""
        rng = np.random.RandomState(1)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 3000, 60)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 40.0,
                                                  np.float32), avgdl=40.0)
        live = np.zeros(nd_pad, np.float32)
        live[:3000] = 1.0
        lanes = [QueryLane(ts_[3], tc[3], 1.4),
                 QueryLane(ts_[10], tc[10], 0.9),
                 QueryLane(ts_[55], tc[55], 2.0)]
        geom = tile_geometry(nd_pad, tile_sub=4)
        bmin, bmax = block_min_max(bd, bt, nd_pad)
        rl, rh, w, cb = build_tile_tables(lanes, bmin, bmax, geom)
        _dp, _fp, pk, lt = _staged(bd, frac, live, geom, nd_pad)
        kw = dict(t_pad=w.shape[1], cb=cb, sub=geom.tile_sub,
                  interpret=True, codec="packed")
        fq = dequantize_frac(quantize_frac(frac))
        ref = reference_scores(bd, fq, lanes, nd_pad)
        ref[live == 0] = 0.0
        # dense: exact vs the dequantized oracle
        od = score_tiles(pk, None, lt, jnp.asarray(rl), jnp.asarray(rh),
                         jnp.asarray(w), dense=True, **kw)
        flat = np.asarray(psc.dense_to_flat(od[0], geom.tile_sub))
        np.testing.assert_allclose(flat, ref, rtol=1e-5)
        # ...and within quantization tolerance of the RAW oracle
        ref_raw = reference_scores(bd, frac, lanes, nd_pad)
        ref_raw[live == 0] = 0.0
        mism = np.abs(flat - ref_raw)
        assert mism.max() <= 3 * len(lanes) * PACK_FRAC_SCALE
        # top-k: exact vs the dequantized oracle
        o = score_tiles(pk, None, lt, jnp.asarray(rl), jnp.asarray(rh),
                        jnp.asarray(w), k=10, **kw)
        top_s, top_d, hits = merge_tile_topk(*o, 10)
        assert int(hits) == int((ref > 0).sum())
        assert_topk_valid(top_s, top_d, ref, 10)

    def test_packed_counts_bit_exact(self):
        """minimum_should_match COUNTS are unaffected by quantization:
        frac > 0 round-trips exactly, so the matched-lane sets agree."""
        rng = np.random.RandomState(2)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 1500, 30)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 20.0,
                                                  np.float32), avgdl=20.0)
        live = np.zeros(nd_pad, np.float32)
        live[:1500] = 1.0
        lanes = [QueryLane(ts_[i], tc[i], 1.0) for i in (1, 5, 9)]
        geom = tile_geometry(nd_pad, tile_sub=4)
        bmin, bmax = block_min_max(bd, bt, nd_pad)
        rl, rh, w, cb = build_tile_tables(lanes, bmin, bmax, geom)
        dp, fp, pk, lt = _staged(bd, frac, live, geom, nd_pad)
        kw = dict(t_pad=w.shape[1], cb=cb, sub=geom.tile_sub,
                  dense=True, with_counts=True, interpret=True)
        raw = score_tiles(dp, fp, lt, jnp.asarray(rl), jnp.asarray(rh),
                          jnp.asarray(w), **kw)
        packed = score_tiles(pk, None, lt, jnp.asarray(rl),
                             jnp.asarray(rh), jnp.asarray(w),
                             codec="packed", **kw)
        np.testing.assert_array_equal(np.asarray(raw[1]),
                                      np.asarray(packed[1]))

    def test_tile_subset_rejects_dense(self):
        rng = np.random.RandomState(3)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 600, 10)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 10.0,
                                                  np.float32), avgdl=10.0)
        geom = tile_geometry(nd_pad, tile_sub=4)
        bmin, bmax = block_min_max(bd, bt, nd_pad)
        rl, rh, w, cb = build_tile_tables(
            [QueryLane(ts_[0], tc[0], 1.0)], bmin, bmax, geom)
        dp, fp, _pk, lt = _staged(
            bd, frac, np.ones(nd_pad, np.float32), geom, nd_pad)
        with pytest.raises(ValueError):
            score_tiles(dp, fp, lt, jnp.asarray(rl), jnp.asarray(rh),
                        jnp.asarray(w), t_pad=w.shape[1], cb=cb,
                        sub=geom.tile_sub, dense=True, interpret=True,
                        tile_ids=jnp.arange(rl.shape[0], dtype=jnp.int32))


class TestBlockMaxPruning:
    def test_bound_dominates_every_tile_score(self):
        """Property test: for random corpora and queries, the summed
        per-(tile, lane) bound dominates EVERY doc's true score within
        its tile — the invariant that makes pruning lossless."""
        for seed in range(4):
            rng = np.random.RandomState(100 + seed)
            bd, bt, ts_, tc, nd_pad = build_corpus(
                rng, rng.randint(800, 4000), 40)
            frac = compute_block_frac(
                bd, bt, np.full(nd_pad + 1, 25.0, np.float32), avgdl=25.0)
            geom = tile_geometry(nd_pad, tile_sub=4)
            bmin, bmax = block_min_max(bd, bt, nd_pad)
            picks = rng.choice(40, 3, replace=False)
            lanes = [QueryLane(ts_[i], tc[i], float(rng.rand() * 2 + 0.1))
                     for i in picks]
            rl, rh, w, cb = build_tile_tables(lanes, bmin, bmax, geom)
            ub = tile_lane_ub(rl, rh, block_frac_max(frac))
            bounds = (ub @ w.T)[:, 0]  # [n_tiles]
            ref = reference_scores(bd, frac, lanes, nd_pad)
            tile_w = geom.tile_w
            for t in range(geom.n_tiles):
                seg = ref[t * tile_w: (t + 1) * tile_w]
                assert seg.max() <= bounds[t] + 1e-4, (seed, t)

    def test_pruned_equals_exhaustive_topk(self):
        """score_tiles_pruned == exhaustive top-k over random corpora,
        and pruning actually fires on at least one of them."""
        any_pruned = False
        for seed in range(4):
            rng = np.random.RandomState(200 + seed)
            bd, bt, ts_, tc, nd_pad = build_corpus(rng, 3500, 60)
            frac = compute_block_frac(
                bd, bt, np.full(nd_pad + 1, 30.0, np.float32), avgdl=30.0)
            live = np.zeros(nd_pad, np.float32)
            live[:3500] = 1.0
            dead = rng.choice(3500, 300, replace=False)
            live[dead] = 0.0
            geom = tile_geometry(nd_pad, tile_sub=4)
            bmin, bmax = block_min_max(bd, bt, nd_pad)
            picks = rng.choice(60, 3, replace=False)
            lanes = [QueryLane(ts_[i], tc[i],
                               float(rng.rand() * 2 + 0.1))
                     for i in picks]
            rl, rh, w, cb = build_tile_tables(lanes, bmin, bmax, geom)
            dp, fp, _pk, lt = _staged(bd, frac, live, geom, nd_pad)
            plan = plan_pruned_tiles(rl, rh, w, block_frac_max(frac),
                                     probe_tiles=2)
            assert plan is not None
            top_s, top_d, hits, scored = score_tiles_pruned(
                dp, fp, lt,
                jnp.asarray(plan["rl_probe"]),
                jnp.asarray(plan["rh_probe"]),
                jnp.asarray(plan["tid_probe"]),
                jnp.asarray(plan["rl_rest"]),
                jnp.asarray(plan["rh_rest"]),
                jnp.asarray(plan["tid_rest"]),
                jnp.asarray(plan["bounds_rest"]), jnp.asarray(w),
                t_pad=w.shape[1], cb=cb, sub=geom.tile_sub, k=10,
                interpret=True)
            ref = reference_scores(bd, frac, lanes, nd_pad)
            ref[live == 0] = 0.0
            assert_topk_valid(np.asarray(top_s[0]), np.asarray(top_d[0]),
                              ref, 10)
            assert int(scored) <= geom.n_tiles
            # hits under pruning: a lower bound, never an overcount
            assert int(hits[0]) <= int((ref > 0).sum())
            if int(scored) < geom.n_tiles:
                any_pruned = True
                # a pruned run must still find the full top-k (checked
                # above) — this asserts the skipping actually happened
        assert any_pruned, "pruning never fired across seeds"

    def test_batched_pruning_member_isolation(self):
        """Per-query thresholds over union lanes: each member's pruned
        top-k equals ITS serial exhaustive top-k; padding members stay
        empty (they must never keep tiles alive or emit candidates)."""
        rng = np.random.RandomState(7)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 4000, 60)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 30.0,
                                                  np.float32), avgdl=30.0)
        live = np.zeros(nd_pad, np.float32)
        live[:4000] = 1.0
        geom = tile_geometry(nd_pad, tile_sub=4)
        bmin, bmax = block_min_max(bd, bt, nd_pad)
        lane_sets = [
            [QueryLane(ts_[1], tc[1], 1.2), QueryLane(ts_[7], tc[7], 0.6)],
            [QueryLane(ts_[7], tc[7], 2.0),
             QueryLane(ts_[20], tc[20], 1.0)],
            [QueryLane(ts_[33], tc[33], 0.8)],
        ]
        rl, rh, w, cb = build_tile_tables_batched(
            lane_sets, bmin, bmax, geom)
        q_pad = 4
        wp = np.zeros((q_pad, w.shape[1]), np.float32)
        wp[:3] = w
        pk = jnp.asarray(pack_segment_blocks(bd, frac, nd_pad))
        lt = jnp.asarray(build_live_t(live, geom))
        fq = dequantize_frac(quantize_frac(frac))
        plan = plan_pruned_tiles(rl, rh, wp, block_frac_max(fq),
                                 probe_tiles=2)
        top_s, top_d, hits, scored = score_tiles_pruned(
            pk, None, lt,
            jnp.asarray(plan["rl_probe"]), jnp.asarray(plan["rh_probe"]),
            jnp.asarray(plan["tid_probe"]),
            jnp.asarray(plan["rl_rest"]), jnp.asarray(plan["rh_rest"]),
            jnp.asarray(plan["tid_rest"]),
            jnp.asarray(plan["bounds_rest"]), jnp.asarray(wp),
            t_pad=wp.shape[1], cb=cb, sub=geom.tile_sub, k=10,
            q_batch=q_pad, q_real=3, codec="packed", interpret=True)
        for q, lanes in enumerate(lane_sets):
            ref = reference_scores(bd, fq, lanes, nd_pad)
            ref[live == 0] = 0.0
            assert_topk_valid(np.asarray(top_s[q]), np.asarray(top_d[q]),
                              ref, 10)
        assert (np.asarray(top_s[3]) == -np.inf).all()
        assert int(hits[3]) == 0


MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "n": {"type": "integer"},
    "tag": {"type": "keyword"},
}}


def build_index(name, n_shards=2, n_docs=600, seed=0, **extra_settings):
    idx = IndexService(
        name, Settings({
            "index.number_of_shards": n_shards,
            "index.refresh_interval": -1, **extra_settings}),
        mapping=MAPPING)
    rng = np.random.RandomState(seed)
    vocab = [f"t{i}" for i in range(20)]
    tags = ["red", "green", "blue"]
    for d in range(n_docs):
        toks = [vocab[rng.randint(len(vocab))]
                for _ in range(rng.randint(3, 9))]
        idx.index_doc(str(d), {"body": " ".join(toks), "n": d,
                               "tag": tags[d % 3]})
    idx.refresh()
    return idx


PRUNE_SETTINGS = {
    "search.pallas.pruning.enabled": True,
    "search.pallas.pruning.probe_tiles": 2,
    "index.search.pallas.postings_codec": "packed",
}


class TestServicePruning:
    def test_mesh_pruned_parity_stats_and_marker(self):
        plain = build_index("prune-plain")
        pruned = build_index("prune-on", **PRUNE_SETTINGS)
        try:
            for q in [{"query": {"match": {"body": "t0 t3 t7"}},
                       "size": 10},
                      {"query": {"match": {"body": "t1"}}, "size": 5}]:
                want = plain.search(dict(q))
                got = pruned.search(dict(q))
                assert got["_plane"] == "mesh_pallas"
                assert "_pruned" in got, "pruned marker missing"
                w_hits = [h["_id"] for h in want["hits"]["hits"]]
                g_hits = [h["_id"] for h in got["hits"]["hits"]]
                assert w_hits == g_hits, q
                for gh, wh in zip(got["hits"]["hits"],
                                  want["hits"]["hits"]):
                    assert abs(gh["_score"] - wh["_score"]) < 2e-3
                # totals: a lower bound under pruning, never an overcount
                assert got["hits"]["total"] <= want["hits"]["total"]
            st = pruned.stats()["total"]["search"]["planes"]
            assert st["pruned_query_total"] >= 2
            assert st["tiles_scored_total"] > 0
            assert st["postings_codec"] == "packed"
            assert st["postings_bytes_staged"] > 0
            # packed staging is half the raw posting bytes
            st_plain = plain.stats()["total"]["search"]["planes"]
            assert st_plain["postings_codec"] == "raw"
            assert (st["postings_bytes_staged"]
                    < st_plain["postings_bytes_staged"])
        finally:
            plain.close()
            pruned.close()

    def test_pruning_actually_skips_tiles(self):
        """With a skewed posting distribution the bound order separates
        tiles and some are pruned (tiles_pruned_total > 0)."""
        idx = build_index("prune-skip", n_docs=700, seed=3,
                          **PRUNE_SETTINGS)
        try:
            for i in range(4):
                r = idx.search({"query": {"match": {"body": f"t{i} t19"}},
                                "size": 3})
                assert r["_plane"] == "mesh_pallas"
            st = idx.stats()["total"]["search"]["planes"]
            assert st["tiles_scored_total"] > 0
            # tiles_pruned may legitimately be zero on tiny corpora with
            # uniform bounds; assert the accounting adds up instead
            assert (st["tiles_scored_total"] + st["tiles_pruned_total"]
                    > 0)
        finally:
            idx.close()

    def test_exhaustive_fallback_triggers(self):
        """Requests needing every tile's dense output never take the
        pruned path: aggs, minimum_should_match (operator:and), sort —
        all still served correctly, with NO _pruned marker."""
        plain = build_index("fb-plain")
        pruned = build_index("fb-on", **PRUNE_SETTINGS)
        try:
            bodies = [
                {"query": {"match": {"body": "t0 t1"}}, "size": 5,
                 "aggs": {"tags": {"terms": {"field": "tag"}}}},
                {"query": {"match": {"body": {"query": "t0 t1",
                                              "operator": "and"}}},
                 "size": 5},
                {"query": {"match": {"body": "t2"}},
                 "sort": [{"n": {"order": "desc"}}], "size": 5},
            ]
            for q in bodies:
                want = plain.search(dict(q))
                got = pruned.search(dict(q))
                assert "_pruned" not in got, q
                assert got["hits"]["total"] == want["hits"]["total"], q
                assert ([h["_id"] for h in got["hits"]["hits"]]
                        == [h["_id"] for h in want["hits"]["hits"]]), q
                if "aggs" in q:
                    assert got["aggregations"] == want["aggregations"]
        finally:
            plain.close()
            pruned.close()

    def test_plane_fault_under_pruning_quarantines_once(self):
        idx = build_index("prune-fault", **PRUNE_SETTINGS)
        try:
            scheme = PlaneFailScheme(planes=["mesh_pallas"]).install()
            r = idx.search({"query": {"match": {"body": "t0 t1"}},
                            "size": 5})
            # served from a fallback rung, exactly one quarantine
            assert r["_plane"] != "mesh_pallas"
            assert r["hits"]["total"] > 0
            ph = idx._mesh_search.plane_health
            assert ph.failures_total["mesh_pallas"] == 1
            assert scheme.hits == 1
            assert "mesh_pallas" in ph.quarantined()
        finally:
            idx.close()

    def test_count_stays_exact_and_batch_stats_clean(self):
        """Review regressions: (a) _count / size:0 requests are
        exact-total consumers — they must never ride the pruned path
        (whose totals are gte lower bounds); (b) the Q==1 pruned fast
        path is not cross-query batching and must not inflate the
        batching-adoption counters."""
        plain = build_index("count-plain")
        pruned = build_index("count-on", **PRUNE_SETTINGS)
        try:
            q = {"query": {"match": {"body": "t0 t3"}}}
            want = plain.count(dict(q))
            got = pruned.count(dict(q))
            assert got["count"] == want["count"]
            r0 = pruned.search({"query": {"match": {"body": "t1"}},
                                "size": 0})
            assert "_pruned" not in r0
            assert r0["hits"]["total"] == plain.search(
                {"query": {"match": {"body": "t1"}},
                 "size": 0})["hits"]["total"]
            # a few pruned single queries: no batched-launch accounting
            for i in range(3):
                r = pruned.search({"query": {"match": {"body": f"t{i}"}},
                                   "size": 5})
                assert "_pruned" in r
            assert pruned._mesh_search.batched_launch_total == 0
            assert pruned._mesh_search.batched_query_total == 0
            assert pruned._mesh_search.pruned_query_total >= 3
        finally:
            plain.close()
            pruned.close()

    def test_deadline_honored_on_pruned_fast_path(self):
        """Review regression: the pruned single-query route must keep
        the PR-4 deadline contract — an expired deadline degrades to a
        partial timed_out response, never a full answer (and never a
        plane quarantine)."""
        from elasticsearch_tpu.search.cancellation import SearchDeadline

        idx = build_index("prune-deadline", **PRUNE_SETTINGS)
        try:
            # warm the pruned program so the expiry isn't racing compile
            warm = idx.search({"query": {"match": {"body": "t0"}},
                               "size": 5})
            assert "_pruned" in warm
            expired = SearchDeadline(1e-9)
            r = idx.search({"query": {"match": {"body": "t0"}},
                            "size": 5}, deadline=expired)
            assert r["timed_out"] is True
            assert idx._mesh_search.plane_health.failures_total[
                "mesh_pallas"] == 0
        finally:
            idx.close()

    def test_host_path_packed_codec_parity(self, monkeypatch):
        """Single-shard (host plan path): the packed codec serves the
        same hits as raw within quantization tolerance — the codec
        threads the host rung, not just the mesh."""
        raw = build_index("codec-raw", n_shards=1, n_docs=300)
        monkeypatch.setenv("ES_TPU_PALLAS_CODEC", "packed")
        packed = build_index("codec-packed", n_shards=1, n_docs=300)
        try:
            # staging happened under the env default
            seg = next(iter(packed.shards.values())) \
                .engine.searchable_segments()[0]
            seg.device_arrays()
            assert seg.kernel_codec == "packed"
            assert seg.kernel_postings_bytes > 0
            q = {"query": {"match": {"body": "t0 t4 t9"}}, "size": 10}
            want = raw.search(dict(q))
            got = packed.search(dict(q))
            assert got["hits"]["total"] == want["hits"]["total"]
            assert ([h["_id"] for h in got["hits"]["hits"]]
                    == [h["_id"] for h in want["hits"]["hits"]])
            for gh, wh in zip(got["hits"]["hits"], want["hits"]["hits"]):
                assert abs(gh["_score"] - wh["_score"]) < 2e-3
        finally:
            raw.close()
            packed.close()
