"""Allocation deciders (disk watermarks, awareness) + adaptive replica
selection.

Mirrors DiskThresholdDecider/DiskThresholdMonitor, the
AwarenessAllocationDecider (cluster/routing/allocation/decider/) and
ResponseCollectorService (node/ResponseCollectorService.java).
"""

import pytest

from elasticsearch_tpu.cluster.allocation import allocate
from elasticsearch_tpu.cluster.multinode import ClusterClient, ClusterNode
from elasticsearch_tpu.cluster.response_collector import ResponseCollectorService
from elasticsearch_tpu.cluster.state import IndexMetadata, ShardRoutingState
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.transport.local import TransportHub


def meta(shards=2, replicas=1):
    return IndexMetadata("idx", Settings({
        "index.number_of_shards": shards,
        "index.number_of_replicas": replicas}), {})


def nodes_of(table):
    return {c.node_id for shards in table.values()
            for copies in shards.values() for c in copies}


class TestDiskThreshold:
    def test_low_watermark_blocks_new_allocations(self):
        info = {"n1": {"attrs": {}, "disk": 0.2},
                "n2": {"attrs": {}, "disk": 0.88}}  # over low watermark
        table = allocate({"idx": meta(shards=4, replicas=0)}, ["n1", "n2"],
                         node_info=info)
        assert nodes_of(table) == {"n1"}

    def test_no_eligible_node_leaves_unassigned(self):
        info = {"n1": {"attrs": {}, "disk": 0.95}}
        table = allocate({"idx": meta(shards=1, replicas=0)}, ["n1"],
                         node_info=info)
        assert table["idx"][0] == []  # unassigned (red) rather than on a
        # node past the watermark

    def test_started_replica_kept_until_replacement_starts(self):
        from elasticsearch_tpu.cluster.state import ShardRoutingState

        info = {f"n{i}": {"attrs": {}, "disk": 0.1} for i in range(1, 4)}
        table = allocate({"idx": meta(shards=1, replicas=1)},
                         ["n1", "n2", "n3"], node_info=info)
        for c in table["idx"][0]:
            c.state = ShardRoutingState.STARTED
        replica_node = next(c.node_id for c in table["idx"][0] if not c.primary)
        info[replica_node]["disk"] = 0.95
        t2 = allocate({"idx": meta(shards=1, replicas=1)},
                      ["n1", "n2", "n3"], previous=table, node_info=info)
        replicas = [c for c in t2["idx"][0] if not c.primary]
        # source retained (STARTED) + replacement (INITIALIZING) coexist
        assert len(replicas) == 2
        states = {c.node_id: c.state for c in replicas}
        assert states[replica_node] == ShardRoutingState.STARTED
        target = next(n for n in states if n != replica_node)
        assert states[target] == ShardRoutingState.INITIALIZING
        # replacement starts -> hot source retires on the next reroute
        for c in t2["idx"][0]:
            c.state = ShardRoutingState.STARTED
        t3 = allocate({"idx": meta(shards=1, replicas=1)},
                      ["n1", "n2", "n3"], previous=t2, node_info=info)
        replicas3 = [c for c in t3["idx"][0] if not c.primary]
        assert [c.node_id for c in replicas3] == [target]

    def test_high_watermark_moves_replicas_off(self):
        info = {"n1": {"attrs": {}, "disk": 0.1},
                "n2": {"attrs": {}, "disk": 0.1},
                "n3": {"attrs": {}, "disk": 0.1}}
        table = allocate({"idx": meta(shards=1, replicas=1)},
                         ["n1", "n2", "n3"], node_info=info)
        replica = next(c for c in table["idx"][0] if not c.primary)
        orig_replica_node = replica.node_id
        orig_primary_node = next(
            c for c in table["idx"][0] if c.primary).node_id
        # the replica's node fills up past the high watermark
        info[orig_replica_node]["disk"] = 0.95
        table2 = allocate({"idx": meta(shards=1, replicas=1)},
                          ["n1", "n2", "n3"], previous=table, node_info=info)
        new_replica = next(c for c in table2["idx"][0] if not c.primary)
        assert new_replica.node_id != orig_replica_node
        # the primary stays put (only replicas relocate on high watermark)
        primary = next(c for c in table2["idx"][0] if c.primary)
        assert primary.node_id == orig_primary_node


class TestDiskThresholdNoTarget:
    def test_replica_kept_when_no_eligible_target(self):
        # a healthy replica is never discarded without a replacement
        info = {"n1": {"attrs": {}, "disk": 0.1},
                "n2": {"attrs": {}, "disk": 0.1}}
        table = allocate({"idx": meta(shards=1, replicas=1)}, ["n1", "n2"],
                         node_info=info)
        replica = next(c for c in table["idx"][0] if not c.primary)
        info[replica.node_id]["disk"] = 0.95  # over high, nowhere to go
        table2 = allocate({"idx": meta(shards=1, replicas=1)}, ["n1", "n2"],
                          previous=table, node_info=info)
        survivors = [c for c in table2["idx"][0] if not c.primary]
        assert len(survivors) == 1
        assert survivors[0].node_id == replica.node_id


class TestAwareness:
    def test_copies_spread_across_zones(self):
        info = {
            "a1": {"attrs": {"zone": "a"}, "disk": 0.0},
            "a2": {"attrs": {"zone": "a"}, "disk": 0.0},
            "b1": {"attrs": {"zone": "b"}, "disk": 0.0},
            "b2": {"attrs": {"zone": "b"}, "disk": 0.0},
        }
        table = allocate({"idx": meta(shards=4, replicas=1)},
                         list(info), node_info=info,
                         awareness_attributes=["zone"])
        for sid, copies in table["idx"].items():
            zones = {info[c.node_id]["attrs"]["zone"] for c in copies}
            assert zones == {"a", "b"}, f"shard {sid} not zone-spread"

    def test_awareness_in_cluster(self):
        hub = TransportHub(strict_serialization=True)
        nodes = [
            ClusterNode("za-1", hub, attrs={"zone": "a"},
                        awareness_attributes=["zone"]),
            ClusterNode("za-2", hub, attrs={"zone": "a"}),
            ClusterNode("zb-1", hub, attrs={"zone": "b"}),
        ]
        nodes[0].bootstrap_cluster()
        for n in nodes[1:]:
            n.join("za-1")
        nodes[0].create_index("idx", {"index": {"number_of_shards": 2,
                                                "number_of_replicas": 1}})
        for sid, copies in nodes[0].routing["idx"].items():
            zones = {nodes[0].node_info_map[c.node_id]["attrs"]["zone"]
                     for c in copies}
            assert zones == {"a", "b"}
        for n in nodes:
            n.close()


class TestAdaptiveReplicaSelection:
    def test_collector_ranks_by_ewma(self):
        rc = ResponseCollectorService()
        rc.add_response_time("fast", 0.001)
        rc.add_response_time("slow", 0.5)
        assert rc.rank("fast") < rc.rank("slow")
        assert rc.rank("unknown") == 0.0  # unknown nodes get probed
        # EWMA adapts: slow node speeds up
        for _ in range(30):
            rc.add_response_time("slow", 0.0001)
        assert rc.rank("slow") < 0.01

    def test_order_copies_prefers_faster_node(self):
        from elasticsearch_tpu.cluster.state import ShardRouting

        rc = ResponseCollectorService()
        rc.add_response_time("n1", 0.5)
        rc.add_response_time("n2", 0.001)
        copies = [
            ShardRouting("i", 0, "n1", True, ShardRoutingState.STARTED),
            ShardRouting("i", 0, "n2", False, ShardRoutingState.STARTED),
        ]
        ordered = rc.order_copies(copies)
        assert ordered[0].node_id == "n2"  # replica preferred: faster

    def test_search_routes_away_from_slow_copy(self):
        hub = TransportHub(strict_serialization=True)
        nodes = [ClusterNode(f"n{i}", hub) for i in range(2)]
        nodes[0].bootstrap_cluster()
        nodes[1].join("n0")
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        client.index("idx", "1", {"a": 1})
        client.refresh("idx")
        # seed the collector: the primary's node is slow
        primary_node = next(n.node_id for n in nodes
                            if n.shards.get(("idx", 0)) is not None
                            and n.shards[("idx", 0)].primary)
        other = next(n.node_id for n in nodes if n.node_id != primary_node)
        client.response_collector.add_response_time(primary_node, 1.0)
        client.response_collector.add_response_time(other, 0.001)
        hub.requests_log.clear()
        r = client.search("idx", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 1
        query_targets = [dst for (src, dst, action) in hub.requests_log
                         if "search" in action]
        assert query_targets == [other]
        for n in nodes:
            n.close()
