"""Golden-value tests: TPU kernels vs scalar reference implementations.

Seeded-random corpora (the reference's randomized testing discipline,
SURVEY.md §4.1) — scoring must match the scalar BM25 to float tolerance
and top-k ordering must match exactly (recall@k = 1.0).
"""

import math
import random

import numpy as np
import pytest

import golden
from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.mapper.mapping import MapperService
from elasticsearch_tpu.ops import aggs as agg_ops
from elasticsearch_tpu.ops import masks as mask_ops
from elasticsearch_tpu.ops import scoring

import jax.numpy as jnp

VOCAB = [f"w{i}" for i in range(50)]


def random_corpus(rng, n_docs, max_len=30):
    return [
        [rng.choice(VOCAB) for _ in range(rng.randint(1, max_len))]
        for _ in range(n_docs)
    ]


def build_segment(docs_tokens):
    svc = MapperService(
        AnalysisRegistry(), {"properties": {"body": {"type": "text", "analyzer": "whitespace"}}}
    )
    b = SegmentBuilder("s")
    for i, toks in enumerate(docs_tokens):
        b.add_document(svc.parse_document(str(i), {"body": " ".join(toks)}), i)
    return b.seal()


def query_arrays(seg, field, terms, qb_pad=8):
    """Host-side query planning: term lookup -> block gather arrays."""
    blocks, weights, rows, avgdls = [], [], [], []
    doc_count = seg.field_stats.get(field, {}).get("doc_count", 0)
    avgdl = seg.field_avgdl(field)
    row = seg.field_norm_idx.get(field, 0)
    for t in terms:
        tid = seg.term_id(field, t)
        if tid < 0:
            continue
        idf = scoring.bm25_idf(int(seg.term_doc_freq[tid]), doc_count)
        start, cnt = int(seg.term_block_start[tid]), int(seg.term_block_count[tid])
        for bi in range(start, start + cnt):
            blocks.append(bi)
            weights.append(idf)
            rows.append(row)
            avgdls.append(avgdl)
    qb = max(qb_pad, 1)
    while qb < len(blocks):
        qb *= 2
    pad = qb - len(blocks)
    return (
        jnp.asarray(np.array(blocks + [0] * pad, dtype=np.int32)),
        jnp.asarray(np.array(weights + [0.0] * pad, dtype=np.float32)),
        jnp.asarray(np.array(rows + [0] * pad, dtype=np.int32)),
        jnp.asarray(np.array(avgdls + [1.0] * pad, dtype=np.float32)),
        jnp.asarray(np.array([True] * len(blocks) + [False] * pad)),
    )


def run_query(seg, terms, field="body"):
    dev = seg.device_arrays()
    qb, qw, qr, qa, qv = query_arrays(seg, field, terms)
    scores, counts = scoring.score_term_blocks(
        dev["block_docs"], dev["block_tfs"], dev["norms"], qb, qw, qr, qa, qv
    )
    return np.asarray(scores), np.asarray(counts)


class TestBM25Golden:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_scores_match_scalar_reference(self, seed):
        rng = random.Random(seed)
        docs = random_corpus(rng, rng.randint(5, 200))
        q = [rng.choice(VOCAB) for _ in range(rng.randint(1, 4))]
        q = list(dict.fromkeys(q))  # unique terms
        seg = build_segment(docs)
        scores, counts = run_query(seg, q)
        ref_scores, ref_matched = golden.score_corpus(docs, q)
        for d in range(len(docs)):
            assert scores[d] == pytest.approx(ref_scores.get(d, 0.0), rel=1e-5, abs=1e-6)
            assert counts[d] == ref_matched.get(d, 0)

    def test_topk_ordering_exact(self):
        rng = random.Random(42)
        docs = random_corpus(rng, 500)
        q = ["w0", "w1", "w2"]
        seg = build_segment(docs)
        scores, counts = run_query(seg, q)
        dev = seg.device_arrays()
        live1 = jnp.concatenate([dev["live"], jnp.zeros(1, bool)])
        top_scores, top_docs = scoring.select_topk(
            jnp.asarray(scores), jnp.asarray(counts) > 0, live1, 10
        )
        ref_scores, _ = golden.score_corpus(docs, q)
        ref_top = golden.top_k(ref_scores, 10)
        got = [(int(d), float(s)) for s, d in zip(top_scores, top_docs) if s > -np.inf]
        # same doc set and same score ordering (ties may permute)
        assert {d for d, _ in got} == {d for d, _ in ref_top}
        got_scores = [s for _, s in got]
        assert got_scores == sorted(got_scores, reverse=True)
        for (d, s), (rd, rs) in zip(got, ref_top):
            assert s == pytest.approx(dict(ref_top)[d], rel=1e-5)

    def test_conjunction_counting(self):
        docs = [["a", "b"], ["a"], ["b"], ["a", "b", "c"]]
        seg = build_segment(docs)
        scores, counts = run_query(seg, ["a", "b"])
        # operator=and --> count == 2
        assert [int(c) for c in counts[:4]] == [2, 1, 1, 2]

    def test_multi_block_term(self):
        # term spanning >1 posting block still scores every doc once
        docs = [["common"] for _ in range(300)]
        seg = build_segment(docs)
        scores, counts = run_query(seg, ["common"])
        assert (counts[:300] == 1).all()
        assert np.allclose(scores[:300], scores[0])

    def test_idf_formula(self):
        assert scoring.bm25_idf(1, 2) == pytest.approx(math.log(1 + 1.5 / 1.5))


class TestMasks:
    def _col_segment(self):
        svc = MapperService(AnalysisRegistry())
        b = SegmentBuilder("s")
        vals = [5, 15, 25, 35, 10]
        for i, v in enumerate(vals):
            b.add_document(svc.parse_document(str(i), {"price": v, "tag": f"t{i % 2}"}), i)
        return b.seal(), vals

    def test_numeric_range(self):
        seg, vals = self._col_segment()
        col = seg.numeric_columns["price"]
        nd1 = jnp.zeros(seg.nd_pad + 1, bool)
        m = np.asarray(mask_ops.numeric_range_mask(
            jnp.asarray(col.flat_docs), jnp.asarray(col.flat_values), 10.0, 30.0, nd1
        ))
        expect = [10 <= v <= 30 for v in vals]
        assert list(m[:5]) == expect

    def test_ord_terms(self):
        seg, _ = self._col_segment()
        col = seg.ordinal_columns["tag.keyword"]
        nd1 = jnp.zeros(seg.nd_pad + 1, bool)
        t0 = col.ord_of("t0")
        m = np.asarray(mask_ops.ord_terms_mask(
            jnp.asarray(col.flat_docs), jnp.asarray(col.flat_ords),
            jnp.asarray(np.array([t0, -1], dtype=np.int32)), nd1
        ))
        assert list(m[:5]) == [True, False, True, False, True]

    def test_geo_distance(self):
        svc = MapperService(AnalysisRegistry(), {"properties": {"loc": {"type": "geo_point"}}})
        b = SegmentBuilder("s")
        pts = [(48.8566, 2.3522), (51.5074, -0.1278), (48.86, 2.35)]  # paris, london, paris2
        for i, (la, lo) in enumerate(pts):
            b.add_document(svc.parse_document(str(i), {"loc": {"lat": la, "lon": lo}}), i)
        seg = b.seal()
        col = seg.geo_columns["loc"]
        nd1 = jnp.zeros(seg.nd_pad + 1, bool)
        m = np.asarray(mask_ops.geo_distance_mask(
            jnp.asarray(col.flat_docs), jnp.asarray(col.lat), jnp.asarray(col.lon),
            48.8566, 2.3522, 10_000.0, nd1
        ))
        assert list(m[:3]) == [True, False, True]


class TestAggOps:
    def test_ordinal_counts_match_golden(self):
        rng = random.Random(7)
        docs_vals = [[rng.choice(["a", "b", "c"]) for _ in range(rng.randint(1, 3))]
                     for _ in range(100)]
        svc = MapperService(AnalysisRegistry())
        b = SegmentBuilder("s")
        for i, vs in enumerate(docs_vals):
            b.add_document(svc.parse_document(str(i), {"tag": vs}), i)
        seg = b.seal()
        col = seg.ordinal_columns["tag.keyword"]
        matched_docs = set(range(0, 100, 2))
        mask = np.zeros(seg.nd_pad + 1, dtype=bool)
        for d in matched_docs:
            mask[d] = True
        counts = np.asarray(agg_ops.ordinal_counts(
            jnp.asarray(col.flat_docs), jnp.asarray(col.flat_ords),
            jnp.asarray(mask), len(col.terms)
        ))
        ref = golden.terms_agg(docs_vals, matched_docs)
        got = {col.terms[i]: int(c) for i, c in enumerate(counts) if c > 0}
        assert got == ref

    def test_histogram_matches_golden(self):
        rng = random.Random(9)
        docs_vals = [[rng.uniform(0, 100)] for _ in range(200)]
        svc = MapperService(AnalysisRegistry())
        b = SegmentBuilder("s")
        for i, vs in enumerate(docs_vals):
            b.add_document(svc.parse_document(str(i), {"x": vs[0]}), i)
        seg = b.seal()
        col = seg.numeric_columns["x"]
        mask = np.zeros(seg.nd_pad + 1, dtype=bool)
        mask[:200] = True
        interval = 10.0
        counts = np.asarray(agg_ops.histogram_counts(
            jnp.asarray(col.flat_docs), jnp.asarray(col.flat_values),
            jnp.asarray(mask), interval, 0.0, 0, 16
        ))
        ref = golden.histogram_agg(docs_vals, set(range(200)), interval)
        got = {i: int(c) for i, c in enumerate(counts) if c > 0}
        assert got == ref

    def test_stats(self):
        svc = MapperService(AnalysisRegistry())
        b = SegmentBuilder("s")
        vals = [3.0, 7.0, 1.0, 9.0]
        for i, v in enumerate(vals):
            b.add_document(svc.parse_document(str(i), {"x": v}), i)
        seg = b.seal()
        col = seg.numeric_columns["x"]
        mask = np.zeros(seg.nd_pad + 1, dtype=bool)
        mask[:3] = True  # only docs 0..2
        valid = np.arange(len(col.flat_docs)) < col.count
        count, total, vmin, vmax, sq = agg_ops.numeric_stats(
            jnp.asarray(col.flat_docs), jnp.asarray(col.flat_values),
            jnp.asarray(valid), jnp.asarray(mask)
        )
        assert int(count) == 3
        assert float(total) == 11.0
        assert float(vmin) == 1.0 and float(vmax) == 7.0

    def test_hll_cardinality_accuracy(self):
        rng = np.random.RandomState(3)
        n_unique = 5000
        values = rng.choice(n_unique, size=20000).astype(np.float64)
        hashes = agg_ops.hash_numeric_values(values)
        docs = np.arange(len(values), dtype=np.int32)
        mask = np.ones(len(values) + 1, dtype=bool)
        valid = np.ones(len(values), dtype=bool)
        regs = agg_ops.hll_registers(
            jnp.asarray(docs), jnp.asarray(hashes), jnp.asarray(valid), jnp.asarray(mask)
        )
        est = agg_ops.hll_estimate(np.asarray(regs))
        true_card = len(np.unique(values))
        assert abs(est - true_card) / true_card < 0.05  # HLL p=14 ~0.8% typical

    def test_hll_merge_associative(self):
        rng = np.random.RandomState(4)
        a_vals = rng.choice(1000, 5000).astype(np.float64)
        b_vals = (rng.choice(1000, 5000) + 500).astype(np.float64)

        def regs_of(vals):
            h = agg_ops.hash_numeric_values(vals)
            docs = np.arange(len(vals), dtype=np.int32)
            return agg_ops.hll_registers(
                jnp.asarray(docs), jnp.asarray(h),
                jnp.asarray(np.ones(len(vals), bool)),
                jnp.asarray(np.ones(len(vals) + 1, bool)),
            )

        merged = agg_ops.hll_merge(regs_of(a_vals), regs_of(b_vals))
        est = agg_ops.hll_estimate(np.asarray(merged))
        true_card = len(np.unique(np.concatenate([a_vals, b_vals])))
        assert abs(est - true_card) / true_card < 0.05
