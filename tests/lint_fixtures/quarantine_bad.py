"""Known-bad snippet for the quarantine-release pass: a shard copy
flagged corrupt without the marker, the detection record, or the
device-staging release. Parsed only."""


class BadQuarantiner:
    def fail_copy(self, shard):
        # BAD on all three axes: no mark_corrupted, no
        # record_corruption, no staging release — a silent in-memory
        # quarantine that leaks HBM and vanishes on restart
        shard.store_corrupted = True


class GoodQuarantiner:
    def fail_copy(self, shard, integ, exc):
        integ.record_corruption("idx", 0, "query", str(exc))
        shard.engine.store.mark_corrupted(str(exc), site="query")
        shard.store_corrupted = True
        for seg in shard.engine.segments:
            seg.release_device_staging()
