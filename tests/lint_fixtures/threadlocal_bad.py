"""Known-bad snippet for the thread-local-hygiene pass. Parsed only."""


class BadExecutor:
    def ensure_plane(self):
        # BAD: writes a non-None denial reason with no reset-to-None
        # earlier in the function — a stale value from the previous call
        # on this thread survives every path that doesn't reach here
        if self.over_budget():
            self.kernel_denied_reason = "hbm_budget"
            return None
        return self.session


class BadLeader:
    def run_members(self, oids, members):
        from elasticsearch_tpu.search.telemetry import (  # noqa: F401
            get_opaque_id,
            set_opaque_id,
        )

        leader_oid = get_opaque_id()
        for oid, member in zip(oids, members):
            set_opaque_id(oid)
            member()
        return True  # BAD: falls off with the last member's id staged


class GoodExecutor:
    def ensure_plane(self):
        self.kernel_denied_reason = None  # reset FIRST
        if self.over_budget():
            self.kernel_denied_reason = "hbm_budget"
            return None
        return self.session
