"""Known-bad snippet for the static lock-order pass: two locks acquired
in both orders (an A->B->A cycle), plus a plain-Lock self-deadlock.
Parsed only, never imported."""

import threading

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()


def forward():
    with _LOCK_A:
        with _LOCK_B:  # A -> B
            pass


def backward():
    with _LOCK_B:
        with _LOCK_A:  # B -> A: the deadlock cycle
            pass


class SelfDeadlock:
    def __init__(self):
        self._plain = threading.Lock()

    def outer(self):
        with self._plain:
            self.inner()  # BAD: re-acquires the same plain Lock

    def inner(self):
        with self._plain:
            pass
