"""Known-bad snippet for the counter-lock-discipline pass. Parsed only."""

import threading


class BadStats:
    def __init__(self):
        self._lock = threading.Lock()
        self.query_total = 0
        self.fallback_by_reason = {}

    def note(self, reason):
        self.query_total += 1  # BAD: read-modify-write outside the lock
        self.fallback_by_reason[reason] = \
            self.fallback_by_reason.get(reason, 0) + 1  # BAD too

    def note_locked(self, reason):
        # OK: *_locked naming convention — the caller holds self._lock
        self.query_total += 1

    def note_safe(self, reason):
        with self._lock:
            self.query_total += 1  # OK
