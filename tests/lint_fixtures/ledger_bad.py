"""Known-bad snippet for the ledger-balance pass: a register with no
evict callback, in a class with no release path. Parsed only."""

from elasticsearch_tpu.common.memory import memory_accountant  # noqa: F401


class BadStager:
    def stage(self, nbytes):
        # BAD on both axes: no evict= kwarg, and BadStager owns no
        # release_scope/release_index call anywhere
        memory_accountant().register(
            "idx", "scope1", "postings_raw", "tbl", nbytes)


class GoodStager:
    def stage(self, nbytes):
        acct = memory_accountant()
        acct.register("idx", "scope2", "postings_raw", "tbl", nbytes,
                      evict=self.drop)

    def drop(self):
        memory_accountant().release_scope("idx", "scope2")
