"""Known-bad snippet for the cancellation-passthrough pass: the broad
handler records a fault (quarantine) without letting
TimeExceeded/TaskCancelled through first. Parsed only, never imported."""


class BadLadder:
    def serve(self, deadline):
        try:
            deadline.checkpoint()
            return self.launch()
        except Exception:  # BAD: swallows cancellation, records a fault
            self.plane_health.record_failure("mesh_pallas")
            return None


class AlsoBadSwallow:
    def serve(self, deadline):
        try:
            deadline.checkpoint()
            return self.launch()
        except Exception:  # BAD: cancellable body, silently eaten
            return None


class GoodLadder:
    def serve(self, deadline):
        try:
            deadline.checkpoint()
            return self.launch()
        except (TaskCancelledException, TimeExceededException):  # noqa: F821
            raise
        except Exception:  # OK: cancellation already re-raised above
            self.plane_health.record_failure("mesh_pallas")
            return None
