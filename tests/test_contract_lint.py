"""Contract-lint subsystem (ISSUE 15, docs/STATIC_ANALYSIS.md).

Three layers, all tier-1:

1. the GATE — every lint pass over the real source tree must be clean
   (zero unallowlisted findings, no stale allowlist entries, every
   entry justified) and docs/LOCK_ORDER.md must match the tree;
2. the SELF-TESTS — each pass must flag its known-bad
   tests/lint_fixtures snippet (a refactor of the analyzer cannot
   silently stop detecting anything) and must NOT flag the good shape
   sitting next to it;
3. the runtime lock-order WITNESS — deliberate inversions are caught,
   consistent orders and RLock reentrancy are not.
"""

import ast
import os
import threading

import pytest

from elasticsearch_tpu.testing.lint import (
    Allowlist,
    SourceTree,
    all_passes,
    run_lint,
)
from elasticsearch_tpu.testing.lint.core import repo_root

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _fixture_findings(pass_name):
    tree = SourceTree(root=FIXTURES, fixture_mode=True)
    return list(all_passes()[pass_name].run(tree))


# ---------------------------------------------------------------------------
# 1. the gate
# ---------------------------------------------------------------------------


class TestContractLintGate:
    def test_source_tree_clean(self):
        result = run_lint()
        assert not result.allowlist_errors, result.allowlist_errors
        assert not result.stale_entries, (
            f"stale allowlist entries (no finding matches — remove "
            f"them): {result.stale_entries}")
        assert not result.unallowlisted, (
            "unallowlisted contract-lint findings:\n"
            + "\n".join(f.render() for f in result.unallowlisted)
            + "\nFix the violation, or — for a justified false positive"
              " — add the id to elasticsearch_tpu/testing/lint/"
              "allowlist.txt WITH a justification")

    def test_at_least_five_passes_registered(self):
        passes = all_passes()
        assert len(passes) >= 5, sorted(passes)
        for expected in ("cancellation-passthrough", "ledger-balance",
                         "counter-lock-discipline",
                         "thread-local-hygiene", "lock-order",
                         "settings-docs", "quarantine-release"):
            assert expected in passes

    def test_lock_order_doc_fresh(self):
        from elasticsearch_tpu.testing.lint.pass_lockorder import (
            lock_graph_for,
            render_lock_order,
        )

        doc = os.path.join(repo_root(), "docs", "LOCK_ORDER.md")
        with open(doc, encoding="utf-8") as f:
            on_disk = f.read()
        current = render_lock_order(lock_graph_for(SourceTree()))
        assert on_disk == current, (
            "docs/LOCK_ORDER.md is stale — regenerate with `python -m "
            "elasticsearch_tpu.testing.lint --emit-lock-order`")

    def test_static_lock_graph_sees_the_real_tree(self):
        # the analyzer is only trustworthy if it still finds the known
        # lock population; anchor on sites that exist today
        from elasticsearch_tpu.testing.lint.pass_lockorder import (
            lock_graph_for,
        )

        lg = lock_graph_for(SourceTree())
        assert len(lg.sites) >= 40, len(lg.sites)
        assert len(lg.edges) >= 20, len(lg.edges)
        for site in ("parallel.plan_exec._MESH_EXEC_LOCK",
                     "common.memory.DeviceMemoryAccountant._lock",
                     "parallel.plan_exec.IndexMeshSearch._stage_lock",
                     "search.admission.SearchAdmissionController._lock"):
            assert site in lg.sites, site
        # the documented stage->accountant ordering is an edge the
        # analyzer must keep seeing (try_reserve under _stage_lock)
        assert ("parallel.plan_exec.IndexMeshSearch._stage_lock",
                "common.memory.DeviceMemoryAccountant._lock") in lg.edges

    def test_cli_main_exits_zero(self):
        from elasticsearch_tpu.testing.lint.__main__ import main

        assert main([]) == 0
        assert main(["--list"]) == 0
        assert main(["--pass", "no-such-pass"]) == 2

    def test_allowlist_requires_justification(self, tmp_path):
        p = tmp_path / "allow.txt"
        p.write_text("some-pass:file.py:qual\n"
                     "other-pass:file.py:qual |   \n")
        allow = Allowlist.load(str(p))
        assert len(allow.errors) == 2
        assert not allow.entries


# ---------------------------------------------------------------------------
# 2. fixture self-tests — every pass must keep firing
# ---------------------------------------------------------------------------


class TestPassSelfTests:
    def test_cancellation_pass_fires(self):
        ids = {f.id for f in _fixture_findings("cancellation-passthrough")}
        assert ("cancellation-passthrough:cancellation_bad.py:"
                "BadLadder.serve") in ids
        assert ("cancellation-passthrough:cancellation_bad.py:"
                "AlsoBadSwallow.serve") in ids
        assert not any("GoodLadder" in i for i in ids)

    def test_ledger_pass_fires(self):
        ids = {f.id for f in _fixture_findings("ledger-balance")}
        assert ("ledger-balance:ledger_bad.py:BadStager.stage:evict"
                in ids)
        assert ("ledger-balance:ledger_bad.py:BadStager.stage:release"
                in ids)
        assert not any("GoodStager" in i for i in ids)

    def test_counter_pass_fires(self):
        ids = {f.id for f in _fixture_findings("counter-lock-discipline")}
        assert ("counter-lock-discipline:counter_bad.py:BadStats.note:"
                "query_total") in ids
        assert ("counter-lock-discipline:counter_bad.py:BadStats.note:"
                "fallback_by_reason") in ids
        assert not any("note_locked" in i or "note_safe" in i
                       for i in ids)

    def test_threadlocal_pass_fires(self):
        ids = {f.id for f in _fixture_findings("thread-local-hygiene")}
        assert ("thread-local-hygiene:threadlocal_bad.py:"
                "BadExecutor.ensure_plane:kernel_denied_reason") in ids
        assert any(i.startswith("thread-local-hygiene:threadlocal_bad"
                                ".py:BadLeader.run_members:oid")
                   for i in ids)
        assert not any("GoodExecutor" in i for i in ids)

    def test_lockorder_pass_fires(self):
        findings = _fixture_findings("lock-order")
        keys = {f.key for f in findings}
        assert any(k.startswith("cycle:") and "_LOCK_A" in k
                   and "_LOCK_B" in k for k in keys), keys
        assert any(f.qualname == "lockorder_bad.SelfDeadlock._plain"
                   and f.key == "self-edge" for f in findings), findings

    def test_settings_docs_pass_fires(self):
        from elasticsearch_tpu.testing.lint.pass_settings_docs import (
            cross_check,
        )

        findings = list(cross_check(
            keys={"search.documented", "search.undocumented",
                  "search.twice"},
            rows={"search.documented": [("A.md", 1)],
                  "search.twice": [("A.md", 2), ("B.md", 3)],
                  "search.unregistered": [("A.md", 4)]},
            pass_name="settings-docs"))
        by_key = {f.key: f.message for f in findings}
        assert "search.undocumented" in by_key
        assert "no settings-table row" in by_key["search.undocumented"]
        assert "search.twice" in by_key
        assert "2 tables" in by_key["search.twice"]
        assert "search.unregistered" in by_key
        assert "search.documented" not in by_key

    def test_quarantine_pass_fires(self):
        ids = {f.id for f in _fixture_findings("quarantine-release")}
        for key in ("marker", "record", "staging-release"):
            assert (f"quarantine-release:quarantine_bad.py:"
                    f"BadQuarantiner.fail_copy:{key}") in ids
        assert not any("GoodQuarantiner" in i for i in ids)

    def test_quarantine_pass_sees_the_real_sites(self):
        # the pass is only trustworthy while it still matches the
        # quarantine population the tree actually has: the load-time
        # reconcile site is allowlisted (never-staged copy), so its
        # finding must keep existing for the stale check to hold
        findings = list(all_passes()["quarantine-release"].run(
            SourceTree()))
        assert any(f.qualname == "ClusterNode._reconcile_shards"
                   and f.key == "staging-release" for f in findings), (
            [f.id for f in findings])

    def test_fixture_files_parse(self):
        # the snippets are parsed, never imported — keep them valid AST
        for fname in sorted(os.listdir(FIXTURES)):
            if fname.endswith(".py"):
                with open(os.path.join(FIXTURES, fname)) as f:
                    ast.parse(f.read())


# ---------------------------------------------------------------------------
# 3. runtime lock-order witness
# ---------------------------------------------------------------------------


class TestLockOrderWitness:
    @pytest.fixture(autouse=True)
    def _instrument_test_locks(self, monkeypatch):
        # locks created by THIS file count as package locks for the
        # duration (the witness only instruments in-package creations)
        from elasticsearch_tpu.testing import lockwitness

        monkeypatch.setattr(lockwitness, "_PKG_DIR",
                            os.path.dirname(os.path.abspath(__file__)))

    def test_consistent_order_is_green(self):
        from elasticsearch_tpu.testing.lockwitness import (
            lock_order_witness,
        )

        with lock_order_witness() as w:
            a = threading.Lock()
            b = threading.Lock()

            def worker():
                with a:
                    with b:
                        pass

            threads = [threading.Thread(target=worker) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            with a:
                with b:
                    pass
        assert w.edges(), "witness observed nothing"
        assert w.find_cycle() is None
        w.assert_acyclic()

    def test_inversion_is_caught(self):
        from elasticsearch_tpu.testing.lockwitness import (
            LockOrderViolation,
            lock_order_witness,
        )

        with lock_order_witness() as w:
            a = threading.Lock()
            b = threading.Lock()
            # sequential on one thread: the ORDER inversion is the bug
            # signal, no actual deadlock needed (Eraser-style)
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        assert w.find_cycle() is not None
        with pytest.raises(LockOrderViolation):
            w.assert_acyclic()

    def test_rlock_reentrancy_records_no_pair(self):
        from elasticsearch_tpu.testing.lockwitness import (
            lock_order_witness,
        )

        with lock_order_witness() as w:
            r = threading.RLock()
            with r:
                with r:  # reentrant: not an ordering observation
                    pass
        assert w.edges() == {}
        assert w.same_site_nestings() == {}

    def test_same_site_distinct_instances_reported_not_failed(self):
        from elasticsearch_tpu.testing.lockwitness import (
            lock_order_witness,
        )

        with lock_order_witness() as w:
            l1, l2 = [threading.Lock() for _ in range(2)]
            with l1:
                with l2:  # same creation site, different instances
                    pass
        assert w.same_site_nestings(), "same-site nesting not recorded"
        w.assert_acyclic()  # but never a failure by itself

    def test_condition_and_event_still_work_installed(self):
        from elasticsearch_tpu.testing.lockwitness import (
            lock_order_witness,
        )

        with lock_order_witness():
            cv = threading.Condition()
            done = threading.Event()
            out = []

            def waiter():
                with cv:
                    while not out:
                        cv.wait(timeout=5.0)
                done.set()

            t = threading.Thread(target=waiter)
            t.start()
            with cv:
                out.append(1)
                cv.notify_all()
            assert done.wait(timeout=5.0)
            t.join(timeout=5.0)

    def test_wrap_existing_observes_preexisting_locks(self):
        # locks created BEFORE install (module globals, singletons) are
        # invisible unless wrapped — the soak helper's central-lock gap
        from elasticsearch_tpu.testing.lockwitness import (
            lock_order_witness,
        )

        class Holder:
            pass

        h = Holder()
        h.lock = threading.Lock()      # created pre-install
        h.rlock = threading.RLock()
        orig_lock, orig_rlock = h.lock, h.rlock
        with lock_order_witness() as w:
            w.wrap_existing(h, "lock", "pre:lock")
            w.wrap_existing(h, "rlock", "pre:rlock")
            with h.lock:
                with h.rlock:
                    pass
        assert ("pre:lock", "pre:rlock") in w.edges()
        # uninstall restored the original objects
        assert h.lock is orig_lock
        assert h.rlock is orig_rlock

    def test_uninstall_restores_factories(self):
        from elasticsearch_tpu.testing.lockwitness import (
            lock_order_witness,
        )

        before_lock = threading.Lock
        before_rlock = threading.RLock
        with lock_order_witness():
            assert threading.Lock is not before_lock
        assert threading.Lock is before_lock
        assert threading.RLock is before_rlock
