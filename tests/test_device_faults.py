"""Device-plane fault injection + transactional staging (ISSUE 10).

The device serving planes get the same explicit failure contract the
transport/query layers got in PR 2/4: staging faults classify
transient (bounded retry w/ backoff) vs deterministic (immediate ladder
demotion + quarantine with reason ``staging_fault``), a fault
mid-staging rolls back every partial registration (ledger leak-free),
and the post-cooldown quarantine probe is SINGLE-FLIGHT — N concurrent
queries arriving after cooldown pay the fault exactly once.
"""

import threading
import time

import pytest

from elasticsearch_tpu.common.memory import memory_accountant
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.common.staging import (
    TransientDeviceError,
    classify_staging_fault,
    run_staged,
    staging_retry_config,
)
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.testing.disruption import (
    EvictionStormScheme,
    KernelLaunchFailScheme,
    PlaneFailScheme,
    StagingFailScheme,
    clear_search_disruptions,
)

MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "vec": {"type": "dense_vector", "dims": 8},
    "n": {"type": "integer"},
}}


@pytest.fixture(autouse=True)
def _clean_schemes():
    clear_search_disruptions()
    yield
    clear_search_disruptions()


def make_index(name, shards=3, cooldown="150ms", plane="pallas",
               vectors=False):
    idx = IndexService(name, Settings({
        "index.number_of_shards": shards,
        "index.search.mesh": True,
        "index.search.mesh.plane": plane,
        "index.search.plane_quarantine.cooldown": cooldown,
        "index.refresh_interval": -1,
    }), mapping=MAPPING)
    for d in range(30):
        doc = {"body": f"w{d % 5} common", "n": d}
        if vectors:
            doc["vec"] = [float((d + j) % 7) for j in range(8)]
        idx.index_doc(str(d), doc)
    idx.refresh()
    return idx


class TestClassification:
    def test_transient_shapes(self):
        assert classify_staging_fault(TransientDeviceError("x")) \
            == "transient"
        assert classify_staging_fault(MemoryError()) == "transient"
        assert classify_staging_fault(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                         "allocating")) == "transient"
        assert classify_staging_fault(
            RuntimeError("transfer to device failed")) == "transient"

    def test_deterministic_shapes(self):
        assert classify_staging_fault(ValueError("bad shape")) \
            == "deterministic"
        assert classify_staging_fault(TypeError("x")) == "deterministic"
        assert classify_staging_fault(
            RuntimeError("Mosaic lowering failed")) == "deterministic"


class TestRunStaged:
    def test_transient_retries_then_succeeds(self):
        attempts = []

        def fn():
            attempts.append(1)
            if len(attempts) < 3:
                raise TransientDeviceError("RESOURCE_EXHAUSTED")
            return "ok"

        before = memory_accountant().staging_retries_total
        out = run_staged(fn, index="t", kind="postings_raw",
                         retry=(3, 0.0))
        assert out == "ok"
        assert len(attempts) == 3
        assert memory_accountant().staging_retries_total == before + 2

    def test_transient_exhaustion_records_fault(self):
        acct = memory_accountant()
        before = acct.staging_faults_transient_total

        def fn():
            raise TransientDeviceError("RESOURCE_EXHAUSTED")

        with pytest.raises(TransientDeviceError):
            run_staged(fn, index="t", kind="postings_raw", retry=(2, 0.0))
        assert acct.staging_faults_transient_total == before + 1
        ev = acct.staging_fault_events[-1]
        assert ev["classification"] == "transient"
        assert ev["retries"] == 1

    def test_deterministic_never_retries(self):
        acct = memory_accountant()
        attempts = []
        before = acct.staging_faults_deterministic_total

        def fn():
            attempts.append(1)
            raise ValueError("shape")

        with pytest.raises(ValueError):
            run_staged(fn, index="t", kind="live_mask", retry=(5, 0.0))
        assert len(attempts) == 1
        assert acct.staging_faults_deterministic_total == before + 1

    def test_config_reads_settings_and_defaults(self):
        s = Settings({"search.staging.retry.max_attempts": 5,
                      "search.staging.retry.backoff_ms": 2.5})
        assert staging_retry_config(s) == (5, 2.5)
        attempts, backoff = staging_retry_config(None)
        assert attempts >= 1 and backoff >= 0.0


class TestStagingRetrySettings:
    def test_cluster_override_wins_and_clears(self):
        """Explicitness-aware dynamic updates (like search.pallas.*):
        an explicit cluster value wins, clearing it reverts to the
        node-file setting."""
        from elasticsearch_tpu.common.staging import (
            configure_staging_retry,
            staging_retry_config,
        )
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"search.staging.retry.max_attempts": 4,
                              "search.staging.retry.backoff_ms": 5.0}))
        try:
            assert staging_retry_config() == (4, 5.0)
            node.put_cluster_settings({"transient": {
                "search.staging.retry.max_attempts": 7}})
            assert staging_retry_config()[0] == 7
            node.put_cluster_settings({"transient": {
                "search.staging.retry.max_attempts": None}})
            assert staging_retry_config()[0] == 4  # node file wins again
        finally:
            node.close()
            configure_staging_retry(max_attempts=3, backoff_ms=10.0)

    def test_rejects_out_of_range(self):
        from elasticsearch_tpu.common.errors import (
            IllegalArgumentException,
        )
        from elasticsearch_tpu.common.settings import (
            SEARCH_STAGING_RETRY_MAX_ATTEMPTS,
        )

        with pytest.raises(IllegalArgumentException):
            SEARCH_STAGING_RETRY_MAX_ATTEMPTS.get(
                Settings({"search.staging.retry.max_attempts": 0}))


class TestTransientRetryAbsorbsFault:
    """A transient staging fault under the retry budget is INVISIBLE to
    the ladder: the query serves from the fast plane, first try."""

    def test_mesh_staging_transient_absorbed(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dfretry")
        scheme = StagingFailScheme(kinds=["postings"], transient=True,
                                   times=2, indices=["dfretry"]).install()
        retries_before = memory_accountant().staging_retries_total
        r = idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
        assert r["_plane"] == "mesh_pallas", r["_plane"]
        assert r["_shards"]["failed"] == 0
        assert scheme.hits == 2
        assert memory_accountant().staging_retries_total \
            == retries_before + 2
        planes = idx.stats()["total"]["search"]["planes"]
        assert planes["plane_failures_total"].get("mesh_pallas", 0) == 0
        idx.close()


class TestStagingLeakFreedom:
    """Satellite: a deterministic fault at each kind boundary rolls the
    per-kind ledger back EXACTLY to the pre-attempt snapshot, demotes
    with reason staging_fault, and the next unfaulted query self-heals
    back onto the fast plane."""

    def _snapshot(self, name):
        return memory_accountant().staged_bytes_by_kind(name)

    def _run_kind_case(self, monkeypatch, name, kinds, faulted_kinds,
                       expect_demote="host"):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index(name)
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        # pre-warm the host rung compile so assertions don't race
        idx._search_uncached(dict(body), skip_mesh=True)
        snap = self._snapshot(name)
        scheme = StagingFailScheme(kinds=kinds, transient=False,
                                   indices=[name]).install()
        t_fault = time.monotonic()
        r = idx.search(dict(body))
        assert scheme.hits >= 1, f"scheme never consulted for {kinds}"
        assert r["_plane"] == expect_demote, (r["_plane"], kinds)
        assert r["_shards"]["failed"] == 0
        after = self._snapshot(name)
        for kind in faulted_kinds:
            assert after[kind] == snap[kind], (
                f"kind [{kind}] leaked bytes after a mid-staging fault: "
                f"{after[kind]} != {snap[kind]}")
        planes = idx.stats()["total"]["search"]["planes"]
        assert planes["plane_failures_by_reason"].get(
            "staging_fault", 0) >= 1, planes
        decisions = idx.search_stats()["phases"]["decisions"]
        assert any(k.endswith(".staging_fault") for k in decisions), \
            decisions
        # fault clears: the next query (post-cooldown) self-heals back
        # onto the fast plane and stages real bytes
        scheme.remove()
        time.sleep(max(0.0, t_fault + 0.25 - time.monotonic()))
        r = idx.search(dict(body, size=6))
        assert r["_plane"] == "mesh_pallas", (
            f"index stranded off its fast plane after the {kinds} fault "
            f"cleared: {r['_plane']}")
        healed = self._snapshot(name)
        for kind in faulted_kinds:
            assert healed[kind] >= snap[kind]
        idx.close()
        for kind, nbytes in self._snapshot(name).items():
            assert nbytes == 0, (kind, nbytes)

    def test_mesh_slot_tables_boundary(self, monkeypatch):
        # constructor-level fault: NOTHING may register
        self._run_kind_case(monkeypatch, "dfslot",
                            ["mesh_slot_tables"],
                            ["mesh_slot_tables", "postings_raw",
                             "live_mask"])

    def test_postings_boundary(self, monkeypatch):
        # ensure_kernel fault AFTER the base executor staged: the
        # postings/live_mask tables roll back; seg_stacked legitimately
        # stays (it committed)
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dfpost")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        idx._search_uncached(dict(body), skip_mesh=True)
        snap = self._snapshot("dfpost")
        scheme = StagingFailScheme(kinds=["postings"], transient=False,
                                   indices=["dfpost"]).install()
        t_fault = time.monotonic()
        r = idx.search(dict(body))
        assert r["_plane"] == "host"
        assert r["_shards"]["failed"] == 0
        ms = idx._mesh_search
        ex = ms._executor
        assert ex is not None
        # no half-staged executor generation: the kernel keys rolled back
        for key in ("k_packed", "k_docs", "k_frac", "k_live_t"):
            assert key not in ex._seg_staged, key
        after = self._snapshot("dfpost")
        for kind in ("postings_packed", "bound_tables"):
            assert after[kind] == snap[kind], kind
        scheme.remove()
        time.sleep(max(0.0, t_fault + 0.25 - time.monotonic()))
        r = idx.search(dict(body, size=6))
        assert r["_plane"] == "mesh_pallas", r["_plane"]
        idx.close()

    def test_live_mask_boundary(self, monkeypatch):
        self._run_kind_case(monkeypatch, "dflive", ["live_mask"],
                            ["live_mask"])

    def test_embeddings_boundary(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dfemb", vectors=True)
        body = {"knn": {"field": "vec", "query_vector": [1.0] * 8,
                        "k": 5}}
        idx._search_uncached(dict(body), skip_mesh=True)  # host warm
        snap = self._snapshot("dfemb")
        scheme = StagingFailScheme(kinds=["embeddings"], transient=False,
                                   indices=["dfemb"]).install()
        t_fault = time.monotonic()
        # the mesh kNN staging faults; the segment-level host staging
        # already holds its (committed) embedding bytes — only the MESH
        # scope's attempt must roll back
        r = idx.search(dict(body))
        assert r["_plane"] == "host"
        assert r["_shards"]["failed"] == 0
        after = self._snapshot("dfemb")
        assert after["embeddings"] == snap["embeddings"], (
            f"mesh kNN staging leaked embedding bytes: "
            f"{after['embeddings']} != {snap['embeddings']}")
        assert after["scale_norm"] == snap["scale_norm"]
        scheme.remove()
        time.sleep(max(0.0, t_fault + 0.25 - time.monotonic()))
        r = idx.search(dict(body))
        assert r["_plane"] == "mesh_pallas", r["_plane"]
        assert self._snapshot("dfemb")["embeddings"] \
            > snap["embeddings"]
        idx.close()

    def test_doc_values_boundary(self, monkeypatch):
        # host-rung sort column: transient fault absorbed by the retry
        # (the column is mandatory for the consumer), ledger exact
        monkeypatch.setenv("ES_TPU_PALLAS", "off")
        idx = make_index("dfcol", plane="auto")
        # a range clause stages its numeric doc-value columns lazily
        body = {"query": {"bool": {
            "must": [{"match": {"body": "w1"}}],
            "filter": [{"range": {"n": {"gte": 3}}}]}}, "size": 5}
        snap = self._snapshot("dfcol")
        scheme = StagingFailScheme(kinds=["doc_values"], transient=True,
                                   times=1, indices=["dfcol"]).install()
        r = idx._search_uncached(dict(body), skip_mesh=True)
        assert scheme.hits == 1
        assert r["_shards"]["failed"] == 0
        assert self._snapshot("dfcol")["doc_values"] \
            > snap["doc_values"]
        idx.close()


class TestKernelLaunchFail:
    def test_rung_selective_fault_quarantines(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dflaunch")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        idx._search_uncached(dict(body), skip_mesh=True)
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        scheme = KernelLaunchFailScheme(rungs=("mesh_pallas",), times=1,
                                        indices=["dflaunch"]).install()
        r = idx.search(dict(body))
        assert r["_plane"] == "host"
        assert scheme.hits == 1
        planes = idx.stats()["total"]["search"]["planes"]
        assert planes["plane_failures_total"]["mesh_pallas"] == 1
        assert planes["plane_failures_by_reason"].get(
            "kernel_fault", 0) == 1
        idx.close()


class TestEvictionStorm:
    def test_forced_eviction_restages_byte_identically(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dfstorm")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        baseline = idx.search(dict(body))
        assert baseline["_plane"] == "mesh_pallas"
        acct = memory_accountant()
        ev_before = acct.evictions_total
        scheme = EvictionStormScheme(period=1,
                                     indices=["dfstorm"]).install()
        r = idx.search(dict(body))
        assert acct.evictions_total > ev_before
        assert scheme.hits >= 1
        assert r["_shards"]["failed"] == 0
        assert [(h["_id"], h["_score"]) for h in r["hits"]["hits"]] == \
            [(h["_id"], h["_score"]) for h in baseline["hits"]["hits"]]
        scheme.remove()
        r = idx.search(dict(body))
        assert [(h["_id"], h["_score"]) for h in r["hits"]["hits"]] == \
            [(h["_id"], h["_score"]) for h in baseline["hits"]["hits"]]
        idx.close()


class TestSingleFlightProbe:
    """Satellite: after quarantine cooldown, N concurrent queries make
    exactly ONE probe attempt; peers serve the healthy rung."""

    def test_one_probe_for_concurrent_burst(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dfprobe", cooldown="200ms")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        idx._search_uncached(dict(body), skip_mesh=True)  # host warm
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        scheme = PlaneFailScheme(planes=("mesh_pallas",),
                                 indices=["dfprobe"]).install()
        t_fault = time.monotonic()
        assert idx.search(dict(body))["_plane"] == "host"
        health = idx._mesh_search.plane_health
        assert health.failures_total["mesh_pallas"] == 1
        # scheme STAYS installed: the probe will fail again. Wait out
        # the cooldown, then fire a concurrent burst — single-flight
        # means the fault is paid exactly ONCE more.
        time.sleep(max(0.0, t_fault + 0.3 - time.monotonic()))
        n = 6
        barrier = threading.Barrier(n)
        results, errors = [], []

        def worker():
            barrier.wait()
            try:
                results.append(idx._search_uncached(dict(body)))
            except Exception as e:  # noqa: BLE001 — zero-5xx contract
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert len(results) == n
        assert all(r["_plane"] == "host" for r in results)
        assert all(r["hits"]["total"] == results[0]["hits"]["total"]
                   for r in results)
        assert scheme.hits == 2, (
            f"post-cooldown herd re-paid the fault {scheme.hits - 1} "
            f"times; single-flight allows exactly 1 probe")
        assert health.failures_total["mesh_pallas"] == 2
        assert health.probes_total == 1
        # probe success path: remove the scheme, wait, serve again
        scheme.remove()
        time.sleep(0.3)
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        assert health.quarantined() == []
        assert idx.stats()["total"]["search"]["planes"][
            "plane_probes_total"] == 2
        idx.close()

    def test_probe_released_when_plane_bails_cleanly(self, monkeypatch):
        """A probe that can't execute (staging says no) must hand its
        admission back instead of wedging the plane half-open for the
        whole lease."""
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        idx = make_index("dfrel", cooldown="100ms")
        body = {"query": {"match": {"body": "w1"}}, "size": 5}
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        health = idx._mesh_search.plane_health
        health.record_failure("mesh_pallas")
        time.sleep(0.15)
        # bench the STAGING too: the admitted probe bails pre-launch
        idx._mesh_search._staging_fault_until = time.monotonic() + 0.2
        r = idx.search(dict(body))
        assert r["_plane"] == "host"
        # admission handed back: once staging heals, the NEXT query may
        # probe (a leaked lease would block it for PROBE_LEASE_S)
        time.sleep(0.25)
        assert idx.search(dict(body))["_plane"] == "mesh_pallas"
        idx.close()
