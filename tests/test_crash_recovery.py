"""Crash recovery: translog torn-tail tolerance, corrupt-generation
handling, and a real SIGKILL-mid-bulk recovery over the TCP worker.

Role models: the reference's TranslogTests (torn-write/corruption cases,
index/translog/TranslogTests.java) and the full-restart recovery ITs
(gateway/RecoveryFromGatewayIT): every ACKED write survives a kill -9,
an unacked torn append is dropped with a warning, and unreadable data at
or below the checkpoint fails recovery loudly instead of losing writes
silently.
"""

import json
import logging
import os
import subprocess
import sys

import pytest

from elasticsearch_tpu.common.errors import TranslogCorruptedException
from elasticsearch_tpu.index.translog import Translog, TranslogOp


def _add_ops(tl, seqnos):
    for s in seqnos:
        tl.add(TranslogOp(TranslogOp.INDEX, s, doc_id=f"d{s}",
                          source={"n": s}))


def _gen_file(tl, gen):
    return os.path.join(tl.directory, f"translog-{gen}.log")


class TestTornTail:
    def test_torn_final_line_tolerated(self, tmp_path, caplog):
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(5))
        tl._writer.flush()
        # crash mid-append: a partial JSON line at the tail
        with open(_gen_file(tl, tl.generation), "a",
                  encoding="utf-8") as f:
            f.write('{"op": "index", "seq_no": 5, "id": "d5", "sour')
        with caplog.at_level(logging.WARNING,
                             "elasticsearch_tpu.index.translog"):
            reopened = Translog(str(tmp_path / "t"))
            ops = reopened.snapshot()
        assert [op.seqno for op in ops] == [0, 1, 2, 3, 4]
        assert any("truncated final line" in r.message for r in caplog.records)

    def test_write_after_torn_tail_not_merged(self, tmp_path):
        # the reopened writer appends: the torn fragment must be TRIMMED
        # at open or the next acked op concatenates onto it and is lost
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(3))
        tl._writer.flush()
        with open(_gen_file(tl, tl.generation), "a",
                  encoding="utf-8") as f:
            f.write('{"op": "index", "seq_no": 3, "id": "d3", "sou')
        restarted = Translog(str(tmp_path / "t"))
        _add_ops(restarted, [3])  # acked write after the restart
        restarted._writer.flush()
        # a SECOND crash/restart must still replay the post-restart op
        again = Translog(str(tmp_path / "t"))
        assert [op.seqno for op in again.snapshot()] == [0, 1, 2, 3]

    def test_complete_tail_missing_newline_kept(self, tmp_path):
        # crash between the json write and its newline: the op is whole
        # and durable — terminate the line, don't drop it
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(3))
        tl._writer.flush()
        path = _gen_file(tl, tl.generation)
        data = open(path, "rb").read()
        open(path, "wb").write(data.rstrip(b"\n"))
        restarted = Translog(str(tmp_path / "t"))
        _add_ops(restarted, [3])
        restarted._writer.flush()
        again = Translog(str(tmp_path / "t"))
        assert [op.seqno for op in again.snapshot()] == [0, 1, 2, 3]

    def test_mid_file_corruption_raises(self, tmp_path):
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(5))
        tl.close()
        path = _gen_file(tl, tl.generation)
        lines = open(path, encoding="utf-8").read().splitlines()
        lines[2] = lines[2][: len(lines[2]) // 2]  # torn NOT at the tail
        open(path, "w", encoding="utf-8").write("\n".join(lines) + "\n")
        reopened = Translog(str(tmp_path / "t"))
        with pytest.raises(TranslogCorruptedException, match="mid-file"):
            reopened.snapshot()

    def test_torn_tail_below_checkpoint_raises(self, tmp_path):
        # the tear swallows ops the checkpoint says are committed: that
        # is corruption, not a benign in-flight append
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(6))
        tl.committed_seqno = 5
        tl.sync()
        tl.close()
        path = _gen_file(tl, tl.generation)
        lines = open(path, encoding="utf-8").read().splitlines()
        torn = lines[:4] + [lines[4][:10]]  # ops 4..5 lost, both committed
        open(path, "w", encoding="utf-8").write("\n".join(torn) + "\n")
        reopened = Translog(str(tmp_path / "t"))
        with pytest.raises(TranslogCorruptedException,
                           match="checkpointed seqno"):
            reopened.snapshot()

    def test_shard_recovery_replays_up_to_torn_tail(self, tmp_path):
        from elasticsearch_tpu.index.shard import IndexShard
        from elasticsearch_tpu.mapper.mapping import MapperService
        from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry

        mapper = MapperService(AnalysisRegistry(None), {"properties": {}})
        path = str(tmp_path / "shard0")
        shard = IndexShard("cr", 0, mapper, data_path=path)
        shard.start_fresh()
        for i in range(8):
            shard.index_doc(f"d{i}", {"n": i})
        tl_path = shard.engine.translog._gen_path(
            shard.engine.translog.generation)
        # simulated kill -9: the engine is never closed; a torn line is
        # appended to the live generation file
        with open(tl_path, "a", encoding="utf-8") as f:
            f.write('{"op": "index", "seq_no": 8, "id": "d8", "so')
        recovered = IndexShard("cr", 0, mapper, data_path=path)
        recovered.recover_from_store()
        recovered.refresh()
        assert recovered.num_docs == 8
        for i in range(8):
            assert recovered.get_doc(f"d{i}").found
        stats = recovered.seq_no_stats()
        assert stats["max_seq_no"] == 7
        assert stats["local_checkpoint"] == 7
        recovered.close()


class TestCorruptGeneration:
    def _corrupted(self, tmp_path):
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(3))
        tl.roll_generation()
        _add_ops(tl, range(3, 6))
        path = _gen_file(tl, 1)
        lines = open(path, encoding="utf-8").read().splitlines()
        lines[1] = "{corrupt"
        open(path, "w", encoding="utf-8").write("\n".join(lines) + "\n")
        return tl, path

    def test_detected_surfaced_and_retained(self, tmp_path, caplog):
        tl, path = self._corrupted(tmp_path)
        with caplog.at_level(logging.WARNING,
                             "elasticsearch_tpu.index.translog"):
            tl.mark_committed(2)  # would have trimmed a healthy gen 1
        assert os.path.exists(path), "corrupt gen must be retained"
        assert tl.corrupt_generations == {1}
        assert any("corrupt" in r.message for r in caplog.records)
        stats = tl.stats()
        assert stats["corrupt_generations"] == [1]
        assert stats["earliest_retained_generation"] == 1
        # observability counts keep serving: the corrupt generation's
        # readable prefix (op 0) + the healthy generation's 3 ops
        assert stats["operations"] == 4
        tl.close()

    def test_deleted_once_fully_committed(self, tmp_path):
        tl, path = self._corrupted(tmp_path)
        tl.mark_committed(2)
        assert os.path.exists(path)
        # everything ever logged is now committed: nothing an unreadable
        # generation could hide remains unacked -> safe to delete
        tl.mark_committed(tl.max_seqno)
        assert not os.path.exists(path)
        assert tl.corrupt_generations == set()
        stats = tl.stats()
        assert stats["corrupt_generations"] == []
        assert stats["earliest_retained_generation"] == tl.generation
        tl.close()

    def test_healthy_trim_unaffected(self, tmp_path):
        tl = Translog(str(tmp_path / "t"))
        _add_ops(tl, range(3))
        tl.roll_generation()
        _add_ops(tl, range(3, 6))
        tl.mark_committed(2)
        assert not os.path.exists(_gen_file(tl, 1))
        assert tl.stats()["earliest_retained_generation"] == 2
        tl.close()


class CrashWorker:
    """One tcp_cluster_worker.py OS process with a durable data path."""

    def __init__(self, name, data_path):
        self.name = name
        self.data_path = data_path
        script = os.path.join(os.path.dirname(__file__),
                              "tcp_cluster_worker.py")
        self.proc = subprocess.Popen(
            [sys.executable, script, name, "0", data_path],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1)
        ready = json.loads(self._readline(timeout=120))
        assert ready.get("ready")
        self.port = ready["port"]

    def _readline(self, timeout=60):
        import select

        r, _, _ = select.select([self.proc.stdout], [], [], timeout)
        if not r:
            raise TimeoutError(f"worker {self.name} silent")
        return self.proc.stdout.readline()

    def send(self, op, **kw):
        """Fire a command WITHOUT reading the reply (for kill races)."""
        self.proc.stdin.write(json.dumps({"op": op, **kw}) + "\n")
        self.proc.stdin.flush()

    def call(self, op, **kw):
        self.send(op, **kw)
        resp = json.loads(self._readline())
        if not resp.get("ok"):
            raise RuntimeError(f"{self.name} {op}: {resp.get('error')}")
        return resp

    def kill(self):
        self.proc.kill()  # SIGKILL: no shutdown hooks, no final fsync
        self.proc.wait()

    def stop(self):
        if self.proc.poll() is None:
            try:
                self.call("exit")
            except Exception:
                pass
            self.proc.wait(timeout=10)


class TestSigkillRecovery:
    INDEX_SETTINGS = {"index": {"number_of_shards": 2,
                                "number_of_replicas": 0}}

    def test_acked_writes_survive_sigkill_mid_bulk(self, tmp_path):
        data = str(tmp_path / "n1")
        w = CrashWorker("n1", data)
        acked = []
        try:
            w.call("bootstrap")
            w.call("create_index", index="cr",
                   settings=self.INDEX_SETTINGS)
            for i in range(25):
                w.call("index", index="cr", id=str(i),
                       doc={"n": i, "msg": f"bulk item {i}"})
                acked.append(str(i))
            # one more op goes out but the ack is never read: the node is
            # SIGKILLed with the append in flight (mid-bulk crash)
            w.send("index", index="cr", id="inflight",
                   doc={"n": 99, "msg": "never acked"})
        finally:
            w.kill()

        # restart over the same data path: translog replay must bring
        # back every acked write
        w2 = CrashWorker("n1", data)
        try:
            w2.call("bootstrap")
            w2.call("create_index", index="cr",
                    settings=self.INDEX_SETTINGS)
            w2.call("refresh", index="cr")
            res = w2.call("search", index="cr",
                          body={"size": 50})["result"]
            hits = res["hits"]["hits"]
            got_ids = [h["_id"] for h in hits]
            # no loss: every acked write replayed; no duplicates: each id
            # appears exactly once (replay is seqno-idempotent)
            assert set(got_ids) >= set(acked), \
                sorted(set(acked) - set(got_ids))
            assert len(got_ids) == len(set(got_ids))
            assert set(got_ids) - set(acked) <= {"inflight"}
            for i in (0, 7, 24):
                got = w2.call("get", index="cr", id=str(i))["result"]
                assert got["_source"]["n"] == i
            # no duplicate/gapped seqnos after replay: each shard's local
            # checkpoint caught up to its max assigned seqno
            stats = w2.call("seq_stats")["result"]
            assert stats, "expected recovered shards"
            for key, s in stats.items():
                assert s["local_checkpoint"] == s["max_seq_no"], (key, s)
            n_ops = sum(s["max_seq_no"] + 1 for s in stats.values())
            assert n_ops == len(got_ids)
        finally:
            w2.stop()
