"""Circuit breaker wiring (HierarchyCircuitBreakerService.java:43):
fielddata/request/in-flight breakers account real allocations and trip
as HTTP errors; stats ride _nodes/stats."""

import numpy as np
import pytest

from elasticsearch_tpu.common.breaker import (
    CircuitBreaker,
    CircuitBreakerService,
    breaker_service,
    configure_breaker_service,
)
from elasticsearch_tpu.common.errors import CircuitBreakingException
from elasticsearch_tpu.common.settings import Settings


@pytest.fixture(autouse=True)
def _restore_breakers():
    yield
    configure_breaker_service(Settings.EMPTY)


def make_node(**breaker_settings):
    from elasticsearch_tpu.node import Node

    node = Node(Settings.from_dict(breaker_settings) if breaker_settings
                else Settings.EMPTY)
    node.create_index("logs", {
        "mappings": {"_doc": {"properties": {
            "tag": {"type": "text"},
            "msg": {"type": "text"},
        }}}})
    for i in range(50):
        node.index_doc("logs", str(i), {
            "tag": f"t{i % 5}", "msg": f"event {i}"}, refresh=(i == 49))
    return node


class TestBreakerWiring:
    def test_request_breaker_trips_agg(self):
        node = make_node(**{"indices.breaker.total.limit": "5kb",
                            "indices.breaker.request.limit": "2kb"})
        with pytest.raises(Exception) as ei:
            node.search("logs", {
                "size": 0,
                "aggs": {"tags": {"terms": {"field": "tag"}}}})
        assert "circuit_breaking_exception" in str(
            getattr(ei.value, "to_dict", lambda: {"error": {"type": type(ei.value).__name__}})())

    def test_request_breaker_releases_after_request(self):
        node = make_node()
        node.search("logs", {"size": 0,
                             "aggs": {"tags": {"terms": {"field": "tag"}}}})
        breaker = node.breaker_service.get_breaker(CircuitBreaker.REQUEST)
        assert breaker.used_bytes == 0

    def test_fielddata_breaker_accounts_text_fielddata(self):
        node = make_node()
        before = node.breaker_service.get_breaker(
            CircuitBreaker.FIELDDATA).used_bytes
        node.search("logs", {"size": 0,
                             "aggs": {"tags": {"terms": {"field": "tag"}}}})
        after = node.breaker_service.get_breaker(
            CircuitBreaker.FIELDDATA).used_bytes
        assert after > before  # fielddata stays accounted (cache-resident)

    def test_inflight_breaker_trips_on_oversized_body(self):
        node = make_node(**{"indices.breaker.total.limit": "100mb"})
        from elasticsearch_tpu.rest.controller import RestController

        # shrink in-flight limit directly
        node.breaker_service.get_breaker(
            CircuitBreaker.IN_FLIGHT_REQUESTS).limit_bytes = 64
        ctrl = RestController(node)
        big = b'{"query": {"match": {"msg": "' + b"x" * 200 + b'"}}}'
        status, bodyr = ctrl.dispatch("POST", "/logs/_search", {}, big)
        assert status == 429
        assert bodyr["error"]["type"] == "circuit_breaking_exception"

    def test_parent_breaker_sums_children(self):
        svc = CircuitBreakerService(total_limit=100, request_limit=90,
                                    fielddata_limit=90)
        svc.get_breaker(CircuitBreaker.REQUEST) \
            .add_estimate_bytes_and_maybe_break(60, "a")
        with pytest.raises(CircuitBreakingException):
            svc.get_breaker(CircuitBreaker.FIELDDATA) \
                .add_estimate_bytes_and_maybe_break(60, "b")
        # failed reservation rolled back
        assert svc.get_breaker(CircuitBreaker.FIELDDATA).used_bytes == 0

    def test_breaker_stats_in_node_stats(self):
        node = make_node()
        st = node.node_stats()["nodes"][node.node_id]["breakers"]
        assert {"request", "fielddata", "in_flight_requests", "parent"} \
            <= set(st)
        assert st["request"]["limit_size_in_bytes"] > 0
