"""Index sorting + sorted-index early termination.

Mirrors IndexSortConfig (core/.../index/IndexSortConfig.java) and the
early-termination hook in QueryPhase.execute (search/query/QueryPhase.java:107).
"""

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def make_index(sort_settings, mapping=None, shards=1):
    base = {"index.number_of_shards": shards}
    base.update(sort_settings)
    return IndexService(
        "sorted", Settings(base),
        mapping=mapping or {"properties": {
            "rank": {"type": "long"},
            "name": {"type": "keyword"},
            "body": {"type": "text"},
        }},
    )


class TestValidation:
    def test_unknown_field_rejected(self):
        with pytest.raises(IllegalArgumentException, match="unknown index sort field"):
            make_index({"index.sort.field": ["nope"]})

    def test_text_field_rejected(self):
        with pytest.raises(IllegalArgumentException, match="invalid index sort field"):
            make_index({"index.sort.field": ["body"]})

    def test_nested_field_rejected(self):
        with pytest.raises(IllegalArgumentException, match="nested"):
            make_index({"index.sort.field": ["user.age"]},
                       mapping={"properties": {"user": {
                           "type": "nested",
                           "properties": {"age": {"type": "long"}}}}})

    def test_bad_order_rejected(self):
        with pytest.raises(IllegalArgumentException, match="Illegal sort order"):
            make_index({"index.sort.field": ["rank"],
                        "index.sort.order": ["sideways"]})

    def test_bad_missing_rejected(self):
        with pytest.raises(IllegalArgumentException, match="Illegal missing value"):
            make_index({"index.sort.field": ["rank"],
                        "index.sort.missing": ["zero"]})


class TestSortedSegments:
    def test_docs_stored_in_sort_order(self):
        idx = make_index({"index.sort.field": ["rank"]})
        for doc_id, rank in [("a", 30), ("b", 10), ("c", 20)]:
            idx.index_doc(doc_id, {"rank": rank, "name": doc_id})
        idx.refresh()
        seg = idx.shards[0].engine.segments[0]
        assert seg.doc_ids == ["b", "c", "a"]
        idx.close()

    def test_desc_and_secondary_key(self):
        idx = make_index({
            "index.sort.field": ["rank", "name"],
            "index.sort.order": ["desc", "asc"],
        })
        for doc_id, rank in [("x", 1), ("y", 2), ("z", 2)]:
            idx.index_doc(doc_id, {"rank": rank, "name": doc_id})
        idx.refresh()
        seg = idx.shards[0].engine.segments[0]
        assert seg.doc_ids == ["y", "z", "x"]
        idx.close()

    def test_keyword_sort_with_missing_last(self):
        idx = make_index({"index.sort.field": ["name"]})
        idx.index_doc("1", {"name": "beta", "rank": 1})
        idx.index_doc("2", {"rank": 2})  # missing name -> last
        idx.index_doc("3", {"name": "alpha", "rank": 3})
        idx.refresh()
        seg = idx.shards[0].engine.segments[0]
        assert seg.doc_ids == ["3", "1", "2"]
        idx.close()

    def test_get_update_delete_survive_permutation(self):
        idx = make_index({"index.sort.field": ["rank"]})
        idx.index_doc("a", {"rank": 5, "name": "first"})
        idx.index_doc("b", {"rank": 1, "name": "second"})
        idx.index_doc("c", {"rank": 3, "name": "third"})
        idx.delete_doc("c")
        idx.refresh()
        # realtime get goes through the version map's (remapped) local ids
        g = idx.get_doc("a")
        assert g.found and g.source["name"] == "first"
        assert idx.get_doc("c").found is False
        r = idx.search({"query": {"match_all": {}}})
        assert r["hits"]["total"] == 2
        # update after refresh still targets the right doc
        idx.index_doc("a", {"rank": 5, "name": "updated"})
        idx.refresh()
        assert idx.get_doc("a").source["name"] == "updated"
        assert idx.search({"query": {"match_all": {}}})["hits"]["total"] == 2
        idx.close()

    def test_force_merge_keeps_sort(self):
        idx = make_index({"index.sort.field": ["rank"]})
        idx.index_doc("a", {"rank": 9})
        idx.refresh()
        idx.index_doc("b", {"rank": 2})
        idx.refresh()
        idx.shards[0].engine.force_merge()
        seg = idx.shards[0].engine.segments[0]
        assert seg.doc_ids == ["b", "a"]
        assert idx.get_doc("a").found
        idx.close()


class TestEarlyTermination:
    def test_sorted_query_terminates_early(self):
        idx = make_index({"index.sort.field": ["rank"]})
        for i in range(20):
            idx.index_doc(str(i), {"rank": (i * 7) % 20, "name": f"n{i}"})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 5,
                        "sort": [{"rank": "asc"}]})
        ranks = [h["sort"][0] for h in r["hits"]["hits"]]
        assert ranks == sorted(ranks) and len(ranks) == 5
        assert ranks == [0, 1, 2, 3, 4]
        # exact totals stay (dense execution), but the early-stop contract
        # is reported like the reference
        assert r["hits"]["total"] == 20
        assert r.get("terminated_early") is True
        idx.close()

    def test_prefix_of_index_sort_qualifies(self):
        idx = make_index({
            "index.sort.field": ["rank", "name"],
            "index.sort.order": ["desc", "asc"],
        })
        for i in range(10):
            idx.index_doc(str(i), {"rank": i, "name": f"n{i}"})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 3,
                        "sort": [{"rank": "desc"}]})
        assert [h["sort"][0] for h in r["hits"]["hits"]] == [9, 8, 7]
        assert r.get("terminated_early") is True
        idx.close()

    def test_mismatched_sort_not_early_terminated(self):
        idx = make_index({"index.sort.field": ["rank"]})
        for i in range(10):
            idx.index_doc(str(i), {"rank": i, "name": f"n{i}"})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 3,
                        "sort": [{"rank": "desc"}]})  # opposite order
        assert [h["sort"][0] for h in r["hits"]["hits"]] == [9, 8, 7]
        assert r.get("terminated_early") is None
        idx.close()

    def test_small_result_not_marked_terminated(self):
        idx = make_index({"index.sort.field": ["rank"]})
        idx.index_doc("1", {"rank": 1})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 10,
                        "sort": [{"rank": "asc"}]})
        assert r.get("terminated_early") is None
        idx.close()

    def test_doc_values_disabled_rejected(self):
        with pytest.raises(IllegalArgumentException, match="docvalues not found"):
            make_index({"index.sort.field": ["rank"]},
                       mapping={"properties": {
                           "rank": {"type": "long", "doc_values": False}}})

    def test_missing_mismatch_not_early_terminated(self):
        # query missing=_first disagrees with the index sort's _last —
        # early termination would pick the wrong first-k docs
        idx = make_index({"index.sort.field": ["rank"]})
        idx.index_doc("a", {"rank": 10})
        idx.index_doc("b", {"name": "no-rank"})
        idx.index_doc("c", {"rank": 20})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 2,
                        "sort": [{"rank": {"order": "asc", "missing": "_first"}}]})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["b", "a"]
        assert r.get("terminated_early") is None
        idx.close()

    def test_keyword_desc_multivalue_not_early_terminated(self):
        # default desc mode (max) disagrees with the query's ordinal key
        # (first/min value): segment order can't serve the first-k cut
        idx = make_index({"index.sort.field": ["name"],
                          "index.sort.order": ["desc"]})
        idx.index_doc("d1", {"name": ["a", "z"]})
        idx.index_doc("d2", {"name": "m"})
        idx.index_doc("d3", {"name": "b"})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 2,
                        "sort": [{"name": "desc"}]})
        assert r.get("terminated_early") is None
        idx.close()

    def test_keyword_asc_multivalue_uses_min_value(self):
        # first_ord must be the doc's MIN ordinal deterministically, so
        # segment order (mode min) agrees with the query's merge keys
        idx = make_index({"index.sort.field": ["name"]})
        idx.index_doc("d1", {"name": ["z", "a"]})
        idx.index_doc("d2", {"name": "b"})
        idx.index_doc("d3", {"name": "c"})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 2,
                        "sort": [{"name": "asc"}]})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["d1", "d2"]
        assert r.get("terminated_early") is True
        idx.close()

    def test_multi_segment_results_merge_correctly(self):
        idx = make_index({"index.sort.field": ["rank"]})
        for i, rank in enumerate([5, 3, 9]):
            idx.index_doc(f"a{i}", {"rank": rank})
        idx.refresh()
        for i, rank in enumerate([4, 1, 8]):
            idx.index_doc(f"b{i}", {"rank": rank})
        idx.refresh()
        r = idx.search({"query": {"match_all": {}}, "size": 4,
                        "sort": [{"rank": "asc"}]})
        assert [h["sort"][0] for h in r["hits"]["hits"]] == [1, 3, 4, 5]
        idx.close()
