"""HTTP client: round-robin, dead-host marking, retries, sniffing
(client/rest/.../RestClient.java + sniffer semantics)."""

import pytest

from elasticsearch_tpu.client import (
    HttpClient,
    NoLiveHostError,
    TransportError,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.http_server import HttpServer


@pytest.fixture()
def cluster():
    nodes, servers = [], []
    for _ in range(2):
        n = Node()
        s = HttpServer(n, port=0)
        s.start()
        nodes.append(n)
        servers.append(s)
    yield nodes, servers
    for s in servers:
        s.stop()
    for n in nodes:
        n.close()


class TestHttpClient:
    def test_round_robin_rotates_hosts(self, cluster):
        nodes, servers = cluster
        client = HttpClient([f"http://127.0.0.1:{s.port}" for s in servers])
        seen = {client.request("GET", "/").host for _ in range(4)}
        assert len(seen) == 2  # both hosts served requests

    def test_error_responses_do_not_mark_dead(self, cluster):
        _, servers = cluster
        client = HttpClient([f"http://127.0.0.1:{servers[0].port}"])
        with pytest.raises(TransportError) as e:
            client.request("GET", "/missing_index/_doc/1")
        assert e.value.status == 404
        # host still usable: next request succeeds without retries
        assert client.request("GET", "/").status == 200

    def test_dead_host_failover(self, cluster):
        _, servers = cluster
        # one dead port + one live: requests transparently fail over
        dead = "http://127.0.0.1:1"  # nothing listens on port 1
        live = f"http://127.0.0.1:{servers[0].port}"
        client = HttpClient([dead, live], timeout=2)
        for _ in range(3):
            assert client.request("GET", "/").host == live
        # the dead host is marked and skipped without costing a retry
        states = {s.host: s for s in client._states}
        assert states[dead].failures >= 1
        assert states[live].failures == 0

    def test_all_dead_raises(self):
        client = HttpClient(["http://127.0.0.1:1"], timeout=1,
                            max_retries=2)
        with pytest.raises(NoLiveHostError):
            client.request("GET", "/")

    def test_ambiguous_write_not_reported_as_cluster_down(self, cluster):
        """A non-idempotent request that dies mid-flight (timeout/reset,
        not connection-refused) must raise AmbiguousWriteError naming the
        one host — NOT NoLiveHostError, which would misrepresent a
        single-host ambiguous write as cluster-wide unavailability and
        hide that the POST may have been applied."""
        import urllib.request
        from unittest import mock

        from elasticsearch_tpu.client import AmbiguousWriteError

        _, servers = cluster
        client = HttpClient([f"http://127.0.0.1:{s.port}" for s in servers])
        reset = ConnectionResetError(104, "Connection reset by peer")
        with mock.patch.object(urllib.request, "urlopen", side_effect=reset):
            with pytest.raises(AmbiguousWriteError) as e:
                client.request("POST", "/idx/_doc/1", body={"a": 1})
        assert e.value.__cause__ is reset
        # idempotent requests with the same failure still exhaust hosts
        # and report cluster-wide unavailability
        with mock.patch.object(urllib.request, "urlopen", side_effect=reset):
            with pytest.raises(NoLiveHostError):
                client.request("GET", "/")

    def test_sniffer_discovers_nodes(self, cluster):
        _, servers = cluster
        client = HttpClient([f"http://127.0.0.1:{servers[0].port}"])
        hosts = client.sniff()
        assert hosts == [f"http://127.0.0.1:{servers[0].port}"]

    def test_typed_helpers_end_to_end(self, cluster):
        _, servers = cluster
        client = HttpClient([f"http://127.0.0.1:{s.port}" for s in servers])
        # both hosts front DIFFERENT single nodes; pin to one for writes
        client.set_hosts([f"http://127.0.0.1:{servers[0].port}"])
        client.put("/lib", body={"mappings": {"properties": {
            "t": {"type": "text"}}}})
        client.index("lib", "1", {"t": "round robin retry sniff"})
        client.bulk([{"index": {"_index": "lib", "_id": "2"}},
                     {"t": "bulk doc"}])
        client.refresh("lib")
        r = client.search("lib", {"query": {"match": {"t": "bulk"}}})
        assert r["hits"]["total"] == 1
        assert client.get_doc("lib", "1")["found"] is True
