"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's in-one-JVM multi-node testing strategy
(test/framework/.../InternalTestCluster.java): instead of real TPU chips,
tests run on the CPU backend with 8 virtual devices so mesh/sharding code
paths execute deterministically (SURVEY.md §4.6.3).

Must run before any jax import — pytest imports conftest first.
"""

import os

# the image pins JAX_PLATFORMS=axon (the real TPU tunnel); tests must run
# on the CPU backend with 8 virtual devices, so override hard.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration test (multi-process)")


@pytest.fixture()
def tmp_data_dir(tmp_path):
    return str(tmp_path / "data")
