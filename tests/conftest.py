"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's in-one-JVM multi-node testing strategy
(test/framework/.../InternalTestCluster.java): instead of real TPU chips,
tests run on the CPU backend with 8 virtual devices so mesh/sharding code
paths execute deterministically (SURVEY.md §4.6.3).

Must run before any jax import — pytest imports conftest first.
"""

import os

# the image pins JAX_PLATFORMS=axon (the real TPU tunnel); tests must run
# on the CPU backend with 8 virtual devices, so override hard. The device
# count must be set via XLA_FLAGS before jax initializes: the
# jax_num_cpu_devices config option only exists on newer jax versions.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # pre-0.5 jax: XLA_FLAGS above already did it
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration test (multi-process)")


@pytest.fixture()
def tmp_data_dir(tmp_path):
    return str(tmp_path / "data")
