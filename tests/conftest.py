"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's in-one-JVM multi-node testing strategy
(test/framework/.../InternalTestCluster.java): instead of real TPU chips,
tests run on the CPU backend with 8 virtual devices so mesh/sharding code
paths execute deterministically (SURVEY.md §4.6.3).

Must run before any jax import — pytest imports conftest first.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_data_dir(tmp_path):
    return str(tmp_path / "data")
