"""Cross-cluster search (RemoteClusterService).

Mirrors the reference's CCS: remote clusters from
``search.remote.<alias>.seeds``, ``alias:index`` expressions, hit
``_index`` prefixed with the alias, ``_clusters`` response section,
``skip_unavailable``, and the ``_remote/info`` API
(core/.../transport/RemoteClusterService.java:60).
"""

import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    NodeNotConnectedException,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def clusters():
    local = Node(Settings({"cluster.name": "local", "node.name": "local-node"}))
    remote = Node(Settings({"cluster.name": "remote", "node.name": "remote-node"}))
    local.create_index("logs", {"mappings": {"properties": {
        "msg": {"type": "text"}, "level": {"type": "keyword"}}}})
    remote.create_index("logs", {"mappings": {"properties": {
        "msg": {"type": "text"}, "level": {"type": "keyword"}}}})
    local.index_doc("logs", "l1", {"msg": "disk error on host", "level": "error"})
    local.index_doc("logs", "l2", {"msg": "all fine", "level": "info"})
    remote.index_doc("logs", "r1", {"msg": "remote disk error", "level": "error"})
    remote.index_doc("logs", "r2", {"msg": "remote warning", "level": "warn"})
    for n in (local, remote):
        for svc in n.indices.values():
            svc.refresh()
    local.remote_clusters.attach("other", remote)
    yield local, remote
    local.close()
    remote.close()


class TestCCS:
    def test_remote_only_search(self, clusters):
        local, _ = clusters
        r = local.search("other:logs", {"query": {"match": {"msg": "disk"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["r1"]
        assert r["hits"]["hits"][0]["_index"] == "other:logs"
        assert r["_clusters"] == {"total": 1, "successful": 1, "skipped": 0}

    def test_mixed_local_and_remote(self, clusters):
        local, _ = clusters
        r = local.search("logs,other:logs",
                         {"query": {"match": {"msg": "disk error"}}})
        indices = {h["_index"] for h in r["hits"]["hits"]}
        assert indices == {"logs", "other:logs"}
        assert r["hits"]["total"] == 2
        assert r["_clusters"]["total"] == 2

    def test_aggs_merge_across_clusters(self, clusters):
        local, _ = clusters
        r = local.search("logs,other:logs", {
            "size": 0,
            "aggs": {"levels": {"terms": {"field": "level"}}}})
        buckets = {b["key"]: b["doc_count"]
                   for b in r["aggregations"]["levels"]["buckets"]}
        assert buckets == {"error": 2, "info": 1, "warn": 1}

    def test_unregistered_alias_is_local_index_name(self, clusters):
        local, _ = clusters
        from elasticsearch_tpu.common.errors import IndexNotFoundException

        with pytest.raises(IndexNotFoundException):
            local.search("nosuch:logs", {"query": {"match_all": {}}})

    def test_unavailable_remote_errors_without_skip(self, clusters):
        local, remote = clusters
        remote.close()
        with pytest.raises(NodeNotConnectedException):
            local.search("other:logs", {"query": {"match_all": {}}})

    def test_skip_unavailable(self, clusters):
        local, remote = clusters
        local.remote_clusters.attach("other", remote, skip_unavailable=True)
        remote.close()
        r = local.search("logs,other:logs", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 2  # local only
        assert r["_clusters"] == {"total": 2, "successful": 1, "skipped": 1}

    def test_remote_info(self, clusters):
        local, _ = clusters
        info = local.remote_clusters.info()
        assert info["other"]["connected"] is True
        assert info["other"]["num_nodes_connected"] == 1
        assert info["other"]["skip_unavailable"] is False

    def test_unknown_alias_rejected(self, clusters):
        local, _ = clusters
        with pytest.raises(IllegalArgumentException):
            local.remote_clusters.get_remote("nope")

    def test_msearch_cross_cluster(self, clusters):
        local, _ = clusters
        r = local.msearch([
            ({"index": "other:logs"}, {"query": {"match_all": {}}}),
            ({"index": "logs"}, {"query": {"match_all": {}}}),
        ])
        assert r["responses"][0]["hits"]["total"] == 2
        assert r["responses"][1]["hits"]["total"] == 2


class TestSettingsDriven:
    def test_seeds_resolve_by_node_name(self):
        a = Node(Settings({"node.name": "node-a"}))
        b = Node(Settings({
            "node.name": "node-b",
            "search.remote.cluster_a.seeds": ["node-a:9300"],
            "search.remote.cluster_a.skip_unavailable": "true",
        }))
        a.create_index("data")
        a.index_doc("data", "1", {"v": 1})
        a.indices["data"].refresh()
        r = b.search("cluster_a:data", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 1
        info = b.remote_clusters.info()
        assert info["cluster_a"]["skip_unavailable"] is True
        a.close()
        b.close()

    def test_dynamic_registration_via_cluster_settings(self):
        a = Node(Settings({"node.name": "dyn-a"}))
        b = Node(Settings({"node.name": "dyn-b"}))
        a.create_index("data")
        a.index_doc("data", "1", {"v": 1})
        a.indices["data"].refresh()
        b.put_cluster_settings({"persistent": {
            "search.remote.peer.seeds": "dyn-a"}})
        r = b.search("peer:data", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 1
        # re-pointing the seeds drops the cached connection
        c = Node(Settings({"node.name": "dyn-c"}))
        c.create_index("data")
        c.index_doc("data", "1", {"v": 2})
        c.index_doc("data", "2", {"v": 3})
        c.indices["data"].refresh()
        b.put_cluster_settings({"persistent": {
            "search.remote.peer.seeds": "dyn-c"}})
        r = b.search("peer:data", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 2
        # empty seeds remove the alias
        b.put_cluster_settings({"persistent": {
            "search.remote.peer.seeds": ""}})
        assert not b.remote_clusters.is_remote_cluster_registered("peer")
        a.close()
        b.close()
        c.close()
