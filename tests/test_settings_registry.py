"""Settings-registry lint: every `search.*` / `index.search.*` key the
codebase reads through a settings lookup must be registered.

PR 3 shipped mesh knobs that were consumed via ``settings.get_*`` before
they were added to the registry in common/settings.py — an unregistered
key silently validates in create-index bodies but rejects in dynamic
updates, and never shows up in the documented surface. This tier-1 lint
walks the source for string-literal settings lookups and fails on any
key the registries don't know, so the drift can't recur.
"""

import os
import re

import elasticsearch_tpu
from elasticsearch_tpu.common.settings import (
    cluster_settings,
    index_scoped_settings,
)

# settings.get/get_str/get_int/... ( "search.foo" / "index.search.foo" )
_LOOKUP_RE = re.compile(
    r"""\.get(?:_str|_int|_bool|_float|_time|_bytes|_list)?\(\s*
        ["'](?P<key>(?:index\.)?search\.[A-Za-z0-9_.]+)["']""",
    re.VERBOSE,
)
# Setting constructors: Setting("key", ...) / Setting.xxx_setting("key", ...)
_SETTING_DEF_RE = re.compile(
    r"""Setting(?:\.[a-z_]+_setting)?\(\s*\n?\s*(?:\#[^\n]*\n\s*)*
        ["'](?P<key>(?:index\.)?search\.[A-Za-z0-9_.]+)["']""",
    re.VERBOSE,
)


def _walk_source():
    root = os.path.dirname(elasticsearch_tpu.__file__)
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            if fname.endswith(".py"):
                path = os.path.join(dirpath, fname)
                with open(path, encoding="utf-8") as f:
                    yield os.path.relpath(path, root), f.read()


def _registered_keys():
    keys = set()
    for registry in (cluster_settings(), index_scoped_settings()):
        keys.update(registry._settings)
    return keys


class TestSettingsRegistryLint:
    def test_every_search_settings_lookup_is_registered(self):
        registered = _registered_keys()
        missing = {}
        for relpath, source in _walk_source():
            for pattern in (_LOOKUP_RE, _SETTING_DEF_RE):
                for m in pattern.finditer(source):
                    key = m.group("key")
                    if key not in registered:
                        missing.setdefault(key, relpath)
        assert not missing, (
            f"settings read via lookup but absent from the registry in "
            f"common/settings.py: {sorted(missing.items())} — register "
            f"them (Scope.INDEX for index.* keys) so validation, dynamic "
            f"updates, and the documented surface stay in sync")

    def test_lint_actually_sees_the_known_lookups(self):
        # the lint is only trustworthy if its regex keeps matching the
        # real lookup idioms; anchor on keys known to be read via
        # settings.get_* today
        seen = set()
        for _relpath, source in _walk_source():
            for m in _LOOKUP_RE.finditer(source):
                seen.add(m.group("key"))
        for key in ("index.search.mesh",
                    "index.search.mesh.max_slots_per_device",
                    "index.search.plane_quarantine.cooldown",
                    "index.search.slowlog.threshold.query.warn"):
            assert key in seen, f"lint regex no longer matches [{key}]"

    def test_new_fault_tolerance_settings_registered(self):
        registered = _registered_keys()
        for key in ("search.default_search_timeout",
                    "search.default_allow_partial_results",
                    "index.search.plane_quarantine.cooldown"):
            assert key in registered, key

    def test_fused_aggs_settings_registered_and_dynamic(self):
        # ISSUE 13 (docs/AGGS.md): the fused-aggregation plane's node
        # default is dynamic (PUT _cluster/settings retunes it live with
        # the explicitness contract) and the per-index override is a
        # registered INDEX-scoped key create_index seeds like
        # search.pallas.*
        registry = cluster_settings()
        assert registry.is_registered("search.aggs.fused")
        assert registry.is_dynamic("search.aggs.fused")
        index_registry = index_scoped_settings()
        assert index_registry.is_registered("index.search.aggs.fused")

    def test_overload_control_settings_registered_and_dynamic(self):
        # ISSUE 12 (docs/OVERLOAD.md): every overload-control knob is
        # registered AND dynamic — operators must be able to resize the
        # queue / retune the brownout ladder mid-incident via
        # PUT _cluster/settings (explicitness-aware overrides), and
        # create_index seeds them per index like search.batch.*
        registry = cluster_settings()
        for key in ("search.queue.size",
                    "search.admission.enabled",
                    "search.admission.max_concurrent",
                    "search.admission.weights",
                    "search.admission.brownout.pruned_threshold",
                    "search.admission.brownout.rescore_threshold",
                    "search.admission.brownout.features_threshold",
                    "search.batch.max_window_ms"):
            assert registry.is_registered(key), key
            assert registry.is_dynamic(key), f"[{key}] must be dynamic"

    def test_rollout_settings_registered(self):
        # ISSUE 14 (docs/RESILIENCE.md "Rollout & drain"): the compile
        # cache is startup-only (XLA's cache must configure before the
        # first compile), warming is a boot decision, and the drain
        # deadline is dynamic — an operator mid-rollout must be able to
        # stretch it via PUT _cluster/settings
        registry = cluster_settings()
        for key in ("search.compile.cache_path",
                    "search.compile.warm_on_start",
                    "search.drain.deadline"):
            assert registry.is_registered(key), key
        assert registry.is_dynamic("search.drain.deadline")

    def test_drain_deadline_seeded_by_create_index(self):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "lint-drain",
                              "search.drain.deadline": "7s"}))
        try:
            node.create_index("drainseed", {"settings": {
                "number_of_shards": 1}})
            adm = node.indices["drainseed"].admission
            assert adm._drain_deadline_s() == 7.0
        finally:
            node.close()

    def test_docs_cross_check_clean(self):
        # ISSUE 15 (docs/STATIC_ANALYSIS.md): this lint's docs half now
        # lives in the contract-lint subsystem — every registered
        # search.* / index.search.* key must own exactly one docs/*.md
        # settings-table row and vice versa; run that pass here so the
        # settings story stays one test file for discoverability
        from elasticsearch_tpu.testing.lint import Allowlist
        from elasticsearch_tpu.testing.lint.core import repo_root
        from elasticsearch_tpu.testing.lint.pass_settings_docs import (
            cross_check,
            doc_rows,
            registered_search_keys,
        )

        allow = Allowlist.load()
        findings = [
            f for f in cross_check(
                registered_search_keys(),
                doc_rows(os.path.join(repo_root(), "docs")),
                "settings-docs")
            if f.id not in allow.entries]
        assert not findings, "\n".join(f.render() for f in findings)

    def test_overload_settings_seeded_by_create_index(self):
        # the admission controller reads its config from the index's
        # Settings map: node-file values must reach indices created
        # later (the search.batch.* seeding contract)
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "lint-seed",
                              "search.queue.size": 41,
                              "search.admission.max_concurrent": 5}))
        try:
            node.create_index("seeded", {"settings": {
                "number_of_shards": 1}})
            adm = node.indices["seeded"].admission
            assert adm._queue_size() == 41
            assert adm._max_concurrent() == 5
        finally:
            node.close()
