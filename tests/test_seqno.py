"""Sequence numbers: global checkpoints, wait_for_active_shards,
refresh=wait_for.

Mirrors GlobalCheckpointTracker (index/seqno/GlobalCheckpointTracker.java:51),
ActiveShardsObserver/wait_for_active_shards, and RefreshListeners
(refresh=wait_for via the periodic index.refresh_interval scheduler).
"""

import time

import pytest

from elasticsearch_tpu.cluster.multinode import ClusterClient, ClusterNode
from elasticsearch_tpu.common.errors import UnavailableShardsException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.seqno import GlobalCheckpointTracker
from elasticsearch_tpu.transport.local import TransportHub


def start_cluster(n_nodes=3):
    hub = TransportHub(strict_serialization=True)
    nodes = [ClusterNode(f"node-{i}", hub) for i in range(n_nodes)]
    nodes[0].bootstrap_cluster()
    for node in nodes[1:]:
        node.join("node-0")
    return hub, nodes


@pytest.fixture()
def cluster():
    hub, nodes = start_cluster(3)
    yield hub, nodes
    for n in nodes:
        n.close()


class TestTracker:
    def test_global_is_min_over_in_sync(self):
        t = GlobalCheckpointTracker("p")
        t.update_local_checkpoint("p", 5)
        assert t.global_checkpoint == 5
        t.initiate_tracking("r1")  # recovering: does not hold back
        assert t.global_checkpoint == 5
        # below the current global checkpoint: membership is deferred
        # (pendingInSync) so the global checkpoint never moves backwards
        t.mark_in_sync("r1", 3)
        assert t.global_checkpoint == 5
        assert "r1" in t.pending_in_sync and "r1" not in t.in_sync
        t.update_local_checkpoint("r1", 5)  # caught up: promoted
        assert "r1" in t.in_sync
        assert t.global_checkpoint == 5
        t.update_local_checkpoint("r1", 4)  # never goes backwards
        assert t.global_checkpoint == 5
        t.update_local_checkpoint("p", 8)
        assert t.global_checkpoint == 5  # r1 holds it back now
        t.update_local_checkpoint("r1", 8)
        assert t.global_checkpoint == 8

    def test_remove_advances(self):
        t = GlobalCheckpointTracker("p")
        t.update_local_checkpoint("p", 9)
        t.mark_in_sync("r1", 2)  # deferred: pending until it reaches 9
        assert t.global_checkpoint == 9
        t.update_local_checkpoint("r1", 3)
        assert "r1" in t.pending_in_sync
        t.remove("r1")
        assert t.global_checkpoint == 9
        assert "r1" not in t.pending_in_sync
        t.remove("p")  # primary is never removed
        assert t.global_checkpoint == 9


class TestClusterCheckpoints:
    def test_checkpoints_flow_primary_to_replica(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        for i in range(5):
            client.index("idx", str(i), {"n": i})
        # find primary + replica shards
        primary = replica = None
        for n in nodes:
            s = n.shards.get(("idx", 0))
            if s is None:
                continue
            if s.primary:
                primary = s
            else:
                replica = s
        assert primary is not None and replica is not None
        stats = primary.seq_no_stats()
        # all 5 ops acked by the replica: global checkpoint is complete
        assert stats["max_seq_no"] == 4
        assert stats["global_checkpoint"] == 4
        # replica learned a recent global checkpoint (piggybacked pre-op,
        # so it may trail by one op)
        assert replica.engine.global_checkpoint >= 3

    def test_replica_failure_advances_global_checkpoint(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        client.index("idx", "a", {"n": 1})
        primary_node = None
        replica_node = None
        for n in nodes:
            s = n.shards.get(("idx", 0))
            if s is not None and s.primary:
                primary_node = n
            elif s is not None:
                replica_node = n
        # cut the replica off; the next write fails the copy and shrinks
        # the in-sync set
        hub.disconnect(primary_node.node_id, replica_node.node_id)
        client.index("idx", "b", {"n": 2})
        stats = primary_node.shards[("idx", 0)].seq_no_stats()
        assert stats["global_checkpoint"] == stats["local_checkpoint"] == 1

    def test_wait_for_active_shards_gate(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        client.index("idx", "a", {"n": 1}, wait_for_active_shards=2)  # ok
        # replica gone: requirement of 2 no longer met
        replica_node = next(
            n for n in nodes
            if n.shards.get(("idx", 0)) is not None
            and not n.shards[("idx", 0)].primary)
        primary_node = next(
            n for n in nodes
            if n.shards.get(("idx", 0)) is not None
            and n.shards[("idx", 0)].primary)
        hub.disconnect(primary_node.node_id, replica_node.node_id)
        client.index("idx", "b", {"n": 2})  # fails the copy
        with pytest.raises(Exception) as ei:
            client.index("idx", "c", {"n": 3}, wait_for_active_shards=2)
        assert "Not enough active copies" in str(ei.value)
        # 1 is still satisfiable
        client.index("idx", "d", {"n": 4}, wait_for_active_shards=1)


class TestTrackerLifecycle:
    def test_departed_replica_pruned_from_in_sync(self, cluster):
        # a replica that leaves the routing table must not pin the
        # global checkpoint forever
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        client.index("idx", "a", {"n": 1})
        primary_node = next(n for n in nodes
                            if n.shards.get(("idx", 0)) is not None
                            and n.shards[("idx", 0)].primary)
        replica_node = next(n for n in nodes
                            if n.shards.get(("idx", 0)) is not None
                            and not n.shards[("idx", 0)].primary)
        tracker = primary_node.shards[("idx", 0)].checkpoints
        assert replica_node.node_id in tracker.in_sync
        # node leaves the cluster: master reroutes, routing drops the copy
        hub.disconnect(replica_node.node_id)
        nodes[0].node_left(replica_node.node_id)
        assert replica_node.node_id not in tracker.in_sync
        stats = primary_node.shards[("idx", 0)].seq_no_stats()
        assert stats["global_checkpoint"] == stats["local_checkpoint"]

    def test_finalize_returns_delta_and_marks_in_sync(self, cluster):
        # ops written between the recovery stream snapshot and finalize
        # must reach the target via the finalize delta, and from in-sync
        # on the copy joins the write fan-out even before STARTED
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[0])
        client.index("idx", "a", {"n": 1})
        primary_node = next(n for n in nodes
                            if n.shards.get(("idx", 0)) is not None)
        # simulate a recovery stream to a fake target
        resp = primary_node._on_start_recovery(
            {"index": "idx", "shard": 0, "target": "fake"}, "fake")
        streamed = {op["id"] for op in resp["ops"]}
        assert streamed == {"a"}
        tracker = primary_node.shards[("idx", 0)].checkpoints
        assert tracker is not None and "fake" not in tracker.in_sync
        # a write lands in the stream->finalize window
        client.index("idx", "b", {"n": 2})
        fin = primary_node._on_recovery_finalize(
            {"index": "idx", "shard": 0,
             "local_checkpoint": resp["max_seq_no"]}, "fake")
        assert {op["id"] for op in fin["ops"]} == {"b"}
        # the copy confirmed a checkpoint below the primary's (op "b"
        # landed after the snapshot), so membership is deferred to
        # pending-in-sync — it already joins the write fan-out, and
        # promotes once its acks catch up to the global checkpoint
        assert "fake" in tracker.pending_in_sync
        tracker.update_local_checkpoint("fake", tracker.global_checkpoint)
        assert "fake" in tracker.in_sync

    def test_bad_wait_for_active_shards_is_400(self, cluster):
        from elasticsearch_tpu.common.errors import IllegalArgumentException

        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[0])
        with pytest.raises(Exception) as ei:
            client.index("idx", "a", {"n": 1},
                         wait_for_active_shards="majority")
        assert "cannot parse wait_for_active_shards" in str(ei.value)


class TestSingleNode:
    def test_seq_no_stats_in_shard_stats(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1,
                                          "index.refresh_interval": "-1"}))
        for i in range(3):
            idx.index_doc(str(i), {"n": i})
        s = idx.shards[0].stats()["seq_no"]
        assert s["max_seq_no"] == 2
        assert s["local_checkpoint"] == 2
        assert s["global_checkpoint"] == 2  # single copy: global == local
        idx.close()

    def test_wait_for_active_shards_single_node(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("idx", {"settings": {
            "index.number_of_replicas": 1}})
        node.index_doc("idx", "1", {"a": 1}, wait_for_active_shards=1)
        with pytest.raises(UnavailableShardsException):
            node.index_doc("idx", "2", {"a": 2}, wait_for_active_shards=2)
        with pytest.raises(UnavailableShardsException):
            node.index_doc("idx", "3", {"a": 3}, wait_for_active_shards="all")
        node.close()


class TestRefreshScheduling:
    def test_periodic_refresh_makes_docs_visible(self):
        idx = IndexService("r", Settings({
            "index.number_of_shards": 1,
            "index.refresh_interval": "100ms"}))
        idx.index_doc("1", {"a": 1})
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if idx.search({"query": {"match_all": {}}})["hits"]["total"] == 1:
                break
            time.sleep(0.05)
        assert idx.search({"query": {"match_all": {}}})["hits"]["total"] == 1
        idx.close()

    def test_refresh_interval_disabled(self):
        idx = IndexService("r2", Settings({
            "index.number_of_shards": 1,
            "index.refresh_interval": "-1"}))
        idx.index_doc("1", {"a": 1})
        time.sleep(0.3)
        assert idx.search({"query": {"match_all": {}}})["hits"]["total"] == 0
        idx.refresh()
        assert idx.search({"query": {"match_all": {}}})["hits"]["total"] == 1
        idx.close()

    def test_refresh_wait_for(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("idx", {"settings": {
            "index.refresh_interval": "150ms"}})
        t0 = time.time()
        node.index_doc("idx", "1", {"a": 1}, refresh="wait_for")
        # the write is visible the moment index_doc returns
        assert node.search("idx", {"query": {"match_all": {}}})["hits"]["total"] == 1
        assert time.time() - t0 < 5.0
        node.close()

    def test_refresh_wait_for_with_disabled_interval_forces(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("idx", {"settings": {
            "index.refresh_interval": "-1"}})
        node.index_doc("idx", "1", {"a": 1}, refresh="wait_for")
        assert node.search("idx", {"query": {"match_all": {}}})["hits"]["total"] == 1
        node.close()


class TestSeqnoIdempotentApply:
    """Out-of-order replica/recovery delivery: the engine's seqno
    staleness guard (reference: InternalEngine
    compareOpToLuceneDocBasedOnSeqNo) must make apply order-independent."""

    def _engine(self):
        # keep the service referenced: its finalizer removes the data dir
        self._idx = IndexService("s", Settings({"index.number_of_shards": 1,
                                                "index.refresh_interval": "-1"}))
        return self._idx.shards[0].engine

    def test_stale_index_after_newer_index_is_noop(self):
        eng = self._engine()
        eng.index("x", {"n": 2}, seqno=5)
        res = eng.index("x", {"n": 1}, seqno=3)
        assert res["result"] == "noop"
        eng.refresh()
        assert eng.get("x").source == {"n": 2}

    def test_stale_index_after_delete_is_not_resurrected(self):
        # delete at seqno 14 arrives before the index at seqno 13
        eng = self._engine()
        eng.delete("x", seqno=14)
        res = eng.index("x", {"n": 1}, seqno=13)
        assert res["result"] == "noop"
        eng.refresh()
        assert not eng.get("x").found

    def test_not_found_delete_tombstone_survives_refresh(self):
        eng = self._engine()
        eng.index("other", {"n": 0}, seqno=1)
        eng.delete("x", seqno=14)
        eng.refresh()  # tombstone with no buffered doc must not corrupt seal
        res = eng.index("x", {"n": 1}, seqno=13)
        assert res["result"] == "noop"
        assert not eng.get("x").found
        assert eng.get("other").source == {"n": 0}

    def test_newer_index_after_stale_delete_applies(self):
        eng = self._engine()
        eng.delete("x", seqno=3)
        res = eng.index("x", {"n": 9}, seqno=7)
        assert res["result"] == "created"
        eng.refresh()
        assert eng.get("x").source == {"n": 9}

    def test_local_checkpoint_advances_on_noop(self):
        eng = self._engine()
        eng.index("x", {"n": 2}, seqno=5)
        eng.index("x", {"n": 1}, seqno=3)
        assert eng.local_checkpoint == 5


class TestRecoveryRerun:
    def test_rerun_recovery_delivers_interim_deletes(self, cluster):
        # A recovery attempt that dies before finalize leaves the target
        # holding streamed state; a delete executed on the primary before
        # the re-run must still reach the target (tombstones are always
        # streamed), or the target resurrects the doc.
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[0])
        client.index("idx", "x", {"n": 1})
        primary_node = next(n for n in nodes
                            if n.shards.get(("idx", 0)) is not None)
        # first recovery stream (target applies it, then "dies" pre-finalize)
        resp1 = primary_node._on_start_recovery(
            {"index": "idx", "shard": 0, "target": "fake"}, "fake")
        assert {op["id"] for op in resp1["ops"]} == {"x"}
        # interim ops on the primary: delete x, index y
        client.delete("idx", "x")
        client.index("idx", "y", {"n": 2})
        # re-run stream must now carry the x tombstone and y
        resp2 = primary_node._on_start_recovery(
            {"index": "idx", "shard": 0, "target": "fake"}, "fake")
        by_id = {(op["op"], op["id"]) for op in resp2["ops"]}
        assert ("delete", "x") in by_id
        assert ("index", "y") in by_id

    def test_finalize_delta_from_translog_includes_deletes(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[0])
        client.index("idx", "a", {"n": 1})
        primary_node = next(n for n in nodes
                            if n.shards.get(("idx", 0)) is not None)
        resp = primary_node._on_start_recovery(
            {"index": "idx", "shard": 0, "target": "fake"}, "fake")
        # ops in the stream->finalize window, including a delete
        client.index("idx", "b", {"n": 2})
        client.delete("idx", "a")
        fin = primary_node._on_recovery_finalize(
            {"index": "idx", "shard": 0,
             "local_checkpoint": resp["max_seq_no"]}, "fake")
        kinds = {(op["op"], op["id"]) for op in fin["ops"]}
        assert ("index", "b") in kinds
        assert ("delete", "a") in kinds


class TestTombstoneGc:
    def test_old_durable_tombstones_pruned_on_refresh(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1,
                                          "index.refresh_interval": "-1",
                                          "index.gc_deletes": "0s"}))
        eng = idx.shards[0].engine
        eng.index("a", {"n": 1})
        eng.delete("a")
        eng.global_checkpoint = eng.local_checkpoint  # globally durable
        eng.refresh()
        assert "a" not in eng.version_map

    def test_recent_or_undurable_tombstones_kept(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1,
                                          "index.refresh_interval": "-1",
                                          "index.gc_deletes": "0s"}))
        eng = idx.shards[0].engine
        eng.index("a", {"n": 1})
        eng.delete("a")
        # not globally durable yet (gcp behind): must be retained for
        # recovery deltas
        eng.global_checkpoint = -1
        eng.refresh()
        assert "a" in eng.version_map
        self._idx = idx


class TestPrimaryTermTieBreak:
    """Equal-seqno ops break by primary term (reference:
    InternalEngine.compareOpToLuceneDocBasedOnSeqNo) and the term
    survives force_merge / store restart / cluster publish."""

    def test_equal_seqno_higher_term_wins(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1}))
        eng = idx.shards[0].engine
        # zombie old primary's op at (seqno 5, term 1)
        eng.index("x", {"v": "old"}, seqno=5, replicated_version=1,
                  primary_term=1)
        # new primary reuses seqno 5 at term 2 — must overwrite
        r = eng.index("x", {"v": "new"}, seqno=5, replicated_version=2,
                      primary_term=2)
        assert r["result"] != "noop"
        assert eng.get("x").source == {"v": "new"}
        # the zombie redelivered after: noop
        r2 = eng.index("x", {"v": "old"}, seqno=5, replicated_version=1,
                       primary_term=1)
        assert r2["result"] == "noop"
        assert eng.get("x").source == {"v": "new"}

    def test_equal_seqno_equal_term_idempotent(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1}))
        eng = idx.shards[0].engine
        eng.index("x", {"v": 1}, seqno=3, replicated_version=1,
                  primary_term=2)
        r = eng.index("x", {"v": 1}, seqno=3, replicated_version=1,
                      primary_term=2)
        assert r["result"] == "noop"

    def test_force_merge_preserves_term(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1}))
        eng = idx.shards[0].engine
        eng.index("x", {"v": 1}, seqno=5, replicated_version=1,
                  primary_term=3)
        eng.refresh()
        eng.force_merge()
        assert eng.version_map["x"].term == 3
        # a zombie equal-seqno lower-term op still noops after the merge
        r = eng.index("x", {"v": 0}, seqno=5, replicated_version=1,
                      primary_term=1)
        assert r["result"] == "noop"

    def test_store_restart_preserves_terms_and_tombstones(self, tmp_path):
        from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
        from elasticsearch_tpu.index.shard import IndexShard
        from elasticsearch_tpu.mapper.mapping import MapperService

        def make_shard():
            return IndexShard(
                "i", 0, MapperService(AnalysisRegistry()),
                data_path=str(tmp_path / "shard0"))

        s1 = make_shard()
        s1.start_fresh()
        s1.engine.index("keep", {"v": 1}, seqno=1, replicated_version=1,
                        primary_term=2)
        s1.engine.index("gone", {"v": 1}, seqno=2, replicated_version=1,
                        primary_term=2)
        s1.engine.delete("gone", seqno=4, replicated_version=2,
                         primary_term=3)
        s1.engine.flush()
        s1.engine.close()
        s2 = make_shard()
        s2.recover_from_store()
        assert s2.engine.version_map["keep"].term == 2
        tomb = s2.engine.version_map["gone"]
        assert tomb.deleted and tomb.term == 3 and tomb.seqno == 4
        # stale index op for the deleted doc cannot resurrect it
        r = s2.engine.index("gone", {"v": 1}, seqno=3,
                            replicated_version=1, primary_term=2)
        assert r["result"] == "noop"
        assert not s2.engine.get("gone").found

    def test_promotion_publishes_bumped_term_to_all_copies(self, cluster):
        hub, nodes = cluster
        # 3 shards over 3 nodes: at least one primary is NOT on the
        # master, so the master survives to run the promotion
        nodes[0].create_index("idx", {"index": {"number_of_shards": 3,
                                                "number_of_replicas": 2}})
        master = next(n for n in nodes if n.is_master)
        sid, primary_node = next(
            (sid, n) for sid in range(3) for n in nodes
            if n is not master and n.shards[("idx", sid)].primary)
        others = [n for n in nodes if n is not primary_node]
        assert all(n.shards[("idx", sid)].primary_term == 1 for n in nodes)
        # kill the primary: a replica is promoted with term 2, and the
        # publish carries the new term to EVERY remaining copy
        hub.disconnect(primary_node.node_id)
        master.node_left(primary_node.node_id)
        assert all(n.shards[("idx", sid)].primary_term == 2 for n in others)
