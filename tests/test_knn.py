"""Dense-vector kNN retrieval plane + hybrid ranking (ISSUE 7).

Covers the vertical slice end to end:

- kernel: ``knn_score_tiles`` (the MXU matmul with fused per-tile
  top-k, q_batch dim, dot/cosine metrics) matches the exact f32 numpy
  oracle over the same bf16-rounded vectors;
- mapper/segment: dims validation (wrong-dims / non-numeric / oversized
  mapping reject with 400), bf16-grid storage, store + translog-only
  recovery round-trips, ``_source`` intact;
- search: knn query clause + top-level knn section, live-mask delete
  exclusion, hybrid RRF/convex fusion, host/mesh parity on the
  8-device CPU mesh, batched kNN bursts through search_batch,
  PlaneFailScheme quarantine-once, dynamic search.knn.* overrides;
- REST: track_total_hits-style total rendering (the PR-6 gte leftover).

Everything runs the kernels in interpret mode on the CPU backend — the
same semantics the compiled TPU path executes (test_pallas_scoring
idiom).
"""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
    QueryShardException,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.ops import pallas_knn as pkn
from elasticsearch_tpu.testing.disruption import (
    PlaneFailScheme,
    clear_search_disruptions,
)

DIMS = 12

MAPPING = {
    "properties": {
        "emb": {"type": "dense_vector", "dims": DIMS,
                "similarity": "cosine"},
        "body": {"type": "text", "analyzer": "whitespace"},
        "n": {"type": "integer"},
    }
}


@pytest.fixture(autouse=True)
def _interpret_kernel(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
    yield
    clear_search_disruptions()


def build_index(n_shards=1, n_docs=60, seed=0, mapping=None,
                **extra_settings):
    idx = IndexService(
        f"knn-{n_shards}s-{seed}", Settings({
            "index.number_of_shards": n_shards,
            "index.refresh_interval": -1, **extra_settings}),
        mapping=mapping or MAPPING)
    rng = np.random.RandomState(seed)
    vecs = rng.randn(n_docs, DIMS).astype(np.float32)
    for d in range(n_docs):
        idx.index_doc(str(d), {"emb": vecs[d].tolist(),
                               "body": f"term{d % 7} term{d % 3}",
                               "n": d})
    idx.refresh()
    return idx, vecs


def oracle_ids(vecs, q, k, metric="cosine", live=None):
    vb = pkn.bf16_round(vecs)
    mask = np.ones(len(vb), bool) if live is None else live
    _s, idx = pkn.reference_knn_topk(vb, mask, q, k, metric)
    return [str(i) for i in idx]


# ----------------------------------------------------------------------
# Kernel
# ----------------------------------------------------------------------


class TestKnnKernel:
    @pytest.mark.parametrize("metric", ["cosine", "dot_product"])
    def test_kernel_matches_oracle(self, metric):
        import jax.numpy as jnp

        rng = np.random.RandomState(2)
        nd, d = 3000, 24
        vecs = pkn.bf16_round(rng.randn(nd, d))
        d_pad = pkn.pad_dims(d)
        geom = pkn.knn_geometry(4096, d_pad, 8)
        assert geom.n_tiles > 1  # exercise the grid + doc-base offsets
        emb = np.zeros((geom.nd_pad, d_pad), np.float32)
        emb[:nd, :d] = vecs
        mask = np.zeros((geom.nd_pad, 1), np.float32)
        mask[:nd] = 1.0
        mask[7] = 0.0  # a deleted doc must never surface
        scale = np.zeros((geom.nd_pad, 1), np.float32)
        scale[:nd] = (pkn.vector_scale_column(vecs, metric)[:nd]
                      if metric == "cosine" else 1.0)
        qs = rng.randn(3, d).astype(np.float32)
        qmat = np.stack([pkn.normalize_query(q, metric, d_pad)
                         for q in qs]
                        + [np.zeros(d_pad, np.float32)])  # q_pad row
        ts, td = pkn.knn_score_tiles(
            jnp.asarray(emb, jnp.bfloat16), jnp.asarray(scale),
            jnp.asarray(mask), jnp.asarray(qmat),
            sub=geom.tile_sub, k=10, q_batch=4, interpret=True)
        top_s, top_d = (np.asarray(o)
                        for o in pkn.merge_knn_topk(ts, td, 10))
        live = np.ones(nd, bool)
        live[7] = False
        for q in range(3):
            ref_s, ref_i = pkn.reference_knn_topk(vecs, live, qs[q], 10,
                                                  metric)
            assert top_d[q].tolist() == ref_i.tolist()
            np.testing.assert_allclose(top_s[q], ref_s, rtol=1e-6)
            assert 7 not in top_d[q]

    def test_tile_sub_shrinks_for_vmem(self):
        # high-dimensional fields shrink the tile so the f32 block fits
        assert pkn.knn_tile_sub(1 << 20, pkn.pad_dims(1024)) < \
            pkn.DEFAULT_KNN_SUB
        assert pkn.knn_tile_sub(1 << 20, pkn.pad_dims(128)) == \
            pkn.DEFAULT_KNN_SUB


# ----------------------------------------------------------------------
# Mapper validation + recovery
# ----------------------------------------------------------------------


class TestMapperValidation:
    def test_missing_dims_rejected(self):
        with pytest.raises(MapperParsingException):
            IndexService("bad-dims", Settings({
                "index.number_of_shards": 1}), mapping={
                "properties": {"v": {"type": "dense_vector"}}}).close()

    def test_dims_above_max_rejected(self):
        with pytest.raises(IllegalArgumentException):
            IndexService("big-dims", Settings({
                "index.number_of_shards": 1,
                "index.mapping.dense_vector.max_dims": 8}), mapping={
                "properties": {
                    "v": {"type": "dense_vector", "dims": 16}}}).close()

    def test_unknown_similarity_rejected(self):
        with pytest.raises(MapperParsingException):
            IndexService("bad-sim", Settings({
                "index.number_of_shards": 1}), mapping={
                "properties": {"v": {"type": "dense_vector", "dims": 4,
                                     "similarity": "l2"}}}).close()

    def test_wrong_dims_doc_rejected_400(self):
        idx, _ = build_index()
        with pytest.raises(MapperParsingException) as ei:
            idx.index_doc("bad", {"emb": [1.0, 2.0]})
        assert ei.value.status_code == 400
        idx.close()

    def test_non_numeric_vector_rejected_400(self):
        idx, _ = build_index()
        with pytest.raises(MapperParsingException) as ei:
            idx.index_doc("bad", {"emb": ["x"] * DIMS})
        assert ei.value.status_code == 400
        with pytest.raises(MapperParsingException):
            idx.index_doc("bad2", {"emb": "not-a-vector"})
        idx.close()

    def test_dense_vector_multi_field_rejected(self):
        with pytest.raises(MapperParsingException):
            IndexService("mf", Settings({
                "index.number_of_shards": 1}), mapping={
                "properties": {"t": {"type": "text", "fields": {
                    "v": {"type": "dense_vector", "dims": 4}}}}}).close()

    def test_knn_on_non_vector_field_400(self):
        idx, _ = build_index()
        with pytest.raises(QueryShardException):
            idx.search({"query": {"knn": {
                "field": "body", "query_vector": [0.0] * DIMS}}})
        with pytest.raises(IllegalArgumentException):
            idx.search({"query": {"knn": {
                "field": "emb", "query_vector": [0.0] * (DIMS + 1)}}})
        idx.close()


class TestRecovery:
    def test_translog_only_recovery_round_trip(self, tmp_data_dir):
        settings = Settings({"index.number_of_shards": 1,
                             "index.refresh_interval": -1})
        idx = IndexService("vrec", settings, mapping=MAPPING,
                           data_path=tmp_data_dir)
        rng = np.random.RandomState(4)
        vecs = rng.randn(8, DIMS).astype(np.float32)
        idx.index_doc("0", {"emb": vecs[0].tolist()})
        idx.flush()  # one committed segment
        for d in range(1, 8):
            idx.index_doc(str(d), {"emb": vecs[d].tolist()})
        idx.close()  # docs 1..7 exist ONLY in the translog

        idx2 = IndexService("vrec", settings, mapping=MAPPING,
                            data_path=tmp_data_dir)
        q = rng.randn(DIMS).astype(np.float32)
        r = idx2.search({"query": {"knn": {
            "field": "emb", "query_vector": q.tolist()}}, "size": 8})
        assert r["hits"]["total"] == 8
        assert [h["_id"] for h in r["hits"]["hits"]] == \
            oracle_ids(vecs, q, 8)
        # _source round-trips bit-exactly through the translog replay
        got = idx2.get_doc("5")
        assert got.found and np.allclose(got.source["emb"], vecs[5])
        idx2.close()

    def test_store_persists_bf16_grid(self, tmp_data_dir):
        settings = Settings({"index.number_of_shards": 1,
                             "index.refresh_interval": -1})
        idx = IndexService("vstore", settings, mapping=MAPPING,
                           data_path=tmp_data_dir)
        vec = (np.random.RandomState(5).randn(DIMS) * 3).tolist()
        idx.index_doc("a", {"emb": vec})
        idx.flush()
        idx.close()
        idx2 = IndexService("vstore", settings, mapping=MAPPING,
                            data_path=tmp_data_dir)
        seg = idx2.shards[0].engine.segments[0]
        col = seg.vector_columns["emb"]
        assert col.dims == DIMS and col.count == 1
        # persisted values sit exactly on the bf16 grid
        np.testing.assert_array_equal(col.vectors,
                                      pkn.bf16_round(col.vectors))
        idx2.close()


# ----------------------------------------------------------------------
# Search semantics (host path)
# ----------------------------------------------------------------------


class TestKnnSearch:
    def test_knn_clause_matches_oracle(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        r = idx.search({"query": {"knn": {
            "field": "emb", "query_vector": q.tolist(), "k": 5}},
            "size": 5})
        assert [h["_id"] for h in r["hits"]["hits"]] == \
            oracle_ids(vecs, q, 5)
        assert r["hits"]["total"] == 60  # live docs carrying the field
        idx.close()

    def test_top_level_knn_section(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        r = idx.search({"knn": {"field": "emb",
                                "query_vector": q.tolist(), "k": 4}})
        assert len(r["hits"]["hits"]) == 4
        assert [h["_id"] for h in r["hits"]["hits"]] == \
            oracle_ids(vecs, q, 4)
        idx.close()

    def test_deleted_docs_excluded_via_live_mask(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        top = oracle_ids(vecs, q, 3)
        idx.delete_doc(top[0])
        idx.refresh()
        r = idx.search({"query": {"knn": {
            "field": "emb", "query_vector": q.tolist()}}, "size": 5})
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert top[0] not in ids
        live = np.ones(len(vecs), bool)
        live[int(top[0])] = False
        assert ids == oracle_ids(vecs, q, 5, live=live)
        assert r["hits"]["total"] == 59
        idx.close()

    def test_knn_inside_bool_filter(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        r = idx.search({"query": {"bool": {
            "must": [{"knn": {"field": "emb",
                              "query_vector": q.tolist()}}],
            "filter": [{"range": {"n": {"lt": 10}}}]}}, "size": 5})
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert ids and all(int(i) < 10 for i in ids)
        live = np.zeros(len(vecs), bool)
        live[:10] = True
        assert ids == oracle_ids(vecs, q, 5, live=live)
        idx.close()

    def test_hybrid_rrf_and_convex(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        hb = {"query": {"match": {"body": "term1"}},
              "knn": {"field": "emb", "query_vector": q.tolist(), "k": 10},
              "rank": {"rrf": {"rank_constant": 60, "window_size": 20}},
              "size": 10}
        r = idx.search(dict(hb))
        assert r["_total_relation"] == "gte"
        assert r["_hybrid"]["fusion"] == "rrf"
        # oracle-side RRF over the two exact rankings
        lex = idx.search({"query": {"match": {"body": "term1"}},
                          "size": 20})
        knn_ids = oracle_ids(vecs, q, 20)
        scores = {}
        for rank, h in enumerate(lex["hits"]["hits"]):
            scores[h["_id"]] = scores.get(h["_id"], 0.0) \
                + 1.0 / (60 + rank + 1)
        for rank, did in enumerate(knn_ids):
            scores[did] = scores.get(did, 0.0) + 1.0 / (60 + rank + 1)
        want = [d for d, _ in sorted(scores.items(),
                                     key=lambda kv: (-kv[1], kv[0]))][:10]
        assert [h["_id"] for h in r["hits"]["hits"]] == want
        # convex fusion (no rank): additive scores
        rc = idx.search({"query": {"match": {"body": "term1"}},
                         "knn": {"field": "emb",
                                 "query_vector": q.tolist(), "k": 10},
                         "size": 5})
        assert rc["_hybrid"]["fusion"] == "convex"
        assert len(rc["hits"]["hits"]) == 5
        idx.close()

    def test_knn_filter_restricts_candidates(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        r = idx.search({"query": {"knn": {
            "field": "emb", "query_vector": q.tolist(),
            "filter": {"range": {"n": {"lt": 10}}}}}, "size": 5})
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert ids and all(int(i) < 10 for i in ids)
        live = np.zeros(len(vecs), bool)
        live[:10] = True
        assert ids == oracle_ids(vecs, q, 5, live=live)
        assert r["hits"]["total"] == 10
        # unknown knn parameters strict-parse to 400
        from elasticsearch_tpu.common.errors import ParsingException

        with pytest.raises(ParsingException):
            idx.search({"query": {"knn": {
                "field": "emb", "query_vector": q.tolist(),
                "filtr": {"match_all": {}}}}})
        idx.close()

    def test_rrf_rank_constant_validated(self):
        idx, _ = build_index()
        q = [0.0] * DIMS
        with pytest.raises(IllegalArgumentException):
            idx.search({"query": {"match_all": {}},
                        "knn": {"field": "emb", "query_vector": q},
                        "rank": {"rrf": {"rank_constant": 0}}})
        # misspelled rrf knobs must 400 (strict parse), and the
        # reference's rank_window_size name is accepted as an alias
        with pytest.raises(IllegalArgumentException):
            idx.search({"query": {"match_all": {}},
                        "knn": {"field": "emb", "query_vector": q},
                        "rank": {"rrf": {"rankconstant": 10}}})
        r = idx.search({"query": {"match_all": {}},
                        "knn": {"field": "emb", "query_vector": q},
                        "rank": {"rrf": {"rank_window_size": 15}},
                        "size": 5})
        assert len(r["hits"]["hits"]) == 5
        idx.close()

    def test_nan_query_vector_rejected_everywhere(self):
        idx, _ = build_index()
        bad = [float("nan")] + [0.0] * (DIMS - 1)
        with pytest.raises(IllegalArgumentException):
            idx.search({"query": {"knn": {"field": "emb",
                                          "query_vector": bad}}})
        # the mesh eligibility gate must not accept it either (the
        # serial path owns the 400, never a kernel OOB doc id)
        from elasticsearch_tpu.search.batching import knn_batch_spec

        body = {"knn": {"field": "emb", "query_vector": bad}}
        if idx._mesh_search is not None:
            assert idx._mesh_search.query_knn_batch(
                [body["knn"]], [10]) is None
        idx.close()

    def test_ineligible_knn_body_runs_solo_not_in_lexical_batch(self):
        from elasticsearch_tpu.search.batching import batchable_body

        # filtered / boosted / malformed knn bodies must NOT join a
        # micro-batch (they would demote every peer off the mesh rung)
        assert not batchable_body({"query": {"knn": {
            "field": "emb", "query_vector": [0.0] * DIMS,
            "filter": {"match_all": {}}}}})
        assert not batchable_body({"knn": {
            "field": "emb", "query_vector": [0.0] * DIMS, "boost": 2.0}})
        assert not batchable_body({"query": {"knn": {
            "field": "emb", "query_vector": [0.0] * DIMS,
            "filtr": {}}}})
        assert batchable_body({"knn": {
            "field": "emb", "query_vector": [0.0] * DIMS, "k": 5}})

    def test_convex_fusion_truncates_knn_side_to_k(self):
        idx, vecs = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        knn_ids = oracle_ids(vecs, q, 10)
        # k=2: only the 2 nearest neighbors may receive a vector score;
        # with a match_none lexical side the fused list IS those 2 docs
        r = idx.search({"query": {"match_none": {}},
                        "knn": {"field": "emb",
                                "query_vector": q.tolist(), "k": 2},
                        "size": 10})
        assert [h["_id"] for h in r["hits"]["hits"]] == knn_ids[:2]
        idx.close()

    def test_nested_include_in_parent_vector_searchable(self):
        idx = IndexService("nestv", Settings({
            "index.number_of_shards": 1,
            "index.refresh_interval": -1}), mapping={
            "properties": {"obj": {
                "type": "nested", "include_in_parent": True,
                "properties": {
                    "emb": {"type": "dense_vector", "dims": 4}}}}})
        idx.index_doc("a", {"obj": [{"emb": [1.0, 0.0, 0.0, 0.0]}]})
        idx.refresh()
        r = idx.search({"query": {"knn": {
            "field": "obj.emb", "query_vector": [1.0, 0.0, 0.0, 0.0]}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["a"]
        # two nested objects flattening the same vector path must 400
        with pytest.raises(MapperParsingException):
            idx.index_doc("b", {"obj": [{"emb": [1, 0, 0, 0]},
                                        {"emb": [0, 1, 0, 0]}]})
        idx.close()

    def test_hybrid_carries_lexical_aggregations_and_source_filtering(self):
        idx, _ = build_index()
        q = np.random.RandomState(9).randn(DIMS).astype(np.float32)
        r = idx.search({
            "query": {"match": {"body": "term1"}},
            "knn": {"field": "emb", "query_vector": q.tolist(), "k": 10},
            "aggs": {"byn": {"avg": {"field": "n"}}},
            "_source": False, "size": 5})
        assert "aggregations" in r and "byn" in r["aggregations"]
        # the knn side inherits _source: false — no fused hit leaks it
        assert all("_source" not in h for h in r["hits"]["hits"])
        # shard header stays internally consistent
        sh = r["_shards"]
        assert sh["successful"] + sh["failed"] == sh["total"]
        idx.close()

    def test_rank_without_knn_rejected(self):
        idx, _ = build_index()
        with pytest.raises(IllegalArgumentException):
            idx.search({"knn": {"field": "emb",
                                "query_vector": [0.0] * DIMS},
                        "rank": {"rrf": {}}})
        idx.close()


# ----------------------------------------------------------------------
# Mesh plane (8-device CPU mesh, interpret kernels)
# ----------------------------------------------------------------------


def build_pair(n_shards=3, n_docs=90, seed=1, **extra):
    mesh, vecs = build_index(n_shards=n_shards, n_docs=n_docs, seed=seed,
                             **extra)
    host, _ = build_index(n_shards=n_shards, n_docs=n_docs, seed=seed,
                          **{"index.search.mesh": False, **extra})
    return mesh, host, vecs


class TestKnnMeshPlane:
    def test_mesh_host_parity_byte_identical(self):
        mesh, host, vecs = build_pair()
        q = np.random.RandomState(3).randn(DIMS).astype(np.float32)
        body = {"query": {"knn": {"field": "emb",
                                  "query_vector": q.tolist(), "k": 6}},
                "size": 6}
        got = mesh.search(dict(body))
        want = host.search(dict(body))
        assert got["_plane"] == "mesh_pallas"
        assert want["_plane"] == "host"
        assert got["hits"]["total"] == want["hits"]["total"]
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])
        for g, w in zip(got["hits"]["hits"], want["hits"]["hits"]):
            assert g["_score"] == w["_score"]
        assert mesh._mesh_search.knn_query_total == 1
        mesh.close()
        host.close()

    def test_batched_knn_burst_one_launch(self):
        mesh, host, _ = build_pair()
        rng = np.random.RandomState(6)
        burst = [{"knn": {"field": "emb",
                          "query_vector": rng.randn(DIMS).tolist(),
                          "k": 5}, "size": 5} for _ in range(4)]
        # a top-level-knn member with NO size must default to k hits —
        # the same count the serial path returns (batching must never
        # change a member's observable result)
        burst.append({"knn": {"field": "emb",
                              "query_vector": rng.randn(DIMS).tolist(),
                              "k": 3}})
        out = mesh.search_batch([dict(b) for b in burst])
        assert mesh._mesh_search.batched_launch_total == 1
        assert mesh._mesh_search.knn_query_total == 5
        for b, got in zip(burst, out):
            assert isinstance(got, dict), got
            assert got["_plane"] == "mesh_pallas"
            want = host.search(dict(b))
            assert ([h["_id"] for h in got["hits"]["hits"]]
                    == [h["_id"] for h in want["hits"]["hits"]])
            assert got["hits"]["total"] == want["hits"]["total"]
        assert len(out[-1]["hits"]["hits"]) == 3
        mesh.close()
        host.close()

    def test_plane_fault_quarantines_once(self):
        mesh, host, _ = build_pair()
        q = np.random.RandomState(3).randn(DIMS).astype(np.float32)
        body = {"query": {"knn": {"field": "emb",
                                  "query_vector": q.tolist()}}, "size": 5}
        scheme = PlaneFailScheme(planes=["mesh_pallas"]).install()
        try:
            got = mesh.search(dict(body))
            assert got["_plane"] == "host"
            want = host.search(dict(body))
            assert ([h["_id"] for h in got["hits"]["hits"]]
                    == [h["_id"] for h in want["hits"]["hits"]])
            ph = mesh._mesh_search.plane_health
            assert ph.failures_total["mesh_pallas"] == 1
            assert "mesh_pallas" in ph.quarantined()
        finally:
            clear_search_disruptions()
        mesh.close()
        host.close()

    def test_knn_disabled_setting_falls_to_host(self):
        mesh, host, _ = build_pair(**{"search.knn.enabled": False})
        q = np.random.RandomState(3).randn(DIMS).astype(np.float32)
        body = {"query": {"knn": {"field": "emb",
                                  "query_vector": q.tolist()}}, "size": 5}
        got = mesh.search(dict(body))
        assert got["_plane"] == "host"
        want = host.search(dict(body))
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])
        mesh.close()
        host.close()

    def test_deletes_invalidate_mesh_staging(self):
        mesh, host, vecs = build_pair()
        q = np.random.RandomState(3).randn(DIMS).astype(np.float32)
        body = {"query": {"knn": {"field": "emb",
                                  "query_vector": q.tolist(), "k": 5}},
                "size": 5}
        first = mesh.search(dict(body))
        victim = first["hits"]["hits"][0]["_id"]
        for idx in (mesh, host):
            idx.delete_doc(victim)
            idx.refresh()
        got = mesh.search(dict(body))
        want = host.search(dict(body))
        assert got["_plane"] == "mesh_pallas"
        assert victim not in [h["_id"] for h in got["hits"]["hits"]]
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])
        assert got["hits"]["total"] == want["hits"]["total"]
        mesh.close()
        host.close()


# ----------------------------------------------------------------------
# REST total rendering (the PR-6 gte leftover)
# ----------------------------------------------------------------------


class TestTotalRendering:
    def test_track_total_hits_renders_object(self):
        from elasticsearch_tpu.rest.handlers import _render_total_hits

        resp = {"hits": {"total": 42, "hits": []}}
        _render_total_hits(resp, {"track_total_hits": True})
        assert resp["hits"]["total"] == {"value": 42, "relation": "eq"}

    def test_pruned_marker_renders_gte(self):
        from elasticsearch_tpu.rest.handlers import _render_total_hits

        resp = {"hits": {"total": 42, "hits": []},
                "_pruned": {"total_relation": "gte", "tiles_scored": 3}}
        _render_total_hits(resp, {})
        assert resp["hits"]["total"] == {"value": 42, "relation": "gte"}

    def test_hybrid_marker_renders_gte(self):
        from elasticsearch_tpu.rest.handlers import _render_total_hits

        resp = {"hits": {"total": 7, "hits": []},
                "_total_relation": "gte"}
        _render_total_hits(resp, {})
        assert resp["hits"]["total"] == {"value": 7, "relation": "gte"}

    def test_integer_threshold_form_opts_in(self):
        from elasticsearch_tpu.rest.handlers import _render_total_hits

        resp = {"hits": {"total": 42, "hits": []}}
        _render_total_hits(resp, {"track_total_hits": 10000})
        assert resp["hits"]["total"] == {"value": 42, "relation": "eq"}

    def test_default_stays_bare_int(self):
        from elasticsearch_tpu.rest.handlers import _render_total_hits

        resp = {"hits": {"total": 42, "hits": []}}
        _render_total_hits(resp, {})
        assert resp["hits"]["total"] == 42

    def test_rest_search_knn_end_to_end(self):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "knn-rest"}))
        try:
            c = Client(node)
            status, _ = c.perform("PUT", "/vidx", body={
                "settings": {"index": {"number_of_shards": 1}},
                "mappings": {"_doc": {"properties": {
                    "emb": {"type": "dense_vector", "dims": 4}}}}})
            assert status == 200
            rng = np.random.RandomState(0)
            for d in range(6):
                status, _ = c.perform(
                    "PUT", f"/vidx/_doc/{d}",
                    body={"emb": rng.randn(4).tolist()})
                assert status in (200, 201)
            c.perform("POST", "/vidx/_refresh")
            status, r = c.perform("POST", "/vidx/_search", body={
                "knn": {"field": "emb",
                        "query_vector": rng.randn(4).tolist(), "k": 3}})
            assert status == 200, r
            assert len(r["hits"]["hits"]) == 3
            assert r["hits"]["total"] == 6  # bare int without opt-in
            status, r2 = c.perform(
                "POST", "/vidx/_search",
                params={"track_total_hits": "true"},
                body={"query": {"match_all": {}}})
            assert status == 200
            assert r2["hits"]["total"] == {"value": 6, "relation": "eq"}
        finally:
            node.close()
