"""Durable cluster metadata across full restarts (GatewayMetaState).

The reference persists global MetaData — index templates, persistent
settings, stored scripts, ingest pipelines, snapshot repositories — via
atomic _state files (gateway/GatewayMetaState.java:61,117,
gateway/MetaDataStateFormat) and restores it before index recovery on
boot. Round-4 VERDICT missing item 3: only per-index metadata survived a
restart here; everything global evaporated."""

import os

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def data_dir(tmp_path):
    return str(tmp_path / "node-data")


def seed_node(data_dir):
    node = Node(Settings.EMPTY, data_path=data_dir)
    node.put_template("logs-template", {
        "index_patterns": ["logs-*"],
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"msg": {"type": "text"}}},
        "order": 3,
    })
    node.put_cluster_settings({
        "persistent": {"cluster": {"routing": {"allocation": {
            "enable": "primaries"}}}},
        "transient": {"search": {"default_search_timeout": "9s"}},
    })
    node.put_stored_script("my-script", {
        "script": {"lang": "painless", "source": "ctx._source.n += 1"}})
    node.ingest.put_pipeline("my-pipe", {
        "description": "adds a field",
        "processors": [{"set": {"field": "added", "value": True}}]})
    node.snapshots.put_repository("my-repo", {
        "type": "fs", "settings": {"location": "backups"}})
    # an index too: global metadata must recover BEFORE index recovery
    node.create_index("docs", {"mappings": {"properties": {
        "msg": {"type": "text"}}}})
    node.index_doc("docs", "1", {"msg": "hello"})
    node.indices["docs"].flush()
    return node


class TestGlobalMetaRestart:
    def test_all_five_survive_full_restart(self, data_dir):
        node = seed_node(data_dir)
        node.close()

        node2 = Node(Settings.EMPTY, data_path=data_dir)
        try:
            state = node2.cluster_service.state
            # 1. template
            assert "logs-template" in state.templates
            assert state.templates["logs-template"]["order"] == 3
            # ...and it still APPLIES to new indices
            node2.create_index("logs-2026")
            n_shards = node2.indices["logs-2026"].settings.get_int(
                "index.number_of_shards", 0)
            assert n_shards == 2
            # 2. persistent settings survive; transient are dropped
            # (reference semantics on full restart)
            assert state.persistent_settings.as_dict().get(
                "cluster.routing.allocation.enable") == "primaries"
            assert state.transient_settings.as_dict() == {}
            # 3. stored script — retrievable with its source intact
            assert "my-script" in state.stored_scripts
            got_script = node2.get_stored_script("my-script")
            assert "ctx._source.n += 1" in str(got_script)
            # 4. ingest pipeline — and it still runs
            assert "my-pipe" in state.ingest_pipelines
            node2.index_doc("docs", "3", {"msg": "y"}, pipeline="my-pipe")
            assert node2.get_doc("docs", "3")["_source"]["added"] is True
            # 5. snapshot repository — registered AND usable
            assert "my-repo" in state.repositories
            got = node2.snapshots.get_repository("my-repo")
            assert got["my-repo"]["type"] == "fs"
            node2.indices["docs"].refresh()
            r = node2.snapshots.create_snapshot(
                "my-repo", "snap1", {"indices": "docs"})
            assert r["snapshot"]["state"] == "SUCCESS"
            # the index itself also recovered
            assert node2.get_doc("docs", "1")["_source"]["msg"] == "hello"
        finally:
            node2.close()

    def test_state_file_is_atomic_and_updated(self, data_dir):
        import json

        node = seed_node(data_dir)
        state_file = os.path.join(data_dir, "_state", "global-meta.json")
        assert os.path.exists(state_file)
        assert not os.path.exists(state_file + ".tmp")  # renamed, not left
        with open(state_file, encoding="utf-8") as f:
            payload = json.load(f)
        assert "logs-template" in payload["templates"]
        # deleting a template updates the durable copy immediately
        node.delete_template("logs-template")
        with open(state_file, encoding="utf-8") as f:
            payload = json.load(f)
        assert "logs-template" not in payload["templates"]
        node.close()

    def test_ephemeral_node_writes_nothing(self):
        node = Node(Settings.EMPTY)  # no data_path: in-memory node
        node.put_template("t", {"index_patterns": ["x-*"]})
        assert not node.persistent_path
        node.close()


class TestParentRegistryRestart:
    """Legacy _parent values persist with the document (translog/store
    record alongside routing) and the IndexService registry is rebuilt
    during recovery — round-5 advisor finding: the registry was
    memory-only, so stored_fields [_parent] silently vanished after a
    restart while the documents survived."""

    def test_parents_survive_flush_restart(self, data_dir):
        node = Node(Settings.EMPTY, data_path=data_dir)
        node.create_index("join", {"settings": {"index": {
            "number_of_shards": 2}}})
        node.index_doc("join", "c1", {"k": "v1"}, routing="p1", parent="p1")
        node.index_doc("join", "c2", {"k": "v2"}, routing="p2", parent="p2")
        node.index_doc("join", "plain", {"k": "v3"})
        node.indices["join"].flush()
        # one more child AFTER the flush: must come back via translog
        node.index_doc("join", "c3", {"k": "v4"}, routing="p3", parent="p3")
        node.close()

        node2 = Node(Settings.EMPTY, data_path=data_dir)
        try:
            svc = node2.indices["join"]
            assert svc.parents == {"c1": "p1", "c2": "p2", "c3": "p3"}
        finally:
            node2.close()

    def test_parent_surfaces_in_stored_fields_after_restart(self, data_dir):
        from elasticsearch_tpu.client import Client

        node = Node(Settings.EMPTY, data_path=data_dir)
        Client(node).perform(
            "PUT", "/pidx/_doc/child", params={"parent": "par-7"},
            body={"msg": "x"})
        node.indices["pidx"].flush()
        node.close()

        node2 = Node(Settings.EMPTY, data_path=data_dir)
        try:
            status, payload = Client(node2).perform(
                "GET", "/pidx/_doc/child",
                params={"stored_fields": "_parent", "routing": "par-7"})
            assert status == 200, payload
            assert payload.get("_parent") == "par-7", payload
        finally:
            node2.close()

    def test_deleted_child_drops_from_rebuilt_registry(self, data_dir):
        node = Node(Settings.EMPTY, data_path=data_dir)
        node.create_index("join2", {})
        node.index_doc("join2", "c1", {"k": "v"}, routing="p1", parent="p1")
        node.index_doc("join2", "c2", {"k": "v"}, routing="p1", parent="p1")
        node.indices["join2"].refresh()
        node.delete_doc("join2", "c2", routing="p1")
        node.indices["join2"].refresh()
        node.indices["join2"].flush()
        node.close()

        node2 = Node(Settings.EMPTY, data_path=data_dir)
        try:
            assert node2.indices["join2"].parents == {"c1": "p1"}
        finally:
            node2.close()
