"""Phase-attributed query tracing (ISSUE 8, docs/OBSERVABILITY.md).

Covers the four contracts:
- plane-truthful profile: a "profile": true query is served by the same
  rung as its unprofiled twin (mesh_pallas / batched / pruned included)
  with byte-identical hits, and reports that plane's phase spans +
  annotations;
- stats-counter correctness under concurrency: a burst of mixed
  batched/serial/knn traffic leaves every counter summing consistently
  (no double counts, no lost increments);
- tracer overhead guard: span count capped, per-phase accumulation
  bounded by the taxonomy, the hot path fast, and the
  search.telemetry.enabled kill switch honored (registered + dynamic);
- MicroBatcher window-wait/batch-shape annotations.

Kernel paths run in interpret mode on the CPU backend (the
tests/test_pallas_scoring idiom).
"""

import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search.batching import MicroBatcher
from elasticsearch_tpu.search.telemetry import (
    NULL_TRACER,
    PHASES,
    QueryTracer,
    SearchTelemetry,
    merge_phase_stats,
)
from elasticsearch_tpu.testing.disruption import clear_search_disruptions

MAPPING = {
    "properties": {
        "body": {"type": "text", "analyzer": "whitespace"},
        "n": {"type": "integer"},
        "emb": {"type": "dense_vector", "dims": 8,
                "similarity": "cosine"},
    }
}


@pytest.fixture(autouse=True)
def _interpret_kernel(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
    yield
    clear_search_disruptions()


def build_index(name="obs", n_shards=2, n_docs=80, seed=0,
                **extra_settings):
    idx = IndexService(name, Settings({
        "index.number_of_shards": n_shards,
        "index.refresh_interval": -1, **extra_settings}), mapping=MAPPING)
    rng = np.random.RandomState(seed)
    vocab = [f"t{i}" for i in range(12)]
    for d in range(n_docs):
        toks = [vocab[rng.randint(len(vocab))]
                for _ in range(rng.randint(3, 9))]
        idx.index_doc(str(d), {"body": " ".join(toks), "n": d,
                               "emb": rng.randn(8).tolist()})
    idx.refresh()
    return idx


def ids(r):
    return [h["_id"] for h in r["hits"]["hits"]]


def scores(r):
    return [h["_score"] for h in r["hits"]["hits"]]


class TestPlaneTruthfulProfile:
    def test_mesh_pallas_profile_reports_plane_and_phases(self):
        idx = build_index("obsprof")
        try:
            body = {"query": {"match": {"body": "t0 t1"}}, "size": 5}
            plain = idx.search(dict(body))
            assert plain["_plane"] == "mesh_pallas", plain["_plane"]
            prof = idx.search(dict(body, profile=True))
            # profile never demotes the plane, hits byte-identical
            assert prof["_plane"] == "mesh_pallas", prof["_plane"]
            assert ids(prof) == ids(plain)
            assert scores(prof) == scores(plain)
            p = prof["profile"]
            assert p["plane"] == "mesh_pallas"
            names = {s["phase"] for s in p["phases"]}
            assert {"staging", "kernel", "merge"} <= names, names
            assert all(s["time_in_nanos"] >= 0 for s in p["phases"])
            # mesh-served: one compiled program, no per-segment trees
            assert p["shards"] == []
        finally:
            idx.close()

    def test_pruned_profile_reports_tile_economy(self):
        idx = build_index("obspruned", n_docs=600, **{
            "index.search.pallas.postings_codec": "packed",
            "search.pallas.pruning.enabled": True,
            "search.pallas.pruning.probe_tiles": 2,
        })
        try:
            body = {"query": {"match": {"body": "t0 t3 t7"}}, "size": 5}
            plain = idx.search(dict(body))
            assert plain["_plane"] == "mesh_pallas"
            assert "_pruned" in plain
            prof = idx.search(dict(body, profile=True))
            assert prof["_plane"] == "mesh_pallas"
            assert "_pruned" in prof
            assert ids(prof) == ids(plain)
            assert scores(prof) == scores(plain)
            ann = prof["profile"]["annotations"]
            assert ann["tiles_scored"] > 0
            assert ann["tiles_pruned"] > 0
            assert ann["postings_bytes_skipped"] > 0
            assert ann["postings_bytes_streamed"] > 0
            counters = idx.search_stats()["phases"]["counters"]
            assert counters["postings_bytes_skipped_total"] > 0
        finally:
            idx.close()

    def test_batched_member_profile_reports_batch_shape(self):
        idx = build_index("obsbatch")
        try:
            burst = [dict({"query": {"match": {"body": f"t{i}"}},
                           "size": 4}, profile=True) for i in range(3)]
            out = idx.search_batch([dict(b) for b in burst])
            for j, got in enumerate(out):
                assert isinstance(got, dict), got
                assert got["_plane"] == "mesh_pallas", got["_plane"]
                ann = got["profile"]["annotations"]
                assert ann["batch_size"] == 3
                assert ann["batch_member_index"] == j
                assert got["profile"]["phases"]
                solo = idx.search({"query": {"match": {"body": f"t{j}"}},
                                   "size": 4})
                assert ids(got) == ids(solo), j
        finally:
            idx.close()

    def test_host_profile_keeps_segment_tree_plus_phases(self):
        idx = build_index("obshost", n_shards=1)
        try:
            r = idx.search({"query": {"match": {"body": "t1"}},
                            "size": 5, "profile": True})
            assert r["_plane"] == "host"
            p = r["profile"]
            assert p["plane"] == "host"
            assert p["shards"], "host profile lost the per-segment tree"
            assert {s["phase"] for s in p["phases"]} >= {"kernel",
                                                         "merge"}
        finally:
            idx.close()

    def test_opaque_id_joins_task_slowlog_and_profile(self, caplog):
        import logging

        from elasticsearch_tpu.search.telemetry import set_opaque_id

        idx = build_index("obsoid", n_shards=1, **{
            "index.search.slowlog.threshold.query.warn": "0s"})
        try:
            set_opaque_id("client-7")
            with caplog.at_level(
                    logging.WARNING,
                    logger="elasticsearch_tpu.index.search.slowlog"):
                r = idx.search({"query": {"match": {"body": "t1"}},
                                "size": 3, "profile": True})
            assert r["profile"]["annotations"]["opaque_id"] == "client-7"
            lines = [rec.getMessage() for rec in caplog.records
                     if rec.name.endswith("search.slowlog")]
            assert lines and "id[client-7]" in lines[0], lines
            assert "plane[host]" in lines[0]
            assert "phases[" in lines[0]
        finally:
            set_opaque_id(None)
            idx.close()

    def test_batch_member_slowlog_keeps_own_opaque_id(self, caplog):
        """Kill switch OFF: every member's tracer is NULL_TRACER, so the
        slowlog falls back to the contextvar — which must be the
        MEMBER's id while its result is built on the leader's thread,
        never the leader's own client id."""
        import logging

        from elasticsearch_tpu.search.telemetry import set_opaque_id

        idx = build_index("obsoidbatch", **{
            "search.telemetry.enabled": False,
            "index.search.slowlog.threshold.query.warn": "0s"})
        try:
            set_opaque_id("leader-client")
            bodies = [{"query": {"match": {"body": f"t{i}"}}, "size": 3}
                      for i in range(3)]
            with caplog.at_level(
                    logging.WARNING,
                    logger="elasticsearch_tpu.index.search.slowlog"):
                out = idx.search_batch(
                    bodies, oids=[f"client-{i}" for i in range(3)])
            assert all(isinstance(r, dict) for r in out)
            lines = [rec.getMessage() for rec in caplog.records
                     if rec.name.endswith("search.slowlog")]
            assert len(lines) == 3, lines
            for i in range(3):
                assert any(f"id[client-{i}]" in ln for ln in lines), (
                    i, lines)
            assert not any("id[leader-client]" in ln for ln in lines)
            # the leader's own request context is restored afterwards
            from elasticsearch_tpu.search.telemetry import get_opaque_id
            assert get_opaque_id() == "leader-client"
        finally:
            set_opaque_id(None)
            idx.close()


class TestCountersUnderConcurrency:
    def test_mixed_burst_counts_consistently(self):
        idx = build_index("obsconc", n_docs=100, **{
            "search.batch.max_queries": 4})
        try:
            # prewarm every program shape serially so the concurrent
            # phase measures counting, not compilation
            idx.search({"query": {"match": {"body": "t0"}}, "size": 3})
            idx.search_batch([
                {"query": {"match": {"body": "t1"}}, "size": 3},
                {"query": {"match": {"body": "t2"}}, "size": 3}])
            qv = [0.1] * 8
            idx.search({"knn": {"field": "emb", "query_vector": qv,
                                "k": 3}})

            lex = [{"query": {"match": {"body": f"t{i % 6}"}}, "size": 3}
                   for i in range(8)]
            knn = [{"knn": {"field": "emb", "query_vector": qv, "k": 3}}
                   for _ in range(4)]
            serial = [{"query": {"match": {"body": f"t{i}"}}, "size": 3,
                       "sort": [{"n": "desc"}]} for i in range(2)]
            bodies = lex + knn + serial
            base_recorded = idx.telemetry.queries_recorded
            mesh = idx._mesh_search
            base_mesh = mesh.query_total
            base_knn = mesh.knn_query_total
            base_host = idx._host_query_total

            errors = []

            def worker(b):
                try:
                    r = idx.search(dict(b))
                    assert isinstance(r, dict)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(b,))
                       for b in bodies]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60.0)
            assert not errors, errors

            # every request recorded exactly once in the telemetry
            assert (idx.telemetry.queries_recorded - base_recorded
                    == len(bodies))
            # every request served by exactly one plane: mesh-served +
            # host-served partition the burst
            mesh_served = mesh.query_total - base_mesh
            host_served = idx._host_query_total - base_host
            assert mesh_served + host_served == len(bodies), (
                mesh_served, host_served)
            # every kNN request reached the MXU rung exactly once
            assert mesh.knn_query_total - base_knn == len(knn)
            # batch accounting stays internally consistent: the batched
            # totals equal the histogram's weighted sum
            bstats = idx.batch_stats.as_dict()
            hist_sum = sum(int(size) * count for size, count
                           in bstats["batch_size_histogram"].items())
            assert bstats["batched_query_total"] == hist_sum
            # per-shard attribution: each shard saw every query once
            for sid, shard in idx.shards.items():
                assert shard.searcher.query_total >= len(bodies), sid
        finally:
            idx.close()


class TestTracerOverheadGuard:
    def test_span_ring_capped_and_accumulators_bounded(self):
        tr = QueryTracer()
        for i in range(10_000):
            t0 = tr.start("kernel")
            tr.stop("kernel", t0)
        # detail ring capped; accumulators bounded by the taxonomy
        assert len(tr._ring) == QueryTracer.MAX_SPANS
        assert tr.ring_dropped == 10_000 - QueryTracer.MAX_SPANS
        spans = tr.spans()
        assert len(spans) == 1  # one accumulator per phase, not 10k
        assert spans[0]["count"] == 10_000
        assert set(tr._acc) <= set(PHASES) | {"kernel"}
        assert tr.annotations()["spans_dropped"] == tr.ring_dropped

    def test_hot_loop_is_cheap(self):
        # generous bound: 20k start/stop pairs (a 5000-segment scan's
        # worth of spans) must stay far from per-query latency budgets.
        # This guards against accidental allocation/IO creeping into
        # the hot path, not against scheduler noise.
        tr = QueryTracer()
        t0 = time.perf_counter()
        for _ in range(20_000):
            t = tr.start("kernel")
            tr.stop("kernel", t)
        took = time.perf_counter() - t0
        assert took < 1.0, f"tracer hot path took {took:.3f}s for 20k spans"

    def test_null_tracer_is_inert(self):
        t0 = NULL_TRACER.start("kernel")
        NULL_TRACER.stop("kernel", t0)
        NULL_TRACER.annotate("x", 1)
        assert NULL_TRACER.spans() == []
        assert NULL_TRACER.annotations() == {}
        tel = SearchTelemetry()
        tel.record_query("host", NULL_TRACER)
        assert tel.queries_recorded == 0

    def test_kill_switch_registered_and_honored(self):
        from elasticsearch_tpu.common.settings import cluster_settings

        reg = cluster_settings()._settings
        assert "search.telemetry.enabled" in reg
        assert reg["search.telemetry.enabled"].dynamic
        idx = build_index("obskill", n_shards=1, **{
            "search.telemetry.enabled": False})
        try:
            assert idx._tracer() is NULL_TRACER
            r = idx.search({"query": {"match": {"body": "t1"}},
                            "size": 3})
            assert isinstance(r, dict)
            phases = idx.search_stats()["phases"]
            assert phases["queries_recorded"] == 0
            assert phases["histogram_us"] == {}
            # the dynamic override wins over the creation-time setting
            idx.telemetry_enabled_override = True
            idx.search({"query": {"match": {"body": "t1"}}, "size": 3})
            assert idx.search_stats()["phases"]["queries_recorded"] == 1
        finally:
            idx.close()


class TestBatchWindowAnnotations:
    def test_microbatcher_annotate_hook(self):
        mb = MicroBatcher(window_s=0.05, max_queries=4)
        seen = {}
        mb.annotate = (lambda item, wait_s, size, idx:
                       seen.setdefault(item, (wait_s, size, idx)))
        start = threading.Barrier(3)
        results = {}

        def slow_single(x):
            time.sleep(0.15)
            return ("single", x)

        def worker(i):
            start.wait()
            results[i] = mb.run(
                "k", i, single_fn=slow_single,
                batch_fn=lambda items: [("batch", x) for x in items])

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        # one went direct (never annotated); the group members carry
        # wait + shape
        assert seen, "annotate hook never fired"
        for item, (wait_s, size, idx) in seen.items():
            assert wait_s >= 0.0
            assert size == len(seen)
            assert 0 <= idx < size

    def test_window_wait_lands_in_profile_annotations(self):
        idx = build_index("obswait", n_docs=60)
        try:
            # prewarm compile so the timed window isn't compilation
            idx.search_batch([
                {"query": {"match": {"body": "t1"}}, "size": 3},
                {"query": {"match": {"body": "t2"}}, "size": 3}])
            start = threading.Barrier(3)
            results = {}

            def worker(i):
                start.wait()
                results[i] = idx.search(dict(
                    {"query": {"match": {"body": f"t{i}"}}, "size": 3},
                    profile=True))

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60.0)
            waits = [r["profile"]["annotations"].get(
                "batch_window_wait_ms") for r in results.values()
                if isinstance(r, dict)]
            # at least the grouped members carry the window wait
            assert any(w is not None and w >= 0.0 for w in waits), waits
        finally:
            idx.close()


class TestQuarantineEvents:
    def test_fault_records_timestamped_event(self):
        from elasticsearch_tpu.testing.disruption import PlaneFailScheme

        idx = build_index("obsquar")
        try:
            body = {"query": {"match": {"body": "t1"}}, "size": 3}
            assert idx.search(dict(body))["_plane"] == "mesh_pallas"
            before_ms = int(time.time() * 1000)
            scheme = PlaneFailScheme(planes=["mesh_pallas"],
                                     indices=["obsquar"]).install()
            try:
                r = idx.search(dict(body))
                assert r["_plane"] != "mesh_pallas"
            finally:
                clear_search_disruptions()
            planes = idx.search_stats()["planes"]
            events = planes["quarantine_events"]
            assert events, "no quarantine event recorded"
            ev = events[-1]
            assert ev["plane"] == "mesh_pallas"
            assert ev["timestamp_ms"] >= before_ms
            assert ev["cooldown_s"] > 0
            # ladder decisions recorded the fault and the fallback
            decisions = idx.search_stats()["phases"]["decisions"]
            assert decisions.get("mesh_pallas.fault", 0) >= 1
        finally:
            idx.close()


class TestNodeStatsMerge:
    def test_merge_phase_stats_sums_histograms(self):
        a = {"query_total": 2,
             "phases": {"taxonomy": list(PHASES), "queries_recorded": 2,
                        "histogram_us": {"host": {"kernel": {"le_8": 2}}},
                        "counters": {"x_total": 1}, "decisions": {}}}
        b = {"query_total": 3,
             "phases": {"taxonomy": list(PHASES), "queries_recorded": 3,
                        "histogram_us": {"host": {"kernel": {"le_8": 1,
                                                             "le_16": 4}}},
                        "counters": {"x_total": 2}, "decisions": {}}}
        m = merge_phase_stats([a, b])
        assert m["query_total"] == 5
        assert m["phases"]["queries_recorded"] == 5
        assert m["phases"]["histogram_us"]["host"]["kernel"] == {
            "le_8": 3, "le_16": 4}
        assert m["phases"]["counters"]["x_total"] == 3
        assert m["phases"]["taxonomy"] == list(PHASES)
