"""Deprecation logger (Warning response headers + dedup) and the
indexing slow log.

Mirrors DeprecationLogger (common/logging/DeprecationLogger.java) and
IndexingSlowLog (index/IndexingSlowLog.java).
"""

import logging

import pytest

from elasticsearch_tpu.common import deprecation as dep
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


class TestDeprecationLogger:
    def test_collects_into_request_scope(self):
        dep.begin_request()
        logger = dep.DeprecationLogger("test")
        logger.deprecated("thing A is deprecated")
        logger.deprecated("thing A is deprecated")  # request-level dedup
        logger.deprecated("thing B is deprecated")
        warnings = dep.collect_warnings()
        assert warnings == ["thing A is deprecated", "thing B is deprecated"]
        # drained: a second collect is empty
        assert dep.collect_warnings() == []

    def test_process_level_log_dedup(self, caplog):
        dep.begin_request()
        logger = dep.DeprecationLogger("test")
        with caplog.at_level(logging.WARNING,
                             logger="elasticsearch_tpu.deprecation"):
            logger.deprecated("only logged once xyz")
            logger.deprecated("only logged once xyz")
        assert sum("only logged once xyz" in r.message
                   for r in caplog.records) <= 1

    def test_warning_header_format(self):
        v = dep.warning_header_value("msg here")
        assert v.startswith('299 ') and '"msg here"' in v

    def test_typed_api_emits_warning(self):
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.rest.controller import RestController

        node = Node()
        node.create_index("idx")
        controller = RestController(node)
        import json

        status, _ = controller.dispatch(
            "PUT", "/idx/tweet/1", {}, json.dumps({"a": 1}).encode())
        assert status in (200, 201)
        warnings = dep.collect_warnings()
        assert any("custom type" in w for w in warnings)
        # the canonical _doc path emits nothing
        controller.dispatch("PUT", "/idx/_doc/2", {},
                            json.dumps({"a": 2}).encode())
        assert dep.collect_warnings() == []
        node.close()


class TestIndexingSlowLog:
    def test_slow_index_logged(self, caplog):
        idx = IndexService("slow", Settings({
            "index.number_of_shards": 1,
            "index.refresh_interval": "-1",
            # 0s threshold: every indexing op is "slow"
            "index.indexing.slowlog.threshold.index.warn": "0s",
            "index.indexing.slowlog.source": 10,
        }))
        with caplog.at_level(
                logging.WARNING,
                logger="elasticsearch_tpu.index.indexing.slowlog"):
            idx.index_doc("1", {"text": "x" * 100})
        recs = [r for r in caplog.records
                if r.name == "elasticsearch_tpu.index.indexing.slowlog"]
        assert len(recs) == 1
        msg = recs[0].getMessage()
        assert "took[" in msg and "id[1]" in msg
        # source truncated to 10 chars
        src = msg.split("source[", 1)[1]
        assert len(src) <= 12
        idx.close()

    def test_disabled_by_default(self, caplog):
        idx = IndexService("quiet", Settings({
            "index.number_of_shards": 1,
            "index.refresh_interval": "-1"}))
        with caplog.at_level(logging.INFO):
            idx.index_doc("1", {"a": 1})
        assert not [r for r in caplog.records
                    if r.name == "elasticsearch_tpu.index.indexing.slowlog"]
        idx.close()

    def test_negative_threshold_disables(self, caplog):
        idx = IndexService("neg", Settings({
            "index.number_of_shards": 1,
            "index.refresh_interval": "-1",
            "index.indexing.slowlog.threshold.index.warn": "-1"}))
        with caplog.at_level(logging.WARNING):
            idx.index_doc("1", {"a": 1})
        assert not [r for r in caplog.records
                    if r.name == "elasticsearch_tpu.index.indexing.slowlog"]
        idx.close()
