"""Rescore, collapse, script_fields, profile, slice (north-star configs)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def idx():
    svc = IndexService("f", Settings({"index.number_of_shards": 1}))
    docs = [
        {"body": "alpha beta", "popularity": 1},
        {"body": "alpha", "popularity": 100},
        {"body": "alpha beta gamma", "popularity": 10},
        {"body": "beta", "popularity": 50},
    ]
    for i, d in enumerate(docs):
        svc.index_doc(str(i), d)
    svc.refresh()
    yield svc
    svc.close()


def ids(r):
    return [h["_id"] for h in r["hits"]["hits"]]


class TestRescore:
    def test_rescore_total(self, idx):
        # base: match alpha; rescore: boost docs matching beta
        r = idx.search({
            "query": {"match": {"body": "alpha"}},
            "rescore": {
                "window_size": 10,
                "query": {
                    "rescore_query": {"match": {"body": "beta"}},
                    "query_weight": 1.0,
                    "rescore_query_weight": 10.0,
                },
            },
        })
        got = ids(r)
        # all alpha docs still present; beta-matching alpha docs ranked first
        assert set(got) == {"0", "1", "2"}
        assert set(got[:2]) == {"0", "2"}

    def test_rescore_function_score_window(self, idx):
        # north-star config 4: function_score-style rescoring over top window
        r = idx.search({
            "query": {"match": {"body": "alpha"}},
            "rescore": {
                "window_size": 2,
                "query": {
                    "rescore_query": {"function_score": {
                        "query": {"match_all": {}},
                        "field_value_factor": {"field": "popularity", "factor": 1.0},
                        "boost_mode": "replace",
                    }},
                    "query_weight": 0.0,
                    "rescore_query_weight": 1.0,
                },
            },
        })
        # only the top-2 by BM25 got rescored by popularity
        assert len(ids(r)) == 3


class TestCollapse:
    def test_collapse_keeps_best_per_group(self):
        svc = IndexService("c", Settings({"index.number_of_shards": 2}))
        rows = [("g1", 1), ("g1", 9), ("g2", 5), ("g2", 3), ("g3", 7)]
        for i, (g, n) in enumerate(rows):
            svc.index_doc(str(i), {"group": g, "n": n, "t": "x"})
        svc.refresh()
        r = svc.search({
            "query": {"match": {"t": "x"}},
            "collapse": {"field": "group"},
            "sort": [{"n": "desc"}],
        })
        assert ids(r) == ["1", "4", "2"]  # best n per group: 9(g1), 7(g3), 5(g2)
        svc.close()


class TestScriptFields:
    def test_script_field_arithmetic(self, idx):
        r = idx.search({
            "query": {"ids": {"values": ["1"]}},
            "script_fields": {
                "pop2": {"script": {"source": "doc['popularity'].value * 2"}},
                "with_params": {"script": {
                    "source": "doc['popularity'].value + params.bonus",
                    "params": {"bonus": 5},
                }},
            },
        })
        f = r["hits"]["hits"][0]["fields"]
        assert f["pop2"] == [200.0]
        assert f["with_params"] == [105.0]

    def test_script_rejects_non_numeric(self, idx):
        from elasticsearch_tpu.common.errors import ParsingException

        with pytest.raises(ParsingException):
            idx.search({
                "query": {"match_all": {}},
                "script_fields": {"bad": {"script": {"source": "__import__('os')"}}},
            })


class TestProfile:
    def test_profile_breakdown_present(self, idx):
        r = idx.search({"query": {"match": {"body": "alpha"}}, "profile": True})
        shards = r["profile"]["shards"]
        assert shards
        q = shards[0]["searches"][0]["query"][0]
        assert q["time_in_nanos"] >= 0
        assert "execute_program" in q["breakdown"]


class TestSlice:
    def test_sliced_scan_partitions(self, idx):
        seen = set()
        for sid in range(3):
            r = idx.search({
                "query": {"match_all": {}},
                "slice": {"id": sid, "max": 3},
                "size": 10,
            })
            got = set(ids(r))
            assert not (seen & got)  # disjoint
            seen |= got
        assert seen == {"0", "1", "2", "3"}
