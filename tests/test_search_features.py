"""Rescore, collapse, script_fields, profile, slice (north-star configs)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def idx():
    svc = IndexService("f", Settings({"index.number_of_shards": 1}))
    docs = [
        {"body": "alpha beta", "popularity": 1},
        {"body": "alpha", "popularity": 100},
        {"body": "alpha beta gamma", "popularity": 10},
        {"body": "beta", "popularity": 50},
    ]
    for i, d in enumerate(docs):
        svc.index_doc(str(i), d)
    svc.refresh()
    yield svc
    svc.close()


def ids(r):
    return [h["_id"] for h in r["hits"]["hits"]]


class TestRescore:
    def test_rescore_total(self, idx):
        # base: match alpha; rescore: boost docs matching beta
        r = idx.search({
            "query": {"match": {"body": "alpha"}},
            "rescore": {
                "window_size": 10,
                "query": {
                    "rescore_query": {"match": {"body": "beta"}},
                    "query_weight": 1.0,
                    "rescore_query_weight": 10.0,
                },
            },
        })
        got = ids(r)
        # all alpha docs still present; beta-matching alpha docs ranked first
        assert set(got) == {"0", "1", "2"}
        assert set(got[:2]) == {"0", "2"}

    def test_rescore_function_score_window(self, idx):
        # north-star config 4: function_score-style rescoring over top window
        r = idx.search({
            "query": {"match": {"body": "alpha"}},
            "rescore": {
                "window_size": 2,
                "query": {
                    "rescore_query": {"function_score": {
                        "query": {"match_all": {}},
                        "field_value_factor": {"field": "popularity", "factor": 1.0},
                        "boost_mode": "replace",
                    }},
                    "query_weight": 0.0,
                    "rescore_query_weight": 1.0,
                },
            },
        })
        # only the top-2 by BM25 got rescored by popularity
        assert len(ids(r)) == 3


class TestCollapse:
    def test_collapse_keeps_best_per_group(self):
        svc = IndexService("c", Settings({"index.number_of_shards": 2}))
        rows = [("g1", 1), ("g1", 9), ("g2", 5), ("g2", 3), ("g3", 7)]
        for i, (g, n) in enumerate(rows):
            svc.index_doc(str(i), {"group": g, "n": n, "t": "x"})
        svc.refresh()
        r = svc.search({
            "query": {"match": {"t": "x"}},
            "collapse": {"field": "group"},
            "sort": [{"n": "desc"}],
        })
        assert ids(r) == ["1", "4", "2"]  # best n per group: 9(g1), 7(g3), 5(g2)
        svc.close()

    def test_collapse_inner_hits_expansion(self):
        svc = IndexService("c2", Settings({"index.number_of_shards": 2}))
        rows = [("g1", 1), ("g1", 9), ("g1", 4), ("g2", 5), ("g2", 3)]
        for i, (g, n) in enumerate(rows):
            svc.index_doc(str(i), {"group": g, "n": n, "t": "x"})
        svc.refresh()
        r = svc.search({
            "query": {"match": {"t": "x"}},
            "collapse": {
                "field": "group",
                "inner_hits": {"name": "group_docs", "size": 2,
                               "sort": [{"n": "desc"}]},
            },
            "sort": [{"n": "desc"}],
        })
        hits = r["hits"]["hits"]
        assert [h["_id"] for h in hits] == ["1", "3"]
        # collapse value rides in fields
        assert hits[0]["fields"]["group"] == ["g1"]
        ih = hits[0]["inner_hits"]["group_docs"]["hits"]
        assert ih["total"] == 3  # whole g1 group
        assert [h["_id"] for h in ih["hits"]] == ["1", "2"]  # top-2 by n
        ih2 = hits[1]["inner_hits"]["group_docs"]["hits"]
        assert ih2["total"] == 2
        assert [h["_id"] for h in ih2["hits"]] == ["3", "4"]
        svc.close()

    def test_collapse_multiple_inner_hits_and_missing_group(self):
        svc = IndexService("c3", Settings({"index.number_of_shards": 1}))
        svc.index_doc("a", {"group": "g1", "n": 2, "t": "x"})
        svc.index_doc("b", {"n": 8, "t": "x"})  # missing group
        svc.index_doc("c", {"n": 6, "t": "x"})  # missing group
        svc.refresh()
        r = svc.search({
            "query": {"match": {"t": "x"}},
            "collapse": {"field": "group", "inner_hits": [
                {"name": "most", "size": 1, "sort": [{"n": "desc"}]},
                {"name": "least", "size": 1, "sort": [{"n": "asc"}]},
            ]},
            "sort": [{"n": "desc"}],
        })
        hits = r["hits"]["hits"]
        assert [h["_id"] for h in hits] == ["b", "a"]  # null group best=b
        null_group = hits[0]
        assert null_group["fields"]["group"] == [None]
        assert [h["_id"] for h in
                null_group["inner_hits"]["most"]["hits"]["hits"]] == ["b"]
        assert [h["_id"] for h in
                null_group["inner_hits"]["least"]["hits"]["hits"]] == ["c"]
        svc.close()

    def test_collapse_sees_groups_beyond_topk_window(self):
        # 20 high-scoring g1 docs must not evict g2's best from the
        # shard's candidate set (shard-level collapse is uncapped)
        svc = IndexService("c6", Settings({"index.number_of_shards": 1}))
        for i in range(20):
            svc.index_doc(f"a{i}", {"group": "g1", "n": 20 - i, "t": "x"})
        for i in range(10):
            svc.index_doc(f"b{i}", {"group": "g2", "n": -i, "t": "x"})
        svc.refresh()
        r = svc.search({"query": {"match": {"t": "x"}},
                        "collapse": {"field": "group"},
                        "sort": [{"n": "desc"}], "size": 10})
        groups = [h["fields"]["group"][0] for h in r["hits"]["hits"]]
        assert groups == ["g1", "g2"]
        svc.close()

    def test_collapse_duplicate_inner_hits_names_rejected(self):
        import pytest

        from elasticsearch_tpu.common.errors import IllegalArgumentException

        svc = IndexService("c7", Settings({"index.number_of_shards": 1}))
        svc.index_doc("a", {"group": "g"})
        svc.refresh()
        with pytest.raises(IllegalArgumentException, match="inner_hits"):
            svc.search({"collapse": {"field": "group", "inner_hits": [
                {"size": 1}, {"size": 2}]}})
        svc.close()

    def test_collapse_rejected_with_search_after_and_scroll(self):
        import pytest

        from elasticsearch_tpu.common.errors import IllegalArgumentException
        from elasticsearch_tpu.node import Node

        svc = IndexService("c4", Settings({"index.number_of_shards": 1}))
        svc.index_doc("a", {"group": "g", "n": 1})
        svc.refresh()
        with pytest.raises(IllegalArgumentException):
            svc.search({"collapse": {"field": "group"},
                        "sort": [{"n": "asc"}], "search_after": [0]})
        svc.close()
        node = Node()
        node.create_index("c5")
        node.index_doc("c5", "1", {"group": "g"})
        with pytest.raises(IllegalArgumentException):
            node.search("c5", {"collapse": {"field": "group"}}, scroll="1m")
        node.close()

    def test_collapse_across_indices(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        for idx in ("i1", "i2"):
            node.create_index(idx)
        node.index_doc("i1", "a", {"group": "g1", "n": 9})
        node.index_doc("i2", "b", {"group": "g1", "n": 5})
        node.index_doc("i2", "c", {"group": "g2", "n": 7})
        for svc in node.indices.values():
            svc.refresh()
        r = node.search("i1,i2", {
            "query": {"match_all": {}},
            "collapse": {"field": "group",
                         "inner_hits": {"name": "g", "size": 5,
                                        "sort": [{"n": "desc"}]}},
            "sort": [{"n": "desc"}],
        })
        hits = r["hits"]["hits"]
        assert [h["_id"] for h in hits] == ["a", "c"]
        # inner hits span both indices
        g1 = hits[0]["inner_hits"]["g"]["hits"]
        assert {h["_id"] for h in g1["hits"]} == {"a", "b"}
        node.close()


class TestScriptFields:
    def test_script_field_arithmetic(self, idx):
        r = idx.search({
            "query": {"ids": {"values": ["1"]}},
            "script_fields": {
                "pop2": {"script": {"source": "doc['popularity'].value * 2"}},
                "with_params": {"script": {
                    "source": "doc['popularity'].value + params.bonus",
                    "params": {"bonus": 5},
                }},
            },
        })
        f = r["hits"]["hits"][0]["fields"]
        assert f["pop2"] == [200.0]
        assert f["with_params"] == [105.0]

    def test_script_rejects_non_numeric(self, idx):
        from elasticsearch_tpu.common.errors import ParsingException

        with pytest.raises(ParsingException):
            idx.search({
                "query": {"match_all": {}},
                "script_fields": {"bad": {"script": {"source": "__import__('os')"}}},
            })


class TestProfile:
    def test_profile_breakdown_present(self, idx):
        r = idx.search({"query": {"match": {"body": "alpha"}}, "profile": True})
        shards = r["profile"]["shards"]
        assert shards
        q = shards[0]["searches"][0]["query"][0]
        assert q["time_in_nanos"] >= 0
        assert "execute_program" in q["breakdown"]


class TestSlice:
    def test_sliced_scan_partitions(self, idx):
        seen = set()
        for sid in range(3):
            r = idx.search({
                "query": {"match_all": {}},
                "slice": {"id": sid, "max": 3},
                "size": 10,
            })
            got = set(ids(r))
            assert not (seen & got)  # disjoint
            seen |= got
        assert seen == {"0", "1", "2", "3"}


class TestProfileTree:
    """Profile responses carry the plan-node tree with pipeline-stage
    breakdowns (ProfileScorer analog; children of the fused device
    program carry structure, the root owns the measured time)."""

    def test_profile_query_tree(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        # pin the host path: profile is plane-truthful now (ISSUE 8) and
        # a mesh-served profile reports phase spans instead of the
        # per-segment plan tree this test inspects
        node.create_index("prof", {
            "settings": {"index": {"search": {"mesh": False}}},
            "mappings": {"_doc": {"properties": {
                "t": {"type": "text"}, "n": {"type": "integer"}}}}})
        for i in range(20):
            node.index_doc("prof", str(i),
                           {"t": f"word{i % 3} common", "n": i},
                           refresh=(i == 19))
        r = node.search("prof", {"profile": True, "query": {"bool": {
            "must": [{"match": {"t": "common"}}],
            "filter": [{"range": {"n": {"gte": 5}}}]}}})
        q = r["profile"]["shards"][0]["searches"][0]["query"][0]
        assert q["type"] == "BoolNode"
        assert q["time_in_nanos"] > 0
        assert {"build_plan", "execute_program",
                "select_topk"} <= set(q["breakdown"])
        kinds = {c["type"] for c in q["children"]}
        assert "ScoreTermsNode" in kinds or "PallasScoreTermsNode" in kinds
        for c in q["children"]:
            assert c["breakdown"] == {"fused_into_parent_program": 0}
        coll = r["profile"]["shards"][0]["searches"][0]["collector"][0]
        assert coll["name"] == "TopKSelector"


class TestExplainDetail:
    """Explain responses carry Lucene-style per-term BM25 breakdowns
    (BM25Similarity.explain analog: boost * idf * tfNorm with inputs)."""

    def test_match_query_breakdown_sums_to_score(self):
        import json

        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.rest.controller import RestController

        node = Node()
        node.create_index("ex", {
            "settings": {"number_of_shards": 1},
            "mappings": {"_doc": {"properties": {
                "t": {"type": "text"}}}}})
        for i in range(10):
            node.index_doc(
                "ex", str(i),
                {"t": f"quick brown fox {i}" if i % 2 else "lazy dog"},
                refresh=(i == 9))
        ctrl = RestController(node)
        st, body = ctrl.dispatch(
            "POST", "/ex/_explain/1", {},
            json.dumps({"query": {"match": {"t": "quick fox"}}}).encode())
        assert st == 200 and body["matched"]
        exp = body["explanation"]
        assert len(exp["details"]) == 2  # one weight per matched term
        assert sum(d["value"] for d in exp["details"]) == \
            __import__("pytest").approx(exp["value"], rel=1e-6)
        comp = exp["details"][0]["details"][0]["details"]
        descs = " ".join(c["description"] for c in comp)
        assert "idf" in descs and "tfNorm" in descs and "boost" in descs
        idf_node = next(c for c in comp if c["description"].startswith("idf"))
        assert {d["description"][0] for d in idf_node["details"]} == {"n", "N"}

    def test_unrecognized_query_stays_summary(self):
        import json

        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.rest.controller import RestController

        node = Node()
        node.create_index("ex2", {"mappings": {"_doc": {"properties": {
            "n": {"type": "integer"}}}}})
        node.index_doc("ex2", "1", {"n": 5}, refresh=True)
        ctrl = RestController(node)
        st, body = ctrl.dispatch(
            "POST", "/ex2/_explain/1", {},
            json.dumps({"query": {"range": {"n": {"gte": 1}}}}).encode())
        assert st == 200 and body["matched"]
        assert body["explanation"]["details"] == []


class TestUnifiedHighlighter:
    """Unified highlighter (the 6.x default): sentence-bounded passages
    scored by unique-term coverage; plain remains available per field."""

    @staticmethod
    def _node():
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("hl", {"mappings": {"_doc": {"properties": {
            "body": {"type": "text"}}}}})
        node.index_doc("hl", "1", {"body": (
            "The quick brown fox jumps over the lazy dog. "
            "Nothing interesting happens in this sentence at all. "
            "Another fox appears and the fox runs away quickly. "
            "The end of the story arrives without any animals.")},
            refresh=True)
        return node

    def test_passages_are_sentence_bounded_and_scored(self):
        node = self._node()
        r = node.search("hl", {
            "query": {"match": {"body": "fox"}},
            "highlight": {"fields": {"body": {"number_of_fragments": 2}}}})
        frags = r["hits"]["hits"][0]["highlight"]["body"]
        assert len(frags) == 2
        # document order by default; both fox sentences present, the
        # boring sentences absent
        assert frags[0].startswith("The quick brown")
        assert "<em>fox</em>" in frags[0] and "<em>fox</em>" in frags[1]
        assert all("Nothing interesting" not in f for f in frags)

    def test_score_order_puts_best_passage_first(self):
        node = self._node()
        r = node.search("hl", {
            "query": {"match": {"body": "fox"}},
            "highlight": {"order": "score",
                          "fields": {"body": {"number_of_fragments": 2}}}})
        frags = r["hits"]["hits"][0]["highlight"]["body"]
        # the two-fox sentence outranks the one-fox sentence
        assert frags[0].count("<em>fox</em>") == 2

    def test_plain_type_still_available(self):
        node = self._node()
        r = node.search("hl", {
            "query": {"match": {"body": "fox"}},
            "highlight": {"fields": {"body": {"type": "plain"}}}})
        frags = r["hits"]["hits"][0]["highlight"]["body"]
        assert any("<em>fox</em>" in f for f in frags)


class TestCanMatchPrefilter:
    """can_match shard prefilter (SearchService.canMatch): shards whose
    doc-value bounds cannot satisfy a pure range query are skipped and
    reported in _shards.skipped."""

    def test_range_query_skips_non_matching_shards(self):
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.utils.murmur3 import shard_id_for

        node = Node()
        node.create_index("rng", {
            # host per-shard path (the mesh data plane executes eligible
            # multi-shard queries as one program and never visits the
            # coordinator's shard loop)
            "settings": {"index": {"number_of_shards": 2,
                                   "search": {"mesh": False}}},
            "mappings": {"_doc": {"properties": {
                "n": {"type": "integer"}}}}})
        # find routing keys that land on distinct shards
        r0 = next(r for r in map(str, range(100))
                  if shard_id_for(r, 2) == 0)
        r1 = next(r for r in map(str, range(100))
                  if shard_id_for(r, 2) == 1)
        for i in range(10):
            node.index_doc("rng", f"a{i}", {"n": i}, routing=r0)
        for i in range(10):
            node.index_doc("rng", f"b{i}", {"n": 1000 + i}, routing=r1)
        node.indices["rng"].refresh()

        res = node.search("rng", {"query": {"range": {"n": {"gte": 900}}},
                                  "size": 20})
        assert res["hits"]["total"] == 10
        assert res["_shards"]["skipped"] == 1
        assert res["_shards"]["successful"] == 2

        # both shards overlap -> nothing skipped
        res = node.search("rng", {"query": {"range": {"n": {"gte": 0}}},
                                  "size": 30})
        assert res["hits"]["total"] == 20
        assert res["_shards"]["skipped"] == 0

        # nothing matches anywhere: one shard still runs for the frame
        res = node.search("rng", {"query": {"range": {"n": {"gte": 10000}}}})
        assert res["hits"]["total"] == 0
        assert res["_shards"]["skipped"] == 1

    def test_non_range_queries_never_skip(self):
        from elasticsearch_tpu.node import Node

        node = Node()
        node.create_index("nr", {
            "settings": {"index": {"number_of_shards": 2}},
            "mappings": {"_doc": {"properties": {
                "t": {"type": "text"}}}}})
        node.index_doc("nr", "1", {"t": "hello"}, refresh=True)
        res = node.search("nr", {"query": {"match": {"t": "hello"}}})
        assert res["_shards"]["skipped"] == 0
