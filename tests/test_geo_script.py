"""geo_polygon query, geo_distance sort, script query (ref:
index/query/GeoPolygonQueryBuilder.java, search/sort/GeoDistanceSortBuilder.java,
index/query/ScriptQueryBuilder.java)."""

import pytest

from elasticsearch_tpu.common.errors import ElasticsearchTpuException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def hit_ids(resp):
    return [h["_id"] for h in resp["hits"]["hits"]]


@pytest.fixture()
def cities(tmp_path):
    idx = IndexService("cities", Settings({"index.number_of_shards": 1}),
                       data_path=str(tmp_path / "cities"))
    idx.put_mapping({"properties": {
        "name": {"type": "keyword"},
        "location": {"type": "geo_point"},
        "population": {"type": "long"},
        "area": {"type": "double"},
    }})
    # Amsterdam, Utrecht, Antwerp (roughly)
    idx.index_doc("ams", {"name": "Amsterdam", "population": 850000, "area": 219.0,
                          "location": {"lat": 52.37, "lon": 4.90}})
    idx.index_doc("utr", {"name": "Utrecht", "population": 350000, "area": 99.0,
                          "location": {"lat": 52.09, "lon": 5.12}})
    idx.index_doc("ant", {"name": "Antwerp", "population": 520000, "area": 204.0,
                          "location": {"lat": 51.22, "lon": 4.40}})
    idx.index_doc("noloc", {"name": "Nowhere", "population": 10, "area": 1.0})
    idx.refresh()
    yield idx
    idx.close()


class TestGeoPolygon:
    def test_polygon_contains(self, cities):
        # triangle around the Netherlands (excludes Antwerp)
        resp = cities.search({"query": {"geo_polygon": {"location": {"points": [
            {"lat": 53.6, "lon": 3.5},
            {"lat": 53.6, "lon": 7.2},
            {"lat": 51.6, "lon": 5.3},
        ]}}}})
        assert sorted(hit_ids(resp)) == ["ams", "utr"]

    def test_polygon_lon_lat_arrays(self, cities):
        # GeoJSON [lon, lat] point arrays
        resp = cities.search({"query": {"geo_polygon": {"location": {"points": [
            [3.5, 53.6], [7.2, 53.6], [5.3, 51.6],
        ]}}}})
        assert sorted(hit_ids(resp)) == ["ams", "utr"]

    def test_too_few_points(self, cities):
        with pytest.raises(ElasticsearchTpuException):
            cities.search({"query": {"geo_polygon": {"location": {"points": [
                {"lat": 1, "lon": 1}, {"lat": 2, "lon": 2}]}}}})


class TestGeoDistanceSort:
    def test_sort_by_distance_from_amsterdam(self, cities):
        resp = cities.search({
            "query": {"exists": {"field": "location"}},
            "sort": [{"_geo_distance": {
                "location": {"lat": 52.37, "lon": 4.90},
                "order": "asc", "unit": "km"}}],
        })
        assert hit_ids(resp) == ["ams", "utr", "ant"]
        sorts = [h["sort"][0] for h in resp["hits"]["hits"]]
        assert sorts[0] == pytest.approx(0.0, abs=1e-3)  # f32 coords ~0.1m
        assert 30 < sorts[1] < 40       # Utrecht ~35 km
        assert 120 < sorts[2] < 140     # Antwerp ~130 km

    def test_missing_location_sorts_last(self, cities):
        resp = cities.search({"sort": [{"_geo_distance": {
            "location": [4.90, 52.37], "order": "asc", "unit": "km"}}]})
        assert hit_ids(resp)[-1] == "noloc"

    def test_multi_point_min(self, cities):
        # min distance to either Amsterdam or Antwerp centers
        resp = cities.search({
            "query": {"exists": {"field": "location"}},
            "sort": [{"_geo_distance": {
                "location": [{"lat": 52.37, "lon": 4.90},
                             {"lat": 51.22, "lon": 4.40}],
                "order": "asc", "unit": "m"}}],
        })
        by_id = {h["_id"]: h["sort"][0] for h in resp["hits"]["hits"]}
        assert by_id["ams"] == pytest.approx(0.0, abs=1.0)  # f32 coords ~0.1m
        assert by_id["ant"] == pytest.approx(0.0, abs=1.0)


class TestGeoSortModes:
    @pytest.fixture()
    def multi(self, tmp_path):
        idx = IndexService("multi", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "multi"))
        idx.put_mapping({"properties": {"loc": {"type": "geo_point"}}})
        # doc 'near_far': one point ~111km north, one ~1110km north of origin
        idx.index_doc("near_far", {"loc": [{"lat": 1.0, "lon": 0.0},
                                           {"lat": 10.0, "lon": 0.0}]})
        idx.index_doc("mid", {"loc": {"lat": 5.0, "lon": 0.0}})
        idx.refresh()
        yield idx
        idx.close()

    def test_desc_defaults_to_max(self, multi):
        resp = multi.search({"sort": [{"_geo_distance": {
            "loc": {"lat": 0.0, "lon": 0.0}, "order": "desc", "unit": "km"}}]})
        ids = hit_ids(resp)
        assert ids == ["near_far", "mid"]  # max(111, 1110) > 556
        assert resp["hits"]["hits"][0]["sort"][0] > 1000

    def test_explicit_mode_min(self, multi):
        resp = multi.search({"sort": [{"_geo_distance": {
            "loc": {"lat": 0.0, "lon": 0.0}, "order": "desc", "unit": "km",
            "mode": "min"}}]})
        assert hit_ids(resp) == ["mid", "near_far"]  # min(111,1110)=111 < 556

    def test_mode_avg(self, multi):
        resp = multi.search({"sort": [{"_geo_distance": {
            "loc": {"lat": 0.0, "lon": 0.0}, "order": "asc", "unit": "km",
            "mode": "avg"}}]})
        by_id = {h["_id"]: h["sort"][0] for h in resp["hits"]["hits"]}
        assert by_id["near_far"] == pytest.approx((111.2 + 1111.95) / 2, rel=0.02)


class TestSearchAfterNullSort:
    def test_null_cursor_pages_past_missing(self, cities):
        # page 1: missing-location doc serializes sort value as null
        resp = cities.search({"sort": [{"_geo_distance": {
            "location": [4.90, 52.37], "order": "asc", "unit": "km"}}], "size": 3})
        assert hit_ids(resp) == ["ams", "utr", "ant"]
        last = resp["hits"]["hits"][-1]["sort"]
        resp2 = cities.search({
            "sort": [{"_geo_distance": {
                "location": [4.90, 52.37], "order": "asc", "unit": "km"}}],
            "search_after": last, "size": 3})
        assert hit_ids(resp2) == ["noloc"]
        assert resp2["hits"]["hits"][0]["sort"] == [None]
        # a null cursor value must not 500 — it maps back to the inf fill
        resp3 = cities.search({
            "sort": [{"_geo_distance": {
                "location": [4.90, 52.37], "order": "asc", "unit": "km"}}],
            "search_after": [None], "size": 3})
        assert hit_ids(resp3) == []


class TestScriptQuery:
    def test_density_filter(self, cities):
        # population density > 3000/km^2: ams ~3881, utr ~3535, ant ~2549
        resp = cities.search({"query": {"script": {"script": {
            "source": "doc['population'].value / doc['area'].value > 3000"}}}})
        assert sorted(hit_ids(resp)) == ["ams", "utr"]

    def test_with_params(self, cities):
        resp = cities.search({"query": {"script": {"script": {
            "source": "doc['population'].value > params.threshold",
            "params": {"threshold": 500000}}}}})
        assert sorted(hit_ids(resp)) == ["ams", "ant"]

    def test_in_bool_filter(self, cities):
        resp = cities.search({"query": {"bool": {
            "must": [{"term": {"name": "Utrecht"}}],
            "filter": [{"script": {"script": "doc['area'].value < 100"}}],
        }}})
        assert hit_ids(resp) == ["utr"]

    def test_division_by_missing_field_no_error(self, cities):
        # a field absent from the whole segment binds zero COLUMNS, so the
        # expression stays in array arithmetic: 1/0.0 -> inf (Java double
        # semantics, matching lang-expression), never a ZeroDivisionError
        # 500 — inf > 0 is true for every doc
        resp = cities.search({"query": {"script": {"script": {
            "source": "1 / doc['absent'].value > 0"}}}})
        assert len(hit_ids(resp)) == 4

    def test_rejects_arbitrary_code(self, cities):
        with pytest.raises(ElasticsearchTpuException):
            cities.search({"query": {"script": {"script": {
                "source": "__import__('os').system('id')"}}}})
