"""geoip + user_agent ingest processors, the _size metadata field,
bigram phrase suggester, and completion suggester contexts.

Mirrors plugins/ingest-geoip, plugins/ingest-user-agent,
plugins/mapper-size, the phrase suggester's StupidBackoff bigram model
(search/suggest/phrase/), and completion contexts
(search/suggest/completion/context/).
"""

import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
    ParsingException,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.node import Node

CHROME_UA = ("Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 "
             "(KHTML, like Gecko) Chrome/70.0.3538.77 Safari/537.36")


@pytest.fixture()
def node():
    n = Node()
    yield n
    n.close()


class TestGeoIp:
    def test_lookup_and_properties(self, node):
        node.ingest.put_pipeline("geo", {"processors": [
            {"geoip": {"field": "ip"}}]})
        node.index_doc("logs", "1", {"ip": "8.8.8.8"}, pipeline="geo")
        src = node.get_doc("logs", "1")["_source"]
        assert src["geoip"]["country_iso_code"] == "US"
        assert src["geoip"]["city_name"] == "Mountain View"
        assert src["geoip"]["location"] == {"lat": 37.386, "lon": -122.0838}

    def test_target_field_and_selected_properties(self, node):
        node.ingest.put_pipeline("geo", {"processors": [
            {"geoip": {"field": "ip", "target_field": "geo",
                       "properties": ["country_iso_code"]}}]})
        node.index_doc("logs", "1", {"ip": "81.2.69.145"}, pipeline="geo")
        src = node.get_doc("logs", "1")["_source"]
        assert src["geo"] == {"country_iso_code": "GB"}

    def test_unresolvable_ip_adds_nothing(self, node):
        node.ingest.put_pipeline("geo", {"processors": [
            {"geoip": {"field": "ip"}}]})
        node.index_doc("logs", "1", {"ip": "10.0.0.1"}, pipeline="geo")
        assert "geoip" not in node.get_doc("logs", "1")["_source"]

    def test_ipv6(self, node):
        node.ingest.put_pipeline("geo", {"processors": [
            {"geoip": {"field": "ip"}}]})
        node.index_doc("logs", "1", {"ip": "2001:4860:4860::8888"},
                       pipeline="geo")
        src = node.get_doc("logs", "1")["_source"]
        assert src["geoip"]["country_iso_code"] == "US"

    def test_bad_ip_fails(self, node):
        node.ingest.put_pipeline("geo", {"processors": [
            {"geoip": {"field": "ip"}}]})
        with pytest.raises(Exception):
            node.index_doc("logs", "1", {"ip": "not-an-ip"}, pipeline="geo")

    def test_missing_field_with_ignore_missing(self, node):
        node.ingest.put_pipeline("geo", {"processors": [
            {"geoip": {"field": "ip", "ignore_missing": True}}]})
        node.index_doc("logs", "1", {"msg": "no ip"}, pipeline="geo")
        assert node.get_doc("logs", "1")["found"]


class TestUserAgent:
    def test_chrome_on_windows(self, node):
        node.ingest.put_pipeline("ua", {"processors": [
            {"user_agent": {"field": "agent"}}]})
        node.index_doc("logs", "1", {"agent": CHROME_UA}, pipeline="ua")
        ua = node.get_doc("logs", "1")["_source"]["user_agent"]
        assert ua["name"] == "Chrome"
        assert ua["major"] == "70"
        assert ua["os"]["name"] == "Windows 10"

    def test_curl(self, node):
        node.ingest.put_pipeline("ua", {"processors": [
            {"user_agent": {"field": "agent", "target_field": "ua"}}]})
        node.index_doc("logs", "1", {"agent": "curl/7.54.0"}, pipeline="ua")
        ua = node.get_doc("logs", "1")["_source"]["ua"]
        assert ua["name"] == "curl" and ua["version"] == "7.54"

    def test_unknown_agent(self, node):
        node.ingest.put_pipeline("ua", {"processors": [
            {"user_agent": {"field": "agent"}}]})
        node.index_doc("logs", "1", {"agent": "my-bot-thing"}, pipeline="ua")
        assert node.get_doc("logs", "1")["_source"]["user_agent"]["name"] == "Other"


class TestSizeField:
    def test_size_indexed_and_queryable(self):
        idx = IndexService("s", Settings({"index.number_of_shards": 1}),
                           mapping={"_size": {"enabled": True},
                                    "properties": {"t": {"type": "text"}}})
        idx.index_doc("small", {"t": "x"})
        idx.index_doc("big", {"t": "y" * 500})
        idx.refresh()
        r = idx.search({"query": {"range": {"_size": {"gt": 100}}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["big"]
        r = idx.search({"query": {"match_all": {}},
                        "sort": [{"_size": "desc"}]})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["big", "small"]
        r = idx.search({"size": 0, "aggs": {"sz": {"max": {"field": "_size"}}}})
        assert r["aggregations"]["sz"]["value"] > 500
        idx.close()

    def test_disabled_by_default(self):
        idx = IndexService("s2", Settings({"index.number_of_shards": 1}))
        idx.index_doc("1", {"t": "x"})
        idx.refresh()
        r = idx.search({"query": {"exists": {"field": "_size"}}})
        assert r["hits"]["total"] == 0
        idx.close()


class TestPhraseBigram:
    def test_bigram_ranks_corpus_collocation_first(self):
        idx = IndexService("p", Settings({"index.number_of_shards": 1}),
                           mapping={"properties": {
                               "body": {"type": "text"}}})
        # "nobel prize" dominates as a bigram; "noble" also exists but
        # never precedes "prize"
        for i in range(5):
            idx.index_doc(f"a{i}", {"body": "nobel prize winners list"})
        for i in range(8):
            idx.index_doc(f"b{i}", {"body": "a noble act of kindness"})
        idx.refresh()
        r = idx.search({"suggest": {"fix": {
            "text": "nobl prize",
            "phrase": {"field": "body"}}}})
        options = r["suggest"]["fix"][0]["options"]
        assert options, "expected phrase corrections"
        # unigram-only scoring would prefer 'noble' (freq 8 > 5); the
        # bigram model picks the collocation
        assert options[0]["text"] == "nobel prize"
        idx.close()


class TestCompletionContexts:
    def make(self):
        idx = IndexService("c", Settings({"index.number_of_shards": 1}),
                           mapping={"properties": {"suggest": {
                               "type": "completion",
                               "contexts": [
                                   {"name": "place", "type": "category"},
                                   {"name": "loc", "type": "geo",
                                    "precision": 4},
                               ]}}})
        idx.index_doc("1", {"suggest": {
            "input": ["timmy's", "timmy house"], "weight": 10,
            "contexts": {"place": ["cafe"],
                         "loc": [{"lat": 43.662, "lon": -79.38}]}}})
        idx.index_doc("2", {"suggest": {
            "input": ["timber mart"], "weight": 5,
            "contexts": {"place": ["shop"],
                         "loc": [{"lat": 48.85, "lon": 2.35}]}}})
        idx.refresh()
        return idx

    def test_category_context_filters(self):
        idx = self.make()
        r = idx.search({"suggest": {"s": {
            "prefix": "tim",
            "completion": {"field": "suggest",
                           "contexts": {"place": ["cafe"]}}}}})
        texts = [o["text"] for o in r["suggest"]["s"][0]["options"]]
        assert "timmy's" in texts and "timber mart" not in texts
        idx.close()

    def test_category_boost(self):
        idx = self.make()
        r = idx.search({"suggest": {"s": {
            "prefix": "tim",
            "completion": {"field": "suggest", "contexts": {"place": [
                {"context": "shop", "boost": 10},
                {"context": "cafe"}]}}}}})
        options = r["suggest"]["s"][0]["options"]
        # shop weight 5 * boost 10 = 50 beats cafe's 10
        assert options[0]["text"] == "timber mart"
        idx.close()

    def test_geo_context(self):
        idx = self.make()
        r = idx.search({"suggest": {"s": {
            "prefix": "tim",
            "completion": {"field": "suggest", "contexts": {"loc": [
                {"context": {"lat": 43.66, "lon": -79.39},
                 "precision": 4}]}}}}})
        texts = [o["text"] for o in r["suggest"]["s"][0]["options"]]
        assert texts and all("timmy" in t for t in texts)
        idx.close()

    def test_unknown_context_rejected(self):
        idx = self.make()
        with pytest.raises(ParsingException):
            idx.search({"suggest": {"s": {
                "prefix": "tim",
                "completion": {"field": "suggest",
                               "contexts": {"nope": ["x"]}}}}})
        idx.close()

    def test_undefined_context_rejected_at_index_time(self):
        idx = self.make()
        with pytest.raises(MapperParsingException):
            idx.index_doc("bad", {"suggest": {
                "input": ["x"], "contexts": {"undefined": ["y"]}}})
        idx.close()
