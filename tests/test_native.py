"""Native C++ analysis library: parity with the pure-Python implementations.

The reference leans on JIT-compiled Java for these loops (Lucene analyzer
chains, Murmur3HashFunction); our native path must be byte-identical to
the Python fallback (which is the behavioral spec)."""

import random
import string

import pytest

from elasticsearch_tpu.analysis.analyzers import (
    Analyzer,
    lowercase_filter,
    standard_tokenizer,
    whitespace_tokenizer,
)
from elasticsearch_tpu.utils import native
from elasticsearch_tpu.utils.murmur3 import murmur3_32, shard_id_for

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


class TestTokenizerParity:
    CASES = [
        "The Quick Brown Fox! 42 times_over",
        "",
        "    leading and trailing   ",
        "punct,only;here: (and) [brackets]",
        "a",
        "x" * 5000,
        "tabs\tand\nnewlines\r\nmixed",
        "under_scores_and_123_numbers",
    ]

    def test_standard_matches_python(self):
        for text in self.CASES:
            fast = native.standard_tokenize_fast(text)
            assert fast is not None
            ref = lowercase_filter(standard_tokenizer(text))
            assert fast == ref, f"mismatch on {text!r}"

    def test_non_ascii_falls_back(self):
        assert native.standard_tokenize_fast("héllo wörld") is None

    def test_whitespace_matches_python(self):
        for text in self.CASES:
            fast = native.whitespace_tokenize_fast(text)
            assert fast == whitespace_tokenizer(text)

    def test_random_ascii_fuzz(self):
        rng = random.Random(11)
        alphabet = string.ascii_letters + string.digits + " .,;-_!()\t\n"
        for _ in range(200):
            text = "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 200)))
            assert native.standard_tokenize_fast(text) == lowercase_filter(
                standard_tokenizer(text)
            )

    def test_analyzer_integration_uses_fast_path(self):
        an = Analyzer("standard", standard_tokenizer, [lowercase_filter])
        assert an.analyze("Fast Path HERE") == ["fast", "path", "here"]
        # unicode text still correct via fallback
        assert an.analyze("héllo wörld") == ["héllo", "wörld"]


class TestMurmurParity:
    def test_hash_parity(self):
        rng = random.Random(5)
        for _ in range(300):
            data = bytes(rng.randrange(256) for _ in range(rng.randint(0, 40)))
            assert native.murmur3_32_fast(data) == murmur3_32(data)

    def test_shard_ids_batch(self):
        ids = [f"doc-{i}" for i in range(500)]
        out = native.shard_ids_batch(ids, 7)
        assert out is not None
        for i, doc_id in enumerate(ids):
            assert out[i] == shard_id_for(doc_id, 7)
