"""Per-field similarity tests.

Mirrors the reference's similarity module (index/similarity/
SimilarityService.java + *Provider.java): BM25 default, classic, boolean,
DFR, IB, LM-Dirichlet, LM-Jelinek-Mercer; custom similarities from index
settings bound to fields via the mapping ``similarity`` parameter.
"""

import math

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.index.similarity import (
    BM25Similarity,
    BooleanSimilarity,
    ClassicSimilarity,
    DFRSimilarity,
    IBSimilarity,
    LMDirichletSimilarity,
    LMJelinekMercerSimilarity,
    SimilarityService,
)

DOCS = [
    "fox fox fox jumps",
    "fox jumps over the lazy dog near the river bank in the morning light",
    "dog sleeps",
    "quick brown fox",
]


def make_index(field_params=None, settings=None):
    props = {"body": {"type": "text", "analyzer": "whitespace"}}
    if field_params:
        props["body"].update(field_params)
    idx = IndexService(
        "sim", Settings(dict({"index.number_of_shards": 1}, **(settings or {}))),
        mapping={"properties": props},
    )
    for i, d in enumerate(DOCS):
        idx.index_doc(str(i + 1), {"body": d})
    idx.refresh()
    return idx


def scores(idx, query="fox"):
    r = idx.search({"query": {"match": {"body": query}}})
    return {h["_id"]: h["_score"] for h in r["hits"]["hits"]}


class TestSimilarityService:
    def test_builtins(self):
        svc = SimilarityService()
        assert isinstance(svc.get("BM25"), BM25Similarity)
        assert isinstance(svc.get("classic"), ClassicSimilarity)
        assert isinstance(svc.get("boolean"), BooleanSimilarity)
        assert isinstance(svc.get(None), BM25Similarity)  # default

    def test_custom_from_settings(self):
        svc = SimilarityService(Settings({
            "index.similarity.my_bm25.type": "BM25",
            "index.similarity.my_bm25.k1": 1.8,
            "index.similarity.my_bm25.b": 0.3,
            "index.similarity.my_dfr.type": "DFR",
            "index.similarity.my_dfr.basic_model": "if",
            "index.similarity.my_dfr.after_effect": "b",
            "index.similarity.my_dfr.normalization": "h1",
            "index.similarity.my_ib.type": "IB",
            "index.similarity.my_ib.distribution": "spl",
            "index.similarity.my_ib.lambda": "ttf",
            "index.similarity.my_lmd.type": "LMDirichlet",
            "index.similarity.my_lmd.mu": 500,
            "index.similarity.my_lmj.type": "LMJelinekMercer",
            "index.similarity.my_lmj.lambda": 0.7,
        }))
        bm = svc.get("my_bm25")
        assert (bm.k1, bm.b) == (1.8, 0.3)
        dfr = svc.get("my_dfr")
        assert (dfr.basic_model, dfr.after_effect, dfr.normalization) == ("if", "b", "h1")
        assert svc.get("my_ib").distribution == "spl"
        assert svc.get("my_lmd").mu == 500.0
        assert svc.get("my_lmj").lam == 0.7

    def test_default_override(self):
        svc = SimilarityService(Settings({
            "index.similarity.default.type": "boolean"}))
        assert isinstance(svc.get(None), BooleanSimilarity)

    def test_unknown_type_rejected(self):
        with pytest.raises(IllegalArgumentException):
            SimilarityService(Settings({"index.similarity.x.type": "nope"}))

    def test_unknown_name_rejected(self):
        with pytest.raises(IllegalArgumentException):
            SimilarityService().get("missing")

    def test_unknown_field_similarity_rejected_at_mapping_time(self):
        with pytest.raises(IllegalArgumentException):
            make_index({"similarity": "typo_name"})

    def test_bad_dfr_params_rejected(self):
        with pytest.raises(IllegalArgumentException):
            DFRSimilarity(basic_model="zz")
        with pytest.raises(IllegalArgumentException):
            IBSimilarity(distribution="zz")
        with pytest.raises(IllegalArgumentException):
            LMJelinekMercerSimilarity(lam=0.0)


class TestEndToEnd:
    def test_boolean_similarity_flat_scores(self):
        idx = make_index({"similarity": "boolean"})
        s = scores(idx)
        # boolean: every match scores exactly the boost (1.0)
        assert set(s) == {"1", "2", "4"}
        for v in s.values():
            assert v == pytest.approx(1.0)
        idx.close()

    def test_classic_similarity_values(self):
        idx = make_index({"similarity": "classic"})
        s = scores(idx)
        # ClassicSimilarity: idf^2 * sqrt(tf) / sqrt(dl)
        idf = 1.0 + math.log((4 + 1.0) / (3 + 1.0))
        assert s["1"] == pytest.approx(idf * idf * math.sqrt(3) / math.sqrt(4), rel=1e-5)
        assert s["4"] == pytest.approx(idf * idf * 1.0 / math.sqrt(3), rel=1e-5)
        idx.close()

    def test_bm25_custom_params(self):
        # b=0 removes length normalization: doc2 (long) ties doc4 (short)
        idx = make_index(
            {"similarity": "len_blind"},
            {"index.similarity.len_blind.type": "BM25",
             "index.similarity.len_blind.b": 0.0},
        )
        s = scores(idx)
        assert s["2"] == pytest.approx(s["4"], rel=1e-5)
        assert s["1"] > s["2"]  # tf=3 still wins
        idx.close()

    def test_lm_dirichlet_ranking(self):
        idx = make_index(
            {"similarity": "lmd"},
            {"index.similarity.lmd.type": "LMDirichlet",
             "index.similarity.lmd.mu": 100},
        )
        s = scores(idx)
        # highest tf/dl ratio wins under the language model
        assert set(s) <= {"1", "2", "4"}
        assert max(s, key=s.get) == "1"
        # scores are clamped at >= 0 (Lucene LMSimilarity behavior)
        assert all(v >= 0 for v in s.values())
        idx.close()

    def test_lm_jelinek_mercer_ranking(self):
        idx = make_index(
            {"similarity": "lmj"},
            {"index.similarity.lmj.type": "LMJelinekMercer",
             "index.similarity.lmj.lambda": 0.5},
        )
        s = scores(idx)
        assert max(s, key=s.get) == "1"
        assert s["4"] > s["2"]  # shorter doc, same tf
        idx.close()

    def test_dfr_and_ib_rank_sensibly(self):
        for params in (
            {"index.similarity.alt.type": "DFR",
             "index.similarity.alt.basic_model": "g",
             "index.similarity.alt.after_effect": "l",
             "index.similarity.alt.normalization": "h2"},
            {"index.similarity.alt.type": "IB",
             "index.similarity.alt.distribution": "ll",
             "index.similarity.alt.lambda": "df",
             "index.similarity.alt.normalization": "h2"},
        ):
            idx = make_index({"similarity": "alt"}, params)
            s = scores(idx)
            assert set(s) == {"1", "2", "4"}
            assert max(s, key=s.get) == "1"
            assert all(v >= 0 for v in s.values())
            idx.close()

    def test_default_similarity_override_applies_without_mapping(self):
        idx = make_index(
            None, {"index.similarity.default.type": "boolean"})
        s = scores(idx)
        assert all(v == pytest.approx(1.0) for v in s.values())
        idx.close()

    def test_mixed_similarities_multi_match(self):
        # one field BM25, one boolean — both contribute in one program
        idx = IndexService(
            "mix", Settings({"index.number_of_shards": 1}),
            mapping={"properties": {
                "a": {"type": "text", "analyzer": "whitespace"},
                "b": {"type": "text", "analyzer": "whitespace",
                      "similarity": "boolean"},
            }},
        )
        idx.index_doc("1", {"a": "fox", "b": "fox"})
        idx.index_doc("2", {"a": "fox", "b": "cat"})
        idx.refresh()
        r = idx.search({"query": {"multi_match": {
            "query": "fox", "fields": ["a", "b"], "type": "most_fields"}}})
        s = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        assert set(s) == {"1", "2"}
        # doc1 gets the boolean field's flat 1.0 on top of the BM25 score
        assert s["1"] == pytest.approx(s["2"] + 1.0, rel=1e-5)
        idx.close()

    def test_phrase_respects_field_similarity(self):
        idx = IndexService(
            "ph", Settings({"index.number_of_shards": 1}),
            mapping={"properties": {
                "b": {"type": "text", "analyzer": "whitespace",
                      "similarity": "boolean"}}},
        )
        idx.index_doc("1", {"b": "quick brown fox"})
        idx.index_doc("2", {"b": "quick brown dog and quick brown cat"})
        idx.refresh()
        r = idx.search({"query": {"match_phrase": {"b": "quick brown"}}})
        s = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
        # boolean similarity: flat scores, no tf/length effect
        assert set(s) == {"1", "2"}
        assert s["1"] == pytest.approx(s["2"], rel=1e-6)
        idx.close()

    def test_bm25_unchanged_by_default(self):
        # regression guard: default scoring stays exact Lucene BM25
        idx = make_index()
        s = scores(idx)
        n, df = 4, 3
        idf = math.log(1 + (n - df + 0.5) / (df + 0.5))
        avgdl = (4 + 14 + 2 + 3) / 4.0
        tf, dl = 3.0, 4.0
        expected = idf * tf * 2.2 / (tf + 1.2 * (1 - 0.75 + 0.75 * dl / avgdl))
        assert s["1"] == pytest.approx(expected, rel=1e-4)
        idx.close()
