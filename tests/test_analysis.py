"""Analysis chain tests (ref: index/analysis + modules/analysis-common)."""

import pytest

from elasticsearch_tpu.analysis.analyzers import (
    AnalysisRegistry,
    html_strip_char_filter,
    make_ngram_tokenizer,
    make_shingle_filter,
    porter_light_stem,
)
from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.settings import Settings


class TestBuiltinAnalyzers:
    def setup_method(self):
        self.reg = AnalysisRegistry()

    def test_standard(self):
        assert self.reg.get("standard").analyze("The QUICK brown-fox, 42!") == [
            "the", "quick", "brown", "fox", "42",
        ]

    def test_simple_drops_digits(self):
        assert self.reg.get("simple").analyze("abc 123 Def") == ["abc", "def"]

    def test_whitespace_preserves_case(self):
        assert self.reg.get("whitespace").analyze("Foo Bar") == ["Foo", "Bar"]

    def test_keyword_single_token(self):
        assert self.reg.get("keyword").analyze("New York") == ["New York"]

    def test_stop_analyzer(self):
        assert self.reg.get("stop").analyze("the quick fox") == ["quick", "fox"]

    def test_english_stems(self):
        toks = self.reg.get("english").analyze("the running dogs jumped")
        assert "the" not in toks
        assert "runn" in toks or "run" in toks
        assert "dog" in toks

    def test_unknown_analyzer_raises(self):
        with pytest.raises(IllegalArgumentException):
            self.reg.get("nope")


class TestComponents:
    def test_ngram(self):
        tok = make_ngram_tokenizer(2, 3)
        texts = [t for t, _, _ in tok("abcd")]
        assert "ab" in texts and "abc" in texts and "cd" in texts

    def test_edge_ngram(self):
        tok = make_ngram_tokenizer(1, 3, edge=True)
        assert [t for t, _, _ in tok("abcd")] == ["a", "ab", "abc"]

    def test_shingle(self):
        f = make_shingle_filter(2, 2)
        toks = f([("quick", 0, 5), ("brown", 6, 11), ("fox", 12, 15)])
        texts = [t for t, _, _ in toks]
        assert "quick brown" in texts and "brown fox" in texts and "quick" in texts

    def test_html_strip(self):
        assert html_strip_char_filter("<p>hello <b>world</b></p>").split() == [
            "hello", "world",
        ]

    def test_stemmer(self):
        assert porter_light_stem("dogs") == "dog"
        assert porter_light_stem("cities") == "citi"


class TestCustomAnalyzers:
    def test_custom_from_settings(self):
        settings = Settings.from_dict({
            "index": {"analysis": {
                "char_filter": {"my_map": {"type": "mapping", "mappings": ["& => and"]}},
                "filter": {"my_stop": {"type": "stop", "stopwords": ["a", "the"]}},
                "analyzer": {"my_an": {
                    "type": "custom",
                    "tokenizer": "standard",
                    "char_filter": ["my_map"],
                    "filter": ["lowercase", "my_stop"],
                }},
            }}
        })
        reg = AnalysisRegistry(settings)
        assert reg.get("my_an").analyze("The Cat & Dog") == ["cat", "and", "dog"]

    def test_custom_ngram_tokenizer(self):
        settings = Settings.from_dict({
            "index": {"analysis": {
                "tokenizer": {"grams": {"type": "edge_ngram", "min_gram": 2, "max_gram": 4}},
                "analyzer": {"ac": {"tokenizer": "grams", "filter": ["lowercase"]}},
            }}
        })
        assert AnalysisRegistry(settings).get("ac").analyze("Search") == [
            "se", "sea", "sear",
        ]

    def test_unknown_filter_fails_at_build(self):
        settings = Settings.from_dict({
            "index": {"analysis": {"analyzer": {"bad": {
                "tokenizer": "standard", "filter": ["nope"]}}}}
        })
        with pytest.raises(IllegalArgumentException):
            AnalysisRegistry(settings)
