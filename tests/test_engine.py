"""Engine/segment/translog/store tests (ref: index/engine, index/translog)."""

import numpy as np
import pytest

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.common.errors import VersionConflictEngineException
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.segment import BLOCK, SegmentBuilder
from elasticsearch_tpu.index.store import CorruptIndexException, Store
from elasticsearch_tpu.index.translog import Translog, TranslogOp
from elasticsearch_tpu.mapper.mapping import MapperService


def make_engine(tmp_path, store=True):
    svc = MapperService(AnalysisRegistry())
    tl = Translog(str(tmp_path / "translog"))
    st = Store(str(tmp_path / "store")) if store else None
    return Engine("test-shard-0", svc, tl, st)


class TestSegmentBuilder:
    def _seal(self, docs):
        svc = MapperService(AnalysisRegistry())
        b = SegmentBuilder("s1")
        for i, src in enumerate(docs):
            b.add_document(svc.parse_document(str(i), src), seqno=i)
        return b.seal()

    def test_postings_block_packed(self):
        seg = self._seal([{"body": "quick fox"}, {"body": "quick dog"}])
        tid = seg.term_id("body", "quick")
        assert tid >= 0
        assert seg.term_doc_freq[tid] == 2
        start = seg.term_block_start[tid]
        assert seg.term_block_count[tid] == 1
        row = seg.block_docs[start]
        assert list(row[:2]) == [0, 1]
        # padding points at the sentinel slot
        assert (row[2:] == seg.nd_pad).all()
        assert seg.block_tfs[start][0] == 1.0

    def test_tf_counted(self):
        seg = self._seal([{"body": "go go go stop"}])
        tid = seg.term_id("body", "go")
        assert seg.block_tfs[seg.term_block_start[tid]][0] == 3.0

    def test_norms_are_field_lengths(self):
        seg = self._seal([{"body": "one two three"}, {"body": "one"}])
        idx = seg.field_norm_idx["body"]
        assert seg.norms[idx][0] == 3.0
        assert seg.norms[idx][1] == 1.0
        assert seg.field_avgdl("body") == 2.0

    def test_large_term_spans_blocks(self):
        n = BLOCK + 10
        seg = self._seal([{"body": "common"} for _ in range(n)])
        tid = seg.term_id("body", "common")
        assert seg.term_block_count[tid] == 2
        assert seg.term_doc_freq[tid] == n

    def test_numeric_column(self):
        seg = self._seal([{"n": 5}, {"x": "no n"}, {"n": [1, 9]}])
        col = seg.numeric_columns["n"]
        assert col.count == 3
        assert col.exists[0] and not col.exists[1] and col.exists[2]
        assert col.first_value[0] == 5.0
        assert col.min_value[2] == 1.0 and col.max_value[2] == 9.0

    def test_ordinal_column_sorted(self):
        seg = self._seal([{"t": "b"}, {"t": "a"}, {"t": ["c", "a"]}])
        col = seg.ordinal_columns["t.keyword"]
        assert col.terms == ["a", "b", "c"]
        assert col.ord_of("b") == 1
        assert col.ord_of("zz") == -1
        assert col.first_ord[1] == 0

    def test_positions_stored(self):
        seg = self._seal([{"body": "alpha beta alpha"}])
        tid = seg.term_id("body", "alpha")
        assert list(seg.positions[tid][0]) == [0, 2]

    def test_terms_for_field(self):
        seg = self._seal([{"a": "x y", "b": "z"}])
        toks = [t for t, _ in seg.terms_for_field("a")]
        assert toks == ["x", "y"]


class TestEngine:
    def test_index_refresh_visibility(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"title": "hello world"})
        assert e.num_docs == 0  # not yet refreshed (NRT semantics)
        assert e.buffered_docs == 1
        e.refresh()
        assert e.num_docs == 1

    def test_realtime_get_sees_unrefreshed(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"v": 1})
        g = e.get("1")
        assert g.found and g.source == {"v": 1} and g.version == 1

    def test_update_bumps_version_and_tombstones(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"v": 1})
        e.refresh()
        r = e.index("1", {"v": 2})
        assert r["_version"] == 2 and r["result"] == "updated"
        e.refresh()
        assert e.num_docs == 1  # old copy tombstoned
        assert e.get("1").source == {"v": 2}

    def test_version_conflict(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"v": 1})
        with pytest.raises(VersionConflictEngineException):
            e.index("1", {"v": 2}, version=99)
        with pytest.raises(VersionConflictEngineException):
            e.index("1", {"v": 2}, op_type="create")

    def test_delete(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"v": 1})
        e.refresh()
        r = e.delete("1")
        assert r["result"] == "deleted"
        assert not e.get("1").found  # realtime GET sees the tombstone
        assert e.num_docs == 1  # NRT: search-invisible until refresh
        e.refresh()
        assert e.num_docs == 0
        assert e.delete("nope")["result"] == "not_found"

    def test_seqnos_monotonic(self, tmp_path):
        e = make_engine(tmp_path)
        for i in range(5):
            e.index(str(i), {"i": i})
        assert e.max_seqno == 4
        assert e.local_checkpoint == 4

    def test_force_merge_single_segment(self, tmp_path):
        e = make_engine(tmp_path)
        for i in range(3):
            e.index(str(i), {"i": i})
            e.refresh()
        e.delete("1")
        assert len(e.segments) == 3
        e.force_merge()
        assert len(e.segments) == 1
        assert e.num_docs == 2
        assert e.segments[0].num_docs == 2  # deletes expunged

    def test_recover_from_translog(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"v": 1})
        e.index("2", {"v": 2})
        e.index("1", {"v": 10})
        e.delete("2")
        e.close()
        # crash: new engine over the same translog, no flush happened
        e2 = make_engine(tmp_path)
        n = e2.recover_from_translog()
        assert n == 4
        assert e2.get("1").source == {"v": 10}
        assert e2.get("1").version == 2
        assert not e2.get("2").found
        assert e2.num_docs == 1

    def test_flush_then_recover_skips_committed(self, tmp_path):
        e = make_engine(tmp_path)
        e.index("1", {"v": 1})
        e.flush()
        e.index("2", {"v": 2})
        e.close()
        e2 = make_engine(tmp_path)
        e2.segments = e2.store.load_segments()
        # rebuild version map from loaded segments (shard open path)
        for seg in e2.segments:
            for doc, doc_id in enumerate(seg.doc_ids):
                if seg.live[doc]:
                    from elasticsearch_tpu.index.engine import VersionEntry
                    e2.version_map[doc_id] = VersionEntry(
                        int(seg.versions[doc]), int(seg.seqnos[doc]), seg.name, doc
                    )
            e2.note_external_seqno(int(seg.seqnos.max()) if seg.num_docs else -1)
        assert e2.recover_from_translog() == 1  # only the uncommitted op
        assert e2.num_docs == 2


class TestTranslog:
    def test_append_and_snapshot(self, tmp_path):
        tl = Translog(str(tmp_path))
        tl.add(TranslogOp(TranslogOp.INDEX, 0, "1", {"a": 1}))
        tl.add(TranslogOp(TranslogOp.DELETE, 1, "1"))
        ops = tl.snapshot()
        assert [o.op_type for o in ops] == ["index", "delete"]
        assert ops[0].source == {"a": 1}

    def test_generation_roll_and_trim(self, tmp_path):
        tl = Translog(str(tmp_path))
        tl.add(TranslogOp(TranslogOp.INDEX, 0, "1", {"a": 1}))
        tl.roll_generation()
        tl.add(TranslogOp(TranslogOp.INDEX, 1, "2", {"a": 2}))
        assert tl.generation == 2
        tl.mark_committed(0)  # gen-1 fully committed -> trimmed
        assert len(tl.snapshot()) == 1
        assert tl.uncommitted_ops()[0].doc_id == "2"

    def test_reopen_preserves_state(self, tmp_path):
        tl = Translog(str(tmp_path))
        tl.add(TranslogOp(TranslogOp.INDEX, 7, "x", {}))
        tl.close()
        tl2 = Translog(str(tmp_path))
        assert tl2.max_seqno == 7
        assert len(tl2.snapshot()) == 1


class TestStore:
    def _segment(self):
        svc = MapperService(AnalysisRegistry())
        b = SegmentBuilder("seg_1")
        b.add_document(svc.parse_document("a", {"body": "hello world", "n": 3}), 0)
        b.add_document(svc.parse_document("b", {"body": "hello", "t": "tag"}), 1)
        return b.seal()

    def test_roundtrip(self, tmp_path):
        st = Store(str(tmp_path))
        seg = self._segment()
        seg.delete_doc(1)
        st.commit([seg], max_seqno=1, version_map=None)
        loaded = st.load_segments()
        assert len(loaded) == 1
        l = loaded[0]
        assert l.num_docs == 2
        assert l.doc_ids == ["a", "b"]
        assert not l.live[1]
        assert l.term_id("body", "hello") == seg.term_id("body", "hello")
        np.testing.assert_array_equal(l.block_docs, seg.block_docs)
        assert l.numeric_columns["n"].first_value[0] == 3.0
        assert l.ordinal_columns["t.keyword"].terms == ["tag"]
        assert l.sources[0] == {"body": "hello world", "n": 3}
        tid = l.term_id("body", "hello")
        assert list(l.positions[tid][0]) == [0]

    def test_corruption_detected(self, tmp_path):
        st = Store(str(tmp_path))
        seg = self._segment()
        st.commit([seg], 1)
        # flip bits in the arrays file
        import os
        p = os.path.join(str(tmp_path), "seg_1", "arrays.npz")
        with open(p, "r+b") as f:
            f.seek(100)
            f.write(b"\xff\xff\xff")
        with pytest.raises(CorruptIndexException):
            st.read_segment("seg_1")

    def test_commit_gc_removes_dropped_segments(self, tmp_path):
        st = Store(str(tmp_path))
        seg = self._segment()
        st.commit([seg], 1)
        import os
        assert os.path.exists(os.path.join(str(tmp_path), "seg_1"))
        st.commit([], 1)
        assert not os.path.exists(os.path.join(str(tmp_path), "seg_1"))
