"""Fused on-device aggregations (ISSUE 13, docs/AGGS.md).

Byte-parity contract: for every fused-eligible agg type, the mesh
program's in-launch reduction must return the EXACT response dict the
host oracle computes — same bucket keys/order/counts, same metric
floats — on every rung (serial mesh_pallas, batched members, with
deletes, multi-segment packed slots). Everything outside the engineered
envelope falls back STRUCTURALLY to the host reduce (counted per
reason) and the pruning x aggs mutual exclusion forces agg'd queries
onto the exhaustive path. Runs the kernel in interpret mode on the CPU
backend (tests/test_pallas_scoring idiom).
"""

import numpy as np
import pytest

from elasticsearch_tpu.common.memory import memory_accountant
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.testing.disruption import (
    PlaneFailScheme,
    QueuePressureScheme,
    clear_search_disruptions,
)

MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "n": {"type": "integer"},
    "price": {"type": "double"},
    "ts": {"type": "date"},
    "tag": {"type": "keyword"},
    "tags": {"type": "keyword"},
}}

EPOCH = 1500000000000  # ~2017-07-14, epoch millis


@pytest.fixture(autouse=True)
def _interpret_kernel(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
    yield
    clear_search_disruptions()


def _fill(idx, n_docs=90, refreshes=1, seed=0):
    rng = np.random.RandomState(seed)
    vocab = [f"t{i}" for i in range(12)]
    tags = ["red", "green", "blue", "teal"]
    per = n_docs // refreshes
    for batch in range(refreshes):
        for d in range(batch * per, (batch + 1) * per):
            toks = [vocab[rng.randint(len(vocab))]
                    for _ in range(rng.randint(3, 9))]
            idx.index_doc(str(d), {
                "body": " ".join(toks),
                "n": d % 17,
                "price": (d % 5) + 0.25,  # non-integer: sum falls back
                "ts": EPOCH + (d % 7) * 3600_000,
                "tag": tags[d % 4],
            })
        idx.refresh()
    return idx


def build_pair(prefix, n_shards=2, n_docs=90, refreshes=1, seed=0,
               mesh_extra=None):
    """(mesh index, host-only oracle index) over identical docs."""
    def mk(name, mesh):
        settings = {"index.number_of_shards": n_shards,
                    "index.refresh_interval": -1,
                    "index.search.mesh": mesh}
        settings.update(mesh_extra or {} if mesh else {})
        return _fill(IndexService(name, Settings(settings),
                                  mapping=MAPPING),
                     n_docs=n_docs, refreshes=refreshes, seed=seed)

    return mk(f"{prefix}-mesh", True), mk(f"{prefix}-host", False)


ALL_FUSED_AGGS = {
    "tags": {"terms": {"field": "tag"}},
    "top2": {"terms": {"field": "tag", "size": 2}},
    "bykey": {"terms": {"field": "tag", "order": {"_key": "asc"}}},
    "hist": {"histogram": {"field": "n", "interval": 5}},
    "hoff": {"histogram": {"field": "n", "interval": 4, "offset": 1}},
    "dh": {"date_histogram": {"field": "ts", "interval": "1h"}},
    "st": {"stats": {"field": "n"}},
    "mn": {"min": {"field": "n"}},
    "mx": {"max": {"field": "n"}},
    "sm": {"sum": {"field": "n"}},
    "av": {"avg": {"field": "n"}},
    "vc": {"value_count": {"field": "n"}},
    "dmn": {"min": {"field": "ts"}},  # epoch-ms ints: hi/lo split path
    "dsm": {"sum": {"field": "ts"}},  # bignum digit reconstruction
}


def assert_parity(got, want, score_tol=0.0):
    assert got["hits"]["total"] == want["hits"]["total"]
    assert ([h["_id"] for h in got["hits"]["hits"]]
            == [h["_id"] for h in want["hits"]["hits"]])
    for g, w in zip(got["hits"]["hits"], want["hits"]["hits"]):
        if score_tol:
            assert abs(g["_score"] - w["_score"]) <= score_tol
        else:
            assert g["_score"] == w["_score"], (g, w)
    assert got.get("aggregations") == want.get("aggregations"), (
        got.get("aggregations"), want.get("aggregations"))


class TestFusedParity:
    def test_every_fused_type_byte_identical(self):
        mesh, host = build_pair("fap")
        try:
            body = {"query": {"match": {"body": "t0 t1"}}, "size": 5,
                    "aggs": dict(ALL_FUSED_AGGS)}
            got = mesh.search(dict(body))
            want = host.search(dict(body))
            assert got["_plane"] == "mesh_pallas", got["_plane"]
            assert_parity(got, want)
            ms = mesh._mesh_search
            assert ms.agg_fused_query_total == 1
            assert ms.agg_host_fallback_total == 0, \
                ms.agg_host_fallback_by_reason
            # the doc_values ledger kind is populated by the staged
            # agg/sort columns and visible in _stats search.memory
            mem = mesh.search_stats()["memory"]
            assert mem["staged_bytes"]["doc_values"] > 0
        finally:
            mesh.close()
            host.close()
        # leak-free: close released every doc_values byte with the scope
        assert memory_accountant().stats("fap-mesh")[
            "staged_bytes_total"] == 0

    def test_multi_segment_packed_slots(self):
        # 5 shards x 2 refreshes = 10 segments > 8 devices: slot packing
        mesh, host = build_pair("fpk", n_shards=5, n_docs=100,
                                refreshes=2)
        try:
            n_pairs = sum(
                1 for sid in mesh.shards
                for seg in mesh.shards[sid].engine.searchable_segments()
                if seg.num_docs > 0)
            assert n_pairs > 8
            body = {"query": {"match": {"body": "t1 t2"}}, "size": 6,
                    "aggs": {"tags": {"terms": {"field": "tag"}},
                             "st": {"stats": {"field": "n"}},
                             "dh": {"date_histogram": {
                                 "field": "ts", "interval": "1h"}}}}
            got = mesh.search(dict(body))
            want = host.search(dict(body))
            assert got["_plane"] == "mesh_pallas", got["_plane"]
            assert_parity(got, want)
        finally:
            mesh.close()
            host.close()

    def test_deletes_excluded_on_device(self):
        mesh, host = build_pair("fdel")
        try:
            for d in range(0, 90, 3):
                mesh.delete_doc(str(d))
                host.delete_doc(str(d))
            body = {"query": {"match": {"body": "t0 t1 t2"}}, "size": 5,
                    "aggs": {"tags": {"terms": {"field": "tag"}},
                             "sm": {"sum": {"field": "n"}},
                             "vc": {"value_count": {"field": "n"}}}}
            got = mesh.search(dict(body))
            want = host.search(dict(body))
            assert got["_plane"] == "mesh_pallas", got["_plane"]
            assert_parity(got, want)
        finally:
            mesh.close()
            host.close()

    def test_sorted_query_stays_on_plane_with_fused_aggs(self):
        mesh, host = build_pair("fsrt")
        try:
            body = {"query": {"match": {"body": "t0 t1"}}, "size": 5,
                    "sort": [{"n": "desc"}],
                    "aggs": {"tags": {"terms": {"field": "tag"}}}}
            got = mesh.search(dict(body))
            want = host.search(dict(body))
            assert got["_plane"] == "mesh_pallas", got["_plane"]
            assert ([h["_id"] for h in got["hits"]["hits"]]
                    == [h["_id"] for h in want["hits"]["hits"]])
            assert got["aggregations"] == want["aggregations"]
            assert mesh._mesh_search.agg_fused_query_total == 1
        finally:
            mesh.close()
            host.close()


class TestBatchedFusedAggs:
    def test_heterogeneous_members_one_launch_member_isolation(self):
        mesh, host = build_pair("fbat")
        try:
            burst = [
                {"query": {"match": {"body": "t0 t1"}}, "size": 5,
                 "aggs": {"tags": {"terms": {"field": "tag"}}}},
                {"query": {"match": {"body": "t2"}}, "size": 4,
                 "aggs": {"st": {"stats": {"field": "n"}},
                          "dh": {"date_histogram": {"field": "ts",
                                                    "interval": "1h"}}}},
                {"query": {"match": {"body": "t3 t4"}}, "size": 6},
                {"query": {"match": {"body": "t1 t5"}}, "size": 5,
                 "aggs": {"h": {"histogram": {"field": "n",
                                              "interval": 4}}}},
            ]
            out = mesh.search_batch([dict(b) for b in burst])
            ms = mesh._mesh_search
            assert ms.batched_launch_total == 1
            for b, got in zip(burst, out):
                assert isinstance(got, dict), got
                assert got["_plane"] == "mesh_pallas", got["_plane"]
                want = host.search(dict(b))
                # batched members share union tables: hits/aggs exact,
                # scores within the established q_batch tolerance
                assert_parity(got, want, score_tol=1e-5)
            assert ms.agg_fused_query_total == 3
        finally:
            mesh.close()
            host.close()

    def test_ineligible_agg_member_demotes_batch_not_peers(self):
        mesh, host = build_pair("fbad")
        try:
            burst = [
                {"query": {"match": {"body": "t0"}}, "size": 4,
                 "aggs": {"tags": {"terms": {"field": "tag"}}}},
                # sub-aggs: outside the fused envelope — the batch falls
                # to the host rung, every member still serves correctly
                {"query": {"match": {"body": "t1"}}, "size": 4,
                 "aggs": {"tags": {"terms": {"field": "tag"},
                                   "aggs": {"m": {"max": {
                                       "field": "n"}}}}}},
            ]
            out = mesh.search_batch([dict(b) for b in burst])
            for b, got in zip(burst, out):
                assert isinstance(got, dict), got
                want = host.search(dict(b))
                assert got["hits"]["total"] == want["hits"]["total"]
                assert got["aggregations"] == want["aggregations"]
            ms = mesh._mesh_search
            assert ms.agg_host_fallback_by_reason.get("sub_aggs", 0) >= 1
        finally:
            mesh.close()
            host.close()


class TestStructuralFallback:
    def test_fallback_reasons_counted_and_results_exact(self):
        mesh, host = build_pair("ffb")
        try:
            # multi-valued keyword: a doc with two tags
            for idx in (mesh, host):
                idx.index_doc("mv", {"body": "t0 t1", "n": 1,
                                     "price": 1.5, "ts": EPOCH,
                                     "tags": ["red", "blue"]})
                idx.refresh()
            cases = [
                # sub-aggs
                ({"tags": {"terms": {"field": "tag"},
                           "aggs": {"m": {"max": {"field": "n"}}}}},
                 "sub_aggs"),
                # multi-valued keyword column
                ({"mv": {"terms": {"field": "tags"}}}, "multi_valued"),
                # non-integer values for a sum
                ({"p": {"sum": {"field": "price"}}},
                 "values_not_fusable"),
                # calendar interval
                ({"cal": {"date_histogram": {"field": "ts",
                                             "interval": "month"}}},
                 "unsupported_params"),
                # cardinality: not a fused type
                ({"card": {"cardinality": {"field": "tag"}}},
                 "unsupported_agg"),
            ]
            for aggs, reason in cases:
                body = {"query": {"match": {"body": "t0 t1"}}, "size": 4,
                        "aggs": aggs}
                got = mesh.search(dict(body))
                want = host.search(dict(body))
                assert got["aggregations"] == want["aggregations"], aggs
                ms = mesh._mesh_search
                assert ms.agg_host_fallback_by_reason.get(reason), (
                    reason, ms.agg_host_fallback_by_reason)
            assert mesh._mesh_search.agg_fused_query_total == 0
        finally:
            mesh.close()
            host.close()

    def test_disabled_by_setting_falls_back_identically(self):
        mesh, host = build_pair(
            "foff", mesh_extra={"index.search.aggs.fused": "false"})
        try:
            body = {"query": {"match": {"body": "t0"}}, "size": 4,
                    "aggs": {"tags": {"terms": {"field": "tag"}}}}
            got = mesh.search(dict(body))
            want = host.search(dict(body))
            assert got["aggregations"] == want["aggregations"]
            ms = mesh._mesh_search
            assert ms.agg_fused_query_total == 0
            assert ms.agg_host_fallback_by_reason.get("disabled", 0) >= 1
            # dynamic cluster override re-enables without a restart
            mesh.aggs_fused_override = True
            got2 = mesh.search(dict(body, size=5))
            assert got2["aggregations"] == want["aggregations"]
            assert ms.agg_fused_query_total == 1
        finally:
            mesh.close()
            host.close()


class TestPruningExclusion:
    EXTRA = {"search.pallas.pruning.enabled": True,
             "search.pallas.pruning.probe_tiles": 2}

    def test_agg_queries_never_prune(self):
        mesh, host = build_pair("fpx", n_docs=600, mesh_extra=self.EXTRA)
        try:
            plain = mesh.search({"query": {"match": {"body": "t1"}},
                                 "size": 5})
            assert "_pruned" in plain, (
                "pruning sanity: the agg-less twin should serve pruned")
            body = {"query": {"match": {"body": "t1"}}, "size": 5,
                    "aggs": {"tags": {"terms": {"field": "tag"}},
                             "sm": {"sum": {"field": "n"}}}}
            got = mesh.search(dict(body))
            want = host.search(dict(body))
            # aggs force the exhaustive path: exact totals, no pruned
            # marker, buckets byte-identical (docs/PRUNING.md)
            assert "_pruned" not in got
            assert got["_plane"] == "mesh_pallas"
            assert_parity(got, want)
        finally:
            mesh.close()
            host.close()


class TestResilienceInteraction:
    def test_brownout_shed_aggs_contract_unchanged(self):
        mesh, _host = build_pair(
            "fbr", mesh_extra={"search.queue.size": 100})
        try:
            body = {"query": {"match": {"body": "t0"}}, "size": 4,
                    "aggs": {"tags": {"terms": {"field": "tag"}}}}
            qp = QueuePressureScheme(occupancy=90,
                                     indices=["fbr-mesh"]).install()
            try:
                mesh.admission.refresh_level()
                shed = mesh.search(dict(body))
            finally:
                qp.remove()
                mesh.admission.refresh_level()
            assert "aggs" in shed.get("_degraded", [])
            assert "aggregations" not in shed
            # no fused work happened for the shed aggs
            assert mesh._mesh_search.agg_fused_query_total == 0
            healed = mesh.search(dict(body))
            assert "_degraded" not in healed
            assert "aggregations" in healed
        finally:
            mesh.close()

    def test_fused_launch_fault_quarantines_once_host_serves(self):
        mesh, host = build_pair("fqf")
        try:
            body = {"query": {"match": {"body": "t0 t1"}}, "size": 5,
                    "aggs": {"tags": {"terms": {"field": "tag"}},
                             "st": {"stats": {"field": "n"}}}}
            scheme = PlaneFailScheme(planes=["mesh_pallas"]).install()
            try:
                got = mesh.search(dict(body))
            finally:
                scheme.remove()
            want = host.search(dict(body))
            assert got["_plane"] != "mesh_pallas"
            assert got["aggregations"] == want["aggregations"]
            ph = mesh._mesh_search.plane_health
            assert ph.failures_total["mesh_pallas"] == 1
            assert "mesh_pallas" in ph.quarantined()
        finally:
            mesh.close()
            host.close()


class TestLedgerLifecycle:
    def test_doc_values_leak_free_across_merge_and_evict(self):
        acct = memory_accountant()
        mesh, host = build_pair("flg", refreshes=2, n_docs=80)
        try:
            body = {"query": {"match": {"body": "t0"}}, "size": 4,
                    "aggs": {"tags": {"terms": {"field": "tag"}},
                             "sm": {"sum": {"field": "n"}}}}
            got = mesh.search(dict(body))
            assert got["_plane"] == "mesh_pallas"
            mem = acct.stats("flg-mesh")
            assert mem["staged_bytes"]["doc_values"] > 0
            assert any(e["kind"] == "doc_values"
                       for e in mem["staging_events"]), (
                "doc_values staging must emit lifecycle events")
            # force-merge retires the segment set: the executor (and its
            # doc_values columns) rebuild on the next query, leak-free
            mesh.force_merge()
            mesh.refresh()
            got2 = mesh.search(dict(body))
            want = host.search(dict(body))
            assert got2["aggregations"] == want["aggregations"]
            # eviction drops the staged columns; the next query restages
            # (force_evict is global-LRU, so assertions stay per-index —
            # other tests' cold scopes may evict too)
            freed = acct.force_evict(scopes=8)
            assert freed > 0
            got3 = mesh.search(dict(body))
            assert got3["aggregations"] == want["aggregations"]
        finally:
            mesh.close()
            host.close()
        for name in ("flg-mesh", "flg-host"):
            assert acct.staged_bytes(name) == 0, (
                f"doc_values ledger leaked for [{name}] across "
                f"merge/evict cycles")
