"""Nested documents: block-join query semantics, nested/reverse_nested
aggs, inner_hits, nested sort, persistence (ref: index/mapper nested
handling in DocumentParser, index/query/NestedQueryBuilder.java,
search/aggregations/bucket/nested/, search/fetch/subphase/InnerHitsFetchSubPhase)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import ElasticsearchTpuException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def hit_ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


@pytest.fixture()
def users(tmp_path):
    """The canonical nested example: user objects with first/last names."""
    idx = IndexService("users", Settings({"index.number_of_shards": 1}),
                       data_path=str(tmp_path / "users"))
    idx.put_mapping({"properties": {
        "group": {"type": "keyword"},
        "user": {
            "type": "nested",
            "properties": {
                "first": {"type": "text"},
                "last": {"type": "text",
                         "fields": {"keyword": {"type": "keyword"}}},
                "age": {"type": "long"},
            },
        },
    }})
    idx.index_doc("1", {
        "group": "fans",
        "user": [
            {"first": "John", "last": "Smith", "age": 34},
            {"first": "Alice", "last": "White", "age": 28},
        ],
    })
    idx.index_doc("2", {
        "group": "fans",
        "user": [
            {"first": "John", "last": "White", "age": 46},
        ],
    })
    idx.index_doc("3", {"group": "owners"})
    idx.refresh()
    yield idx
    idx.close()


class TestNestedQuery:
    def test_no_cross_object_leakage(self, users):
        """The defining nested semantic: must clauses matching across
        DIFFERENT objects do not match the parent (the pre-block-join
        flattened behavior would return doc 1)."""
        q = {"query": {"nested": {"path": "user", "query": {"bool": {"must": [
            {"match": {"user.first": "john"}},
            {"match": {"user.last": "white"}},
        ]}}}}}
        resp = users.search(q)
        assert hit_ids(resp) == ["2"]

    def test_same_object_match(self, users):
        q = {"query": {"nested": {"path": "user", "query": {"bool": {"must": [
            {"match": {"user.first": "john"}},
            {"match": {"user.last": "smith"}},
        ]}}}}}
        assert hit_ids(users.search(q)) == ["1"]

    def test_single_clause_matches_any_object(self, users):
        q = {"query": {"nested": {"path": "user",
                                  "query": {"match": {"user.first": "john"}}}}}
        assert hit_ids(users.search(q)) == ["1", "2"]

    def test_range_on_nested_numeric(self, users):
        q = {"query": {"nested": {"path": "user",
                                  "query": {"range": {"user.age": {"gte": 40}}}}}}
        assert hit_ids(users.search(q)) == ["2"]

    def test_score_modes(self, users):
        base = {"path": "user", "query": {"match": {"user.first": "john"}}}
        scores = {}
        for mode in ("avg", "sum", "min", "max", "none"):
            resp = users.search(
                {"query": {"nested": dict(base, score_mode=mode)}})
            scores[mode] = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
        # one matching object per parent here: avg == sum == min == max
        assert scores["avg"]["1"] == pytest.approx(scores["sum"]["1"])
        assert scores["min"]["2"] == pytest.approx(scores["max"]["2"])
        assert scores["none"]["1"] == 0.0

    def test_sum_vs_max_multi_object(self, users, tmp_path):
        idx = IndexService("m", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "m"))
        idx.put_mapping({"properties": {"c": {
            "type": "nested", "properties": {"t": {"type": "text"}}}}})
        idx.index_doc("x", {"c": [{"t": "apple"}, {"t": "apple"}]})
        idx.refresh()
        q = lambda m: {"query": {"nested": {
            "path": "c", "query": {"match": {"c.t": "apple"}}, "score_mode": m}}}
        s_sum = idx.search(q("sum"))["hits"]["hits"][0]["_score"]
        s_max = idx.search(q("max"))["hits"]["hits"][0]["_score"]
        s_avg = idx.search(q("avg"))["hits"]["hits"][0]["_score"]
        assert s_sum == pytest.approx(2 * s_max)
        assert s_avg == pytest.approx(s_max)
        idx.close()

    def test_unmapped_path_raises(self, users):
        with pytest.raises(ElasticsearchTpuException):
            users.search({"query": {"nested": {
                "path": "nope", "query": {"match_all": {}}}}})

    def test_ignore_unmapped(self, users):
        resp = users.search({"query": {"nested": {
            "path": "nope", "query": {"match_all": {}},
            "ignore_unmapped": True}}})
        assert resp["hits"]["total"] == 0

    def test_nested_fields_not_searchable_at_root(self, users):
        """Nested object fields are separate docs: a root-level match on
        the nested field path finds nothing (reference behavior)."""
        resp = users.search({"query": {"match": {"user.first": "john"}}})
        assert resp["hits"]["total"] == 0

    def test_in_bool_with_root_filter(self, users):
        q = {"query": {"bool": {
            "must": [{"nested": {"path": "user",
                                 "query": {"match": {"user.first": "john"}}}}],
            "filter": [{"term": {"group": "fans"}}],
        }}}
        assert hit_ids(users.search(q)) == ["1", "2"]

    def test_delete_parent_removes_nested(self, users):
        users.delete_doc("2")
        users.refresh()
        q = {"query": {"nested": {"path": "user", "query": {"bool": {"must": [
            {"match": {"user.first": "john"}},
            {"match": {"user.last": "white"}},
        ]}}}}}
        assert hit_ids(users.search(q)) == []


class TestInnerHits:
    def test_nested_inner_hits(self, users):
        q = {"query": {"nested": {
            "path": "user",
            "query": {"match": {"user.first": "john"}},
            "inner_hits": {},
        }}}
        resp = users.search(q)
        by_id = {h["_id"]: h for h in resp["hits"]["hits"]}
        ih = by_id["1"]["inner_hits"]["user"]["hits"]
        assert ih["total"] == 1
        assert ih["hits"][0]["_nested"] == {"field": "user", "offset": 0}
        assert ih["hits"][0]["_source"]["first"] == "John"

    def test_inner_hits_size_and_name(self, users):
        q = {"query": {"nested": {
            "path": "user",
            "query": {"match_all": {}},
            "inner_hits": {"name": "members", "size": 1},
        }}}
        resp = users.search(q)
        by_id = {h["_id"]: h for h in resp["hits"]["hits"]}
        ih = by_id["1"]["inner_hits"]["members"]["hits"]
        assert ih["total"] == 2
        assert len(ih["hits"]) == 1

    def test_has_child_inner_hits(self, tmp_path):
        idx = IndexService("qa", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "qa"))
        idx.put_mapping({"properties": {
            "j": {"type": "join", "relations": {"q": "a"}},
            "body": {"type": "text"},
        }})
        idx.index_doc("q1", {"j": "q"})
        idx.index_doc("a1", {"j": {"name": "a", "parent": "q1"}, "body": "good answer"})
        idx.index_doc("a2", {"j": {"name": "a", "parent": "q1"}, "body": "bad reply"})
        idx.refresh()
        resp = idx.search({"query": {"has_child": {
            "type": "a", "query": {"match": {"body": "answer"}},
            "inner_hits": {}}}})
        assert hit_ids(resp) == ["q1"]
        ih = resp["hits"]["hits"][0]["inner_hits"]["a"]["hits"]
        assert ih["total"] == 1
        assert ih["hits"][0]["_id"] == "a1"
        idx.close()

    def test_has_parent_inner_hits(self, tmp_path):
        idx = IndexService("qa2", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "qa2"))
        idx.put_mapping({"properties": {
            "j": {"type": "join", "relations": {"q": "a"}},
            "title": {"type": "text"},
        }})
        idx.index_doc("q1", {"j": "q", "title": "trains"})
        idx.index_doc("a1", {"j": {"name": "a", "parent": "q1"}})
        idx.refresh()
        resp = idx.search({"query": {"has_parent": {
            "parent_type": "q", "query": {"match": {"title": "trains"}},
            "inner_hits": {}}}})
        assert hit_ids(resp) == ["a1"]
        ih = resp["hits"]["hits"][0]["inner_hits"]["q"]["hits"]
        assert ih["hits"][0]["_id"] == "q1"
        idx.close()


class TestNestedAggs:
    def test_nested_agg_counts_objects(self, users):
        resp = users.search({"size": 0, "aggs": {
            "u": {"nested": {"path": "user"},
                  "aggs": {"min_age": {"min": {"field": "user.age"}}}}}})
        agg = resp["aggregations"]["u"]
        assert agg["doc_count"] == 3  # 3 nested objects across 2 docs
        assert agg["min_age"]["value"] == 28.0

    def test_nested_agg_respects_query(self, users):
        resp = users.search({
            "size": 0,
            "query": {"term": {"group": "fans"}},
            "aggs": {"u": {"nested": {"path": "user"},
                           "aggs": {"avg_age": {"avg": {"field": "user.age"}}}}},
        })
        agg = resp["aggregations"]["u"]
        assert agg["doc_count"] == 3
        assert agg["avg_age"]["value"] == pytest.approx((34 + 28 + 46) / 3)

    def test_reverse_nested(self, users):
        resp = users.search({"size": 0, "aggs": {"u": {
            "nested": {"path": "user"},
            "aggs": {"johns": {
                "filter": {"match": {"user.first": "john"}},
                "aggs": {"back": {
                    "reverse_nested": {},
                    "aggs": {"groups": {"terms": {"field": "group"}}},
                }},
            }},
        }}})
        johns = resp["aggregations"]["u"]["johns"]
        assert johns["doc_count"] == 2  # two john objects
        back = johns["back"]
        assert back["doc_count"] == 2  # two parent docs
        buckets = {b["key"]: b["doc_count"] for b in back["groups"]["buckets"]}
        assert buckets == {"fans": 2}

    def test_reverse_nested_outside_nested_fails(self, users):
        with pytest.raises(ElasticsearchTpuException):
            users.search({"size": 0, "aggs": {
                "bad": {"reverse_nested": {}, "aggs": {}}}})

    def test_nested_terms_agg(self, users):
        resp = users.search({"size": 0, "aggs": {"u": {
            "nested": {"path": "user"},
            "aggs": {"lasts": {"terms": {"field": "user.last.keyword"}}},
        }}})
        buckets = {b["key"]: b["doc_count"]
                   for b in resp["aggregations"]["u"]["lasts"]["buckets"]}
        assert buckets == {"White": 2, "Smith": 1}


class TestNestedSort:
    def test_sort_asc_by_nested_min(self, users):
        resp = users.search({
            "query": {"nested": {"path": "user",
                                 "query": {"exists": {"field": "user.age"}}}},
            "sort": [{"user.age": {"order": "asc"}}],
        })
        ids = [h["_id"] for h in resp["hits"]["hits"]]
        assert ids == ["1", "2"]  # min ages 28 vs 46

    def test_sort_desc_by_nested_max(self, users):
        resp = users.search({
            "query": {"nested": {"path": "user",
                                 "query": {"exists": {"field": "user.age"}}}},
            "sort": [{"user.age": {"order": "desc"}}],
        })
        ids = [h["_id"] for h in resp["hits"]["hits"]]
        assert ids == ["2", "1"]  # max ages 46 vs 34


class TestNestedPersistence:
    def test_flush_and_reopen(self, tmp_path):
        path = str(tmp_path / "p")
        idx = IndexService("p", Settings({"index.number_of_shards": 1}),
                           data_path=path)
        idx.put_mapping({"properties": {"c": {
            "type": "nested",
            "properties": {"t": {"type": "text"}, "n": {"type": "long"}}}}})
        idx.index_doc("1", {"c": [{"t": "alpha", "n": 1}, {"t": "beta", "n": 2}]})
        idx.index_doc("2", {"c": [{"t": "alpha beta", "n": 3}]})
        idx.refresh()
        idx.flush()
        idx.close()

        idx2 = IndexService("p", Settings({"index.number_of_shards": 1}),
                            data_path=path)
        idx2.put_mapping({"properties": {"c": {
            "type": "nested",
            "properties": {"t": {"type": "text"}, "n": {"type": "long"}}}}})
        q = {"query": {"nested": {"path": "c", "query": {"bool": {"must": [
            {"match": {"c.t": "alpha"}}, {"match": {"c.t": "beta"}},
        ]}}}}}
        assert hit_ids(idx2.search(q)) == ["2"]
        resp = idx2.search({"size": 0, "aggs": {"cc": {
            "nested": {"path": "c"},
            "aggs": {"s": {"sum": {"field": "c.n"}}}}}})
        assert resp["aggregations"]["cc"]["s"]["value"] == 6.0
        idx2.close()

    def test_force_merge_preserves_nested(self, users):
        users.index_doc("4", {"group": "fans",
                              "user": [{"first": "Zoe", "last": "Smith", "age": 20}]})
        users.refresh()
        users.force_merge()
        q = {"query": {"nested": {"path": "user", "query": {"bool": {"must": [
            {"match": {"user.first": "zoe"}},
            {"match": {"user.last": "smith"}},
        ]}}}}}
        assert hit_ids(users.search(q)) == ["4"]


@pytest.fixture()
def deep(tmp_path):
    """Two-level nesting: driver -> vehicle (the reference's multi-level
    nested example)."""
    idx = IndexService("deep", Settings({"index.number_of_shards": 1}),
                       data_path=str(tmp_path / "deep"))
    idx.put_mapping({"properties": {"driver": {
        "type": "nested",
        "properties": {
            "last_name": {"type": "text"},
            "vehicle": {
                "type": "nested",
                "properties": {
                    "make": {"type": "text"},
                    "model": {"type": "text"},
                },
            },
        },
    }}})
    idx.index_doc("1", {"driver": {
        "last_name": "McQueen",
        "vehicle": [{"make": "Powell", "model": "Canyonero"},
                    {"make": "Miller", "model": "Meteor"}],
    }})
    idx.index_doc("2", {"driver": {
        "last_name": "Hudson",
        "vehicle": [{"make": "Mifune", "model": "Mach Five"},
                    {"make": "Miller", "model": "Meteor"}],
    }})
    idx.refresh()
    yield idx
    idx.close()


class TestNestedInNested:
    def test_query_two_levels(self, deep):
        q = {"query": {"nested": {"path": "driver", "query": {"nested": {
            "path": "driver.vehicle",
            "query": {"bool": {"must": [
                {"match": {"driver.vehicle.make": "powell"}},
                {"match": {"driver.vehicle.model": "canyonero"}},
            ]}},
        }}}}}
        assert hit_ids(deep.search(q)) == ["1"]

    def test_query_inner_path_directly(self, deep):
        q = {"query": {"nested": {
            "path": "driver.vehicle",
            "query": {"match": {"driver.vehicle.make": "mifune"}},
        }}}
        assert hit_ids(deep.search(q)) == ["2"]

    def test_nested_agg_in_nested_agg(self, deep):
        resp = deep.search({"size": 0, "aggs": {"d": {
            "nested": {"path": "driver"},
            "aggs": {"v": {"nested": {"path": "driver.vehicle"}}},
        }}})
        assert resp["aggregations"]["d"]["doc_count"] == 2
        assert resp["aggregations"]["d"]["v"]["doc_count"] == 4

    def test_root_level_inner_path_agg(self, deep):
        resp = deep.search({"size": 0, "aggs": {"v": {
            "nested": {"path": "driver.vehicle"}}}})
        assert resp["aggregations"]["v"]["doc_count"] == 4


class TestNestedParsing:
    def test_null_array_element_skipped(self, tmp_path):
        idx = IndexService("n", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "n"))
        idx.put_mapping({"properties": {"c": {
            "type": "nested", "properties": {"t": {"type": "text"}}}}})
        idx.index_doc("1", {"c": [None, {"t": "kept"}]})
        idx.refresh()
        assert hit_ids(idx.search({"query": {"nested": {
            "path": "c", "query": {"match": {"c.t": "kept"}}}}})) == ["1"]
        resp = idx.search({"size": 0, "aggs": {"cc": {"nested": {"path": "c"}}}})
        assert resp["aggregations"]["cc"]["doc_count"] == 1
        idx.close()

    def test_include_in_parent_no_double_count_inner(self, tmp_path):
        idx = IndexService("i", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "i"))
        idx.put_mapping({"properties": {"a": {
            "type": "nested", "include_in_parent": True,
            "properties": {
                "x": {"type": "text"},
                "b": {"type": "nested", "properties": {"y": {"type": "text"}}},
            }}}})
        idx.index_doc("1", {"a": [{"x": "v", "b": [{"y": "w"}]}]})
        idx.refresh()
        resp = idx.search({"size": 0, "aggs": {"bb": {
            "nested": {"path": "a.b"}}}})
        assert resp["aggregations"]["bb"]["doc_count"] == 1
        q = {"query": {"nested": {"path": "a.b",
                                  "query": {"match": {"a.b.y": "w"}},
                                  "score_mode": "sum", "inner_hits": {}}}}
        resp = idx.search(q)
        ih = resp["hits"]["hits"][0]["inner_hits"]["a.b"]["hits"]
        assert ih["total"] == 1
        idx.close()


class TestNestedCorruptionDetection:
    def test_parent_of_corruption_detected(self, tmp_path):
        import glob
        import os

        from elasticsearch_tpu.common.integrity import integrity_service
        from elasticsearch_tpu.index.store import MARKER_PREFIX

        path = str(tmp_path / "c")
        idx = IndexService("c", Settings({"index.number_of_shards": 1}),
                           data_path=path)
        idx.put_mapping({"properties": {"c": {
            "type": "nested", "properties": {"t": {"type": "text"}}}}})
        idx.index_doc("1", {"c": [{"t": "alpha"}]})
        idx.refresh()
        idx.flush()
        idx.close()

        (target,) = glob.glob(os.path.join(path, "**", "parent_of.npy"),
                              recursive=True)
        with open(target, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            byte = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([byte[0] ^ 0xFF]))

        # boot over the corrupt bytes QUARANTINES the copy instead of
        # crashing index open (docs/RESILIENCE.md "Data integrity"):
        # detection is still mandatory — counted at the load site, a
        # durable corrupted_* marker lands in the shard dir, and every
        # query fails loudly rather than serving silent empty hits
        before = integrity_service().stats()[
            "corruption_detected_by_site"]["load"]
        reopened = IndexService("c", Settings({"index.number_of_shards": 1}),
                                data_path=path)
        try:
            after = integrity_service().stats()[
                "corruption_detected_by_site"]["load"]
            assert after == before + 1
            assert reopened.shards[0].store_corrupted
            assert reopened.shards[0].engine.store.corruption_markers()
            assert any(f.startswith(MARKER_PREFIX)
                       for f in os.listdir(os.path.join(path, "0", "index")))
            from elasticsearch_tpu.common.errors import (
                SearchPhaseExecutionException,
            )

            with pytest.raises(SearchPhaseExecutionException):
                reopened.search({"query": {"match_all": {}}})
        finally:
            reopened.close()


class TestIncludeInRoot:
    def test_include_in_root_copies_fields(self, tmp_path):
        idx = IndexService("r", Settings({"index.number_of_shards": 1}),
                           data_path=str(tmp_path / "r"))
        idx.put_mapping({"properties": {"c": {
            "type": "nested", "include_in_root": True,
            "properties": {"t": {"type": "text"}}}}})
        idx.index_doc("1", {"c": [{"t": "hello"}]})
        idx.refresh()
        # root-level query now matches (flattened copy)...
        assert hit_ids(idx.search({"query": {"match": {"c.t": "hello"}}})) == ["1"]
        # ...and nested semantics still hold
        assert hit_ids(idx.search({"query": {"nested": {
            "path": "c", "query": {"match": {"c.t": "hello"}}}}})) == ["1"]
        idx.close()
