"""Search templates, termvectors, rollover, shrink, percolate, hot_threads."""

import pytest

from elasticsearch_tpu.client import Client
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def client():
    node = Node(Settings.EMPTY)
    c = Client(node)
    yield c
    node.close()


def ok(resp):
    status, payload = resp
    assert status in (200, 201), payload
    return payload


class TestSearchTemplates:
    def test_inline_template(self, client):
        client.index("idx", "1", {"color": "red"}, refresh="true")
        client.index("idx", "2", {"color": "blue"}, refresh="true")
        r = ok(client.perform("POST", "/idx/_search/template", body={
            "source": {"query": {"term": {"color": "{{c}}"}}},
            "params": {"c": "red"},
        }))
        assert r["hits"]["total"] == 1

    def test_stored_template(self, client):
        client.index("idx", "1", {"n": 5}, refresh="true")
        ok(client.perform("PUT", "/_scripts/tmpl1", body={
            "script": {"lang": "mustache",
                       "source": '{"query": {"range": {"n": {"gte": {{min}}}}}}'},
        }))
        r = ok(client.perform("POST", "/idx/_search/template", body={
            "id": "tmpl1", "params": {"min": 3},
        }))
        assert r["hits"]["total"] == 1

    def test_render(self, client):
        r = ok(client.perform("POST", "/_render/template", body={
            "source": {"query": {"match": {"f": "{{v}}"}}},
            "params": {"v": "x y"},
        }))
        assert r["template_output"] == {"query": {"match": {"f": "x y"}}}

    def test_tojson(self, client):
        r = ok(client.perform("POST", "/_render/template", body={
            "source": '{"query": {"terms": {"tag": {{#toJson}}tags{{/toJson}}}}}',
            "params": {"tags": ["a", "b"]},
        }))
        assert r["template_output"]["query"]["terms"]["tag"] == ["a", "b"]


class TestTermvectors:
    def test_termvectors(self, client):
        client.index("idx", "1", {"body": "quick quick fox"}, refresh="true")
        r = ok(client.perform("GET", "/idx/_termvectors/1"))
        assert r["found"]
        terms = r["term_vectors"]["body"]["terms"]
        assert terms["quick"]["term_freq"] == 2
        assert [t["position"] for t in terms["quick"]["tokens"]] == [0, 1]
        assert terms["fox"]["doc_freq"] == 1

    def test_missing_doc(self, client):
        client.index("idx", "1", {"a": "x"}, refresh="true")
        r = ok(client.perform("GET", "/idx/_termvectors/404"))
        assert not r["found"]


class TestRollover:
    def test_rollover_by_docs(self, client):
        ok(client.perform("PUT", "/logs-000001", body={"aliases": {"logs": {}}}))
        for i in range(3):
            client.index("logs", str(i), {"n": i}, refresh="true")
        # condition not met
        r = ok(client.perform("POST", "/logs/_rollover", body={
            "conditions": {"max_docs": 100}}))
        assert not r["rolled_over"]
        # condition met
        r = ok(client.perform("POST", "/logs/_rollover", body={
            "conditions": {"max_docs": 2}}))
        assert r["rolled_over"]
        assert r["new_index"] == "logs-000002"
        # alias moved: writes go to the new index
        client.index("logs", "x", {"n": 9}, refresh="true")
        status, sr = client.search("logs-000002", {})
        assert sr["hits"]["total"] == 1

    def test_dry_run(self, client):
        ok(client.perform("PUT", "/logs-000001", body={"aliases": {"logs": {}}}))
        r = ok(client.perform("POST", "/logs/_rollover", {"dry_run": ""},
                              {"conditions": {"max_docs": 0}}))
        assert not r["rolled_over"] and r["dry_run"]


class TestShrink:
    def test_shrink_to_one_shard(self, client):
        ok(client.perform("PUT", "/big", body={
            "settings": {"index": {"number_of_shards": 4}}}))
        for i in range(20):
            client.index("big", str(i), {"n": i})
        client.perform("POST", "/big/_refresh")
        r = ok(client.perform("POST", "/big/_shrink/small", body={
            "settings": {"index": {"number_of_shards": 1}}}))
        assert r["acknowledged"]
        status, sr = client.search("small", {"size": 0})
        assert sr["hits"]["total"] == 20
        assert sr["_shards"]["total"] == 1


class TestPercolate:
    def test_percolate_matches_stored_queries(self, client):
        ok(client.perform("PUT", "/queries", body={
            "mappings": {"properties": {
                "query": {"type": "percolator"},
                "body": {"type": "text"},
            }},
        }))
        client.index("queries", "q1", {"query": {"match": {"body": "fox"}}})
        client.index("queries", "q2", {"query": {"match": {"body": "turtle"}}})
        client.index("queries", "q3", {"query": {"range": {"price": {"gte": 100}}}})
        client.perform("POST", "/queries/_refresh")
        status, r = client.search("queries", {"query": {"percolate": {
            "field": "query",
            "document": {"body": "a quick fox jumped", "price": 150},
        }}})
        got = {h["_id"] for h in r["hits"]["hits"]}
        assert got == {"q1", "q3"}


class TestHotThreads:
    def test_hot_threads_dump(self, client):
        status, text = client.perform("GET", "/_nodes/hot_threads")
        assert status == 200
        assert "thread id" in text
