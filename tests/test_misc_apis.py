"""Search templates, termvectors, rollover, shrink, percolate, hot_threads."""

import pytest

from elasticsearch_tpu.client import Client
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def client():
    node = Node(Settings.EMPTY)
    c = Client(node)
    yield c
    node.close()


def ok(resp):
    status, payload = resp
    assert status in (200, 201), payload
    return payload


class TestSearchTemplates:
    def test_inline_template(self, client):
        client.index("idx", "1", {"color": "red"}, refresh="true")
        client.index("idx", "2", {"color": "blue"}, refresh="true")
        r = ok(client.perform("POST", "/idx/_search/template", body={
            "source": {"query": {"term": {"color": "{{c}}"}}},
            "params": {"c": "red"},
        }))
        assert r["hits"]["total"] == 1

    def test_stored_template(self, client):
        client.index("idx", "1", {"n": 5}, refresh="true")
        ok(client.perform("PUT", "/_scripts/tmpl1", body={
            "script": {"lang": "mustache",
                       "source": '{"query": {"range": {"n": {"gte": {{min}}}}}}'},
        }))
        r = ok(client.perform("POST", "/idx/_search/template", body={
            "id": "tmpl1", "params": {"min": 3},
        }))
        assert r["hits"]["total"] == 1

    def test_render(self, client):
        r = ok(client.perform("POST", "/_render/template", body={
            "source": {"query": {"match": {"f": "{{v}}"}}},
            "params": {"v": "x y"},
        }))
        assert r["template_output"] == {"query": {"match": {"f": "x y"}}}

    def test_tojson(self, client):
        r = ok(client.perform("POST", "/_render/template", body={
            "source": '{"query": {"terms": {"tag": {{#toJson}}tags{{/toJson}}}}}',
            "params": {"tags": ["a", "b"]},
        }))
        assert r["template_output"]["query"]["terms"]["tag"] == ["a", "b"]


class TestTermvectors:
    def test_termvectors(self, client):
        client.index("idx", "1", {"body": "quick quick fox"}, refresh="true")
        r = ok(client.perform("GET", "/idx/_termvectors/1"))
        assert r["found"]
        terms = r["term_vectors"]["body"]["terms"]
        assert terms["quick"]["term_freq"] == 2
        assert [t["position"] for t in terms["quick"]["tokens"]] == [0, 1]
        assert terms["fox"]["doc_freq"] == 1

    def test_missing_doc(self, client):
        client.index("idx", "1", {"a": "x"}, refresh="true")
        r = ok(client.perform("GET", "/idx/_termvectors/404"))
        assert not r["found"]


class TestRollover:
    def test_rollover_by_docs(self, client):
        ok(client.perform("PUT", "/logs-000001", body={"aliases": {"logs": {}}}))
        for i in range(3):
            client.index("logs", str(i), {"n": i}, refresh="true")
        # condition not met
        r = ok(client.perform("POST", "/logs/_rollover", body={
            "conditions": {"max_docs": 100}}))
        assert not r["rolled_over"]
        # condition met
        r = ok(client.perform("POST", "/logs/_rollover", body={
            "conditions": {"max_docs": 2}}))
        assert r["rolled_over"]
        assert r["new_index"] == "logs-000002"
        # alias moved: writes go to the new index
        client.index("logs", "x", {"n": 9}, refresh="true")
        status, sr = client.search("logs-000002", {})
        assert sr["hits"]["total"] == 1

    def test_dry_run(self, client):
        ok(client.perform("PUT", "/logs-000001", body={"aliases": {"logs": {}}}))
        r = ok(client.perform("POST", "/logs/_rollover", {"dry_run": ""},
                              {"conditions": {"max_docs": 0}}))
        assert not r["rolled_over"] and r["dry_run"]


class TestShrink:
    def test_shrink_to_one_shard(self, client):
        ok(client.perform("PUT", "/big", body={
            "settings": {"index": {"number_of_shards": 4}}}))
        for i in range(20):
            client.index("big", str(i), {"n": i})
        client.perform("POST", "/big/_refresh")
        r = ok(client.perform("POST", "/big/_shrink/small", body={
            "settings": {"index": {"number_of_shards": 1}}}))
        assert r["acknowledged"]
        status, sr = client.search("small", {"size": 0})
        assert sr["hits"]["total"] == 20
        assert sr["_shards"]["total"] == 1


class TestPercolate:
    def test_percolate_matches_stored_queries(self, client):
        ok(client.perform("PUT", "/queries", body={
            "mappings": {"properties": {
                "query": {"type": "percolator"},
                "body": {"type": "text"},
            }},
        }))
        client.index("queries", "q1", {"query": {"match": {"body": "fox"}}})
        client.index("queries", "q2", {"query": {"match": {"body": "turtle"}}})
        client.index("queries", "q3", {"query": {"range": {"price": {"gte": 100}}}})
        client.perform("POST", "/queries/_refresh")
        status, r = client.search("queries", {"query": {"percolate": {
            "field": "query",
            "document": {"body": "a quick fox jumped", "price": 150},
        }}})
        got = {h["_id"] for h in r["hits"]["hits"]}
        assert got == {"q1", "q3"}


class TestHotThreads:
    def test_hot_threads_dump(self, client):
        status, text = client.perform("GET", "/_nodes/hot_threads")
        assert status == 200
        assert "thread id" in text


class TestClusterReroute:
    """_cluster/reroute is real: commands parse + apply against the
    routing table via cluster/allocation.py, dry_run previews without
    committing, and the RESULTING state comes back (VERDICT Weak 5: the
    old handler returned a hardcoded ack — an API that lies)."""

    def test_empty_reroute_returns_state(self, client):
        client.perform("PUT", "/ridx", body={
            "settings": {"index": {"number_of_shards": 2,
                                   "number_of_replicas": 1}}})
        r = ok(client.perform("POST", "/_cluster/reroute"))
        assert r["acknowledged"] is True
        shards = r["state"]["routing_table"]["indices"]["ridx"]["shards"]
        assert set(shards) == {"0", "1"}
        for copies in shards.values():
            primaries = [c for c in copies if c["primary"]]
            assert len(primaries) == 1
            assert primaries[0]["state"] == "STARTED"

    def test_cancel_primary_requires_allow_primary(self, client):
        client.perform("PUT", "/ridx2", body={
            "settings": {"index": {"number_of_shards": 1}}})
        ok(client.perform("POST", "/_cluster/reroute"))
        node_id = next(iter(
            client.node.cluster_service.state.nodes))
        status, payload = client.perform(
            "POST", "/_cluster/reroute",
            body={"commands": [{"cancel": {
                "index": "ridx2", "shard": 0, "node": node_id}}]})
        assert status == 400
        assert "allow_primary" in str(payload)

    def test_unknown_command_and_index_rejected(self, client):
        status, payload = client.perform(
            "POST", "/_cluster/reroute",
            body={"commands": [{"frobnicate": {"index": "x", "shard": 0}}]})
        assert status == 400
        client.perform("PUT", "/ridx3", body={})
        status, payload = client.perform(
            "POST", "/_cluster/reroute",
            body={"commands": [{"move": {
                "index": "nope", "shard": 0,
                "from_node": "a", "to_node": "b"}}]})
        assert status == 400

    def test_dry_run_does_not_commit(self, client):
        client.perform("PUT", "/ridx4", body={})
        before = client.node.cluster_service.state.version
        r = ok(client.perform("POST", "/_cluster/reroute",
                              params={"dry_run": "true"}))
        assert r["acknowledged"] is True
        assert "ridx4" in r["state"]["routing_table"]["indices"]
        assert client.node.cluster_service.state.version == before

    def test_explain_lists_command_decisions(self, client):
        client.perform("PUT", "/ridx5", body={
            "settings": {"index": {"number_of_shards": 1,
                                   "number_of_replicas": 1}}})
        # allocate_replica on the only node is rejected (copy exists) —
        # validation is per the reference's decider chain
        node_id = next(iter(client.node.cluster_service.state.nodes))
        ok(client.perform("POST", "/_cluster/reroute"))
        status, payload = client.perform(
            "POST", "/_cluster/reroute", params={"explain": "true"},
            body={"commands": [{"allocate_replica": {
                "index": "ridx5", "shard": 0, "node": node_id}}]})
        assert status == 400  # same-shard decider: copy already there
        r = ok(client.perform("POST", "/_cluster/reroute",
                              params={"explain": "true"}))
        assert r.get("explanations") == []

    def test_move_relocation_lifecycle(self):
        """A move keeps source (RELOCATING) + target (INITIALIZING,
        inheriting the primary flag) through normalization, and the next
        allocation retires the source once the target starts — review
        finding: the normalizer used to cancel the target immediately,
        making move a silent no-op."""
        from elasticsearch_tpu.cluster import allocation as alloc
        from elasticsearch_tpu.cluster.state import (
            IndexMetadata,
            ShardRoutingState,
        )

        meta = {"i": IndexMetadata("i", Settings({
            "index.number_of_shards": 1,
            "index.number_of_replicas": 0}), {})}
        table = alloc.allocate(meta, ["n1", "n2"])
        (c,) = table["i"][0]
        c.state = ShardRoutingState.STARTED
        src = c.node_id
        dst = "n2" if src == "n1" else "n1"
        alloc.apply_command(table, meta, {"n1": "n1", "n2": "n2"},
                            "move", {"index": "i", "shard": 0,
                                     "from_node": src, "to_node": dst})
        t2 = alloc.allocate(meta, ["n1", "n2"], previous=table)
        assert len(t2["i"][0]) == 2  # move in progress: source + target
        assert {x.state for x in t2["i"][0]} == {
            ShardRoutingState.RELOCATING, ShardRoutingState.INITIALIZING}
        for x in t2["i"][0]:
            if x.node_id == dst:
                assert x.primary  # target inherits the primary flag
                x.state = ShardRoutingState.STARTED
        t3 = alloc.allocate(meta, ["n1", "n2"], previous=t2)
        (final,) = t3["i"][0]
        assert (final.node_id, final.primary, final.state) == (
            dst, True, ShardRoutingState.STARTED)

    def test_routing_table_tracks_index_lifecycle(self, client):
        """After a committed reroute the routing table must keep
        following metadata: new indices appear, deleted ones drop
        (review finding: the snapshot used to freeze)."""
        client.perform("PUT", "/rlife1", body={})
        ok(client.perform("POST", "/_cluster/reroute"))
        client.perform("PUT", "/rlife2", body={})
        client.perform("DELETE", "/rlife1")
        status, payload = client.perform("GET", "/_cluster/state")
        assert status == 200
        indices = payload["routing_table"]["indices"]
        assert "rlife2" in indices
        assert "rlife1" not in indices

    def test_replica_move_does_not_retire_source_early(self):
        """With 2+ replicas, a started same-role PEER must not retire a
        RELOCATING source whose own target is still recovering (review
        finding: the retire matcher needs the explicit relocating_to
        link, not any started copy)."""
        from elasticsearch_tpu.cluster import allocation as alloc
        from elasticsearch_tpu.cluster.state import (
            IndexMetadata,
            ShardRoutingState,
        )

        meta = {"i": IndexMetadata("i", Settings({
            "index.number_of_shards": 1,
            "index.number_of_replicas": 2}), {})}
        nodes = ["n1", "n2", "n3", "n4"]
        table = alloc.allocate(meta, nodes)
        for c in table["i"][0]:
            c.state = ShardRoutingState.STARTED
        src = next(c for c in table["i"][0] if not c.primary)
        used = {c.node_id for c in table["i"][0]}
        dst = next(n for n in nodes if n not in used)
        alloc.apply_command(table, meta, {n: n for n in nodes}, "move",
                            {"index": "i", "shard": 0,
                             "from_node": src.node_id, "to_node": dst})
        t2 = alloc.allocate(meta, nodes, previous=table)
        # the other STARTED replica must NOT have retired the source
        by_node = {c.node_id: c for c in t2["i"][0]}
        assert src.node_id in by_node
        assert by_node[src.node_id].state == ShardRoutingState.RELOCATING
        assert by_node[dst].state == ShardRoutingState.INITIALIZING
        # target starts -> NOW the source retires
        by_node[dst].state = ShardRoutingState.STARTED
        t3 = alloc.allocate(meta, nodes, previous=t2)
        nodes_after = {c.node_id for c in t3["i"][0]}
        assert src.node_id not in nodes_after
        assert dst in nodes_after
        assert len(t3["i"][0]) == 3  # primary + 2 replicas
