"""DeviceMemoryAccountant (ISSUE 9, docs/OBSERVABILITY.md): the exact
HBM staging ledger, lifecycle events, restage amplification, and the
budget breaker's LRU-evict → demote (never error) contract.

Mirrors the reference's HierarchyCircuitBreakerService accounting-child
tests — but the scarce resource here is device staging, so the ledger
asserts EXACTNESS (per-kind sums == total, close returns to baseline)
rather than heuristic estimates.
"""

import threading

import numpy as np
import pytest

from elasticsearch_tpu.common.memory import (
    KIND_LIVE_MASK,
    KIND_POSTINGS_RAW,
    KIND_SCALE_NORM,
    KINDS,
    DeviceMemoryAccountant,
    memory_accountant,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService

MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "n": {"type": "integer"},
}}


def _entry_sum(acct):
    """Recompute the ledger total from the per-kind map — the invariant
    partner of the incrementally-tracked staged_bytes()."""
    return sum(acct.staged_bytes_by_kind().values())


@pytest.fixture()
def acct():
    """A private accountant instance; every test must leave it balanced
    (register/release net zero) so the shared breaker mirror is clean."""
    a = DeviceMemoryAccountant()
    yield a
    # drain whatever the test left so the accounting-breaker mirror
    # returns to its pre-test estimate
    for index in {k[0] for k in a._entries}:
        a.release_index(index)
    assert a.staged_bytes() == 0


@pytest.fixture()
def ledger_leak_check():
    """The ISSUE 9 leak-check fixture: the PROCESS accountant's staged
    bytes must return EXACTLY to baseline once the test's indices close."""
    acct = memory_accountant()
    base = acct.staged_bytes()
    yield acct
    assert acct.staged_bytes() == base, (
        f"device-memory ledger leaked: {acct.staged_bytes()} != {base} "
        f"baseline after index close")


def _mk_index(name, extra=None, docs=40, shards=2):
    settings = {"index.number_of_shards": shards,
                "index.refresh_interval": -1}
    settings.update(extra or {})
    idx = IndexService(name, Settings(settings), mapping=MAPPING)
    rng = np.random.RandomState(11)
    vocab = [f"w{i}" for i in range(8)]
    for d in range(docs):
        idx.index_doc(str(d), {
            "body": " ".join(vocab[rng.randint(len(vocab))]
                             for _ in range(6)),
            "n": d})
    idx.refresh()
    return idx


class TestLedgerExactness:
    def test_per_kind_sums_to_total(self, acct):
        acct.register("i", "s1", KIND_POSTINGS_RAW, "t1", 100)
        acct.register("i", "s1", KIND_LIVE_MASK, "t2", 30)
        acct.register("i", "s2", KIND_SCALE_NORM, "t3", 7)
        by_kind = acct.staged_bytes_by_kind()
        assert sum(by_kind.values()) == acct.staged_bytes() == 137
        assert by_kind[KIND_POSTINGS_RAW] == 100
        assert set(by_kind) == set(KINDS)
        assert acct.staged_bytes("i") == 137
        assert acct.staged_bytes("other") == 0

    def test_reregister_replaces_not_leaks(self, acct):
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100)
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 60,
                      reason="refresh")
        assert acct.staged_bytes() == 60
        assert acct.staging_events[-1]["reason"] == "refresh"

    def test_inplace_initial_reclassified_as_restage(self, acct):
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100)
        # a call site that says "initial" while bytes are already live
        # is a restage — the amplification numerator must see it
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100)
        assert acct.staging_events[-1]["reason"] == "probe"

    def test_restage_after_release_is_probe(self, acct):
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100)
        acct.release_scope("i", "s")
        assert acct.staged_bytes() == 0
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100)
        assert acct.staging_events[-1]["reason"] == "probe"

    def test_release_index_clears_history(self, acct):
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100)
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 100,
                      reason="refresh")
        assert acct.stats("i")["restaged_bytes_total"] == 100
        acct.release_index("i")
        assert acct.staged_bytes("i") == 0
        assert acct.stats("i")["restaged_bytes_total"] == 0
        # post-delete re-create: a fresh "initial" is initial again
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 50)
        assert acct.staging_events[-1]["reason"] == "initial"

    def test_event_ring_bounded(self, acct):
        cap = DeviceMemoryAccountant.MAX_EVENTS
        for i in range(cap + 10):
            acct.register("i", "s", KIND_POSTINGS_RAW, f"t{i}", 1)
        assert len(acct.staging_events) == cap
        assert acct.events_dropped == 10
        assert acct.staged_bytes() == cap + 10

    def test_restage_amplification(self, acct):
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 1000)
        st = acct.stats("i")
        assert st["bytes_logically_changed_total"] == 1000
        assert st["restage_amplification"] == 0.0
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 1000,
                      reason="delete_invalidation")
        acct.note_logical_change("i", 100)
        st = acct.stats("i")
        assert st["restaged_bytes_total"] == 1000
        assert st["bytes_logically_changed_total"] == 1100
        assert st["restage_amplification"] == round(1000 / 1100, 4)

    def test_quiet_register_skips_events_and_amplification(self, acct):
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 64, quiet=True)
        assert acct.staged_bytes() == 64
        assert not acct.staging_events
        assert acct.stats("i")["bytes_logically_changed_total"] == 0


class TestBudgetBreaker:
    def test_lru_evicts_coldest_first(self, acct):
        dropped = []
        for name, age in (("cold", 3), ("warm", 2), ("hot", 1)):
            acct.register("i", name, KIND_POSTINGS_RAW, "t", 100,
                          evict=lambda n=name: dropped.append(n))
        acct.touch("i", "warm")
        acct.touch("i", "hot")  # LRU order now: cold < warm < hot
        acct.budget_bytes = 300
        assert acct.try_reserve("i", 100)  # needs 100: evicts cold only
        assert dropped == ["cold"]
        assert acct.staged_bytes() == 200
        assert acct.evictions_total == 1
        assert acct.evicted_bytes_total == 100
        assert acct.eviction_events[-1]["segment"] == "cold"

    def test_denial_when_nothing_evictable(self, acct):
        acct.register("i", "pinned", KIND_POSTINGS_RAW, "t", 90)
        acct.budget_bytes = 100
        assert not acct.try_reserve("i", 50)
        assert acct.budget_denials_total == 1
        assert acct.staged_bytes() == 90  # nothing was dropped

    def test_exclude_scope_protects_the_stager(self, acct):
        acct.register("i", "me", KIND_POSTINGS_RAW, "t", 80,
                      evict=lambda: None)
        acct.budget_bytes = 100
        # the only evictable scope is the one asking: denied, not evicted
        assert not acct.try_reserve("i", 80, exclude_scope="me")
        assert acct.staged_bytes() == 80

    def test_zero_budget_is_unlimited(self, acct):
        assert acct.try_reserve("i", 10**15)
        assert acct.budget_denials_total == 0

    def test_set_budget_evicts_immediately_and_mirrors_limit(self, acct):
        breaker = acct._accounting_breaker()
        prev_limit = breaker.limit_bytes
        try:
            acct.register("i", "s", KIND_POSTINGS_RAW, "t", 500,
                          evict=lambda: None)
            acct.set_budget(200)
            assert breaker.limit_bytes == 200
            assert acct.staged_bytes() == 0  # over budget: evicted now
            assert acct.evictions_total == 1
        finally:
            acct.set_budget(prev_limit)

    def test_breaker_mirror_tracks_ledger(self, acct):
        breaker = acct._accounting_breaker()
        before = breaker.used_bytes
        acct.register("i", "s", KIND_POSTINGS_RAW, "t", 4096)
        assert breaker.used_bytes == before + 4096
        acct.release_scope("i", "s")
        assert breaker.used_bytes == before


class TestServiceLeakCheck:
    """Every staging site registers; close/delete returns the ledger
    EXACTLY to baseline (the acceptance-criteria leak check)."""

    @pytest.fixture(autouse=True)
    def _kernel(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")

    def test_close_returns_to_baseline(self, ledger_leak_check):
        acct = ledger_leak_check
        idx = _mk_index("dmleak")
        try:
            idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
            assert acct.staged_bytes("dmleak") > 0
            st = idx.search_stats()["memory"]
            assert (st["staged_bytes_total"]
                    == sum(st["staged_bytes"].values()) > 0)
        finally:
            idx.close()
        assert acct.staged_bytes("dmleak") == 0

    def test_force_merge_restage_cycle(self, ledger_leak_check):
        acct = ledger_leak_check
        idx = _mk_index("dmmerge", shards=1)
        try:
            # second segment so the merge actually replaces something
            for d in range(100, 120):
                idx.index_doc(str(d), {"body": "w1 w2", "n": d})
            idx.refresh()
            idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
            staged_presplit = acct.staged_bytes("dmmerge")
            assert staged_presplit > 0
            idx.force_merge()
            # retired segments released their staged tables at merge
            events_before = len(acct.stats("dmmerge")["staging_events"])
            idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
            # the merged segment restaged lazily on that query
            assert acct.staged_bytes("dmmerge") > 0
            post_merge = acct.stats("dmmerge")["staging_events"][
                events_before:]
            assert post_merge
            # the merge product carries the retired segments' corpus:
            # its staging must be classified a RESTAGE ("refresh"), so
            # the full-corpus merge cost lands in the amplification
            # numerator (ROADMAP item 3's number), not the denominator
            assert any(e["reason"] == "refresh" for e in post_merge), \
                [e["reason"] for e in post_merge]
            st = idx.search_stats()["memory"]
            assert (st["staged_bytes_total"]
                    == sum(st["staged_bytes"].values()))
        finally:
            idx.close()
        assert acct.staged_bytes("dmmerge") == 0

    def test_delete_logs_delete_invalidation(self, ledger_leak_check):
        acct = ledger_leak_check
        idx = _mk_index("dmdel", shards=1)
        try:
            idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
            idx.delete_doc("3")
            idx.refresh()  # buffered deletes apply at refresh
            events = acct.stats("dmdel")["staging_events"]
            reasons = {e["reason"] for e in events}
            assert "delete_invalidation" in reasons, reasons
            st = acct.stats("dmdel")
            assert st["bytes_logically_changed_total"] > 0
            assert st["restaged_bytes_total"] > 0
        finally:
            idx.close()

    def test_doc_values_kind_populated_and_leak_free(self, monkeypatch,
                                                     ledger_leak_check):
        # ISSUE 13 (docs/AGGS.md): the fused-agg plane stages columnar
        # doc values under the `doc_values` ledger kind — exact bytes in
        # the per-kind map, lifecycle events with reasons, leak-free
        # across force-merge/evict cycles
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        acct = ledger_leak_check
        idx = _mk_index("dmdv", shards=2)
        try:
            body = {"query": {"match": {"body": "w1"}}, "size": 5,
                    "aggs": {"s": {"sum": {"field": "n"}}}}
            got = idx.search(dict(body))
            assert got["_plane"] == "mesh_pallas", got["_plane"]
            st = acct.stats("dmdv")
            assert st["staged_bytes"]["doc_values"] > 0
            dv_events = [e for e in st["staging_events"]
                         if e["kind"] == "doc_values"]
            assert dv_events and all(e["reason"] for e in dv_events)
            assert (st["staged_bytes_total"]
                    == sum(st["staged_bytes"].values()))
            # merge retires the segment set; the rebuilt executor
            # restages the columns on the next agg query, exactly once
            idx.force_merge()
            idx.refresh()
            got2 = idx.search(dict(body))
            assert got2["aggregations"] == got["aggregations"]
            assert acct.stats("dmdv")["staged_bytes"]["doc_values"] > 0
            # eviction drops the columns with their executor scope; the
            # next query restages them (no orphaned doc_values bytes)
            assert acct.force_evict(scopes=8) > 0
            got3 = idx.search(dict(body))
            assert got3["aggregations"] == got["aggregations"]
            st3 = acct.stats("dmdv")
            assert (st3["staged_bytes_total"]
                    == sum(st3["staged_bytes"].values()))
        finally:
            idx.close()
        assert acct.staged_bytes("dmdv") == 0

    def test_mesh_staging_accounted_and_released(self, ledger_leak_check):
        acct = ledger_leak_check
        idx = _mk_index("dmmesh", {"index.search.mesh": True})
        try:
            got = idx.search({"query": {"match": {"body": "w1"}},
                              "size": 5})
            assert got["_plane"] == "mesh_pallas", got["_plane"]
            by_kind = acct.stats("dmmesh")["staged_bytes"]
            assert by_kind["mesh_slot_tables"] > 0, by_kind
            assert (by_kind["postings_raw"] + by_kind["postings_packed"]
                    > 0), by_kind
        finally:
            idx.close()
        assert acct.staged_bytes("dmmesh") == 0


class TestBudgetDemotion:
    """Over-budget mesh staging LRU-evicts, then DEMOTES to the host
    rung with ladder decision reason hbm_budget and byte-identical hits
    — queries degrade, never error (the acceptance criterion)."""

    @pytest.fixture(autouse=True)
    def _kernel(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")

    @pytest.fixture()
    def budget_guard(self):
        acct = memory_accountant()
        yield acct
        acct.set_budget(0)

    @staticmethod
    def _same_hits(got, want):
        gs = [(h["_id"], h["_score"]) for h in got["hits"]["hits"]]
        ws = [(h["_id"], h["_score"]) for h in want["hits"]["hits"]]
        assert len(gs) == len(ws)
        for (gi, gsc), (wi, wsc) in zip(gs, ws):
            assert abs(gsc - wsc) < 1e-5, (gs, ws)
        # doc identity may permute only within exact score ties
        assert sorted(i for i, _ in gs) == sorted(i for i, _ in ws)

    def test_over_budget_demotes_with_identical_hits(self, budget_guard,
                                                     ledger_leak_check):
        acct = budget_guard
        idx = _mk_index("dmbudget", {"index.search.mesh": True})
        body = {"query": {"match": {"body": "w1 w3"}}, "size": 6}
        try:
            baseline = idx.search(dict(body))
            assert baseline["_plane"] == "mesh_pallas"
            evictions = acct.evictions_total
            acct.set_budget(1)
            assert acct.evictions_total > evictions, (
                "budget below the ledger must evict immediately")
            degraded = idx.search(dict(body))
            assert degraded["_plane"] == "host", degraded["_plane"]
            self._same_hits(degraded, baseline)
            decisions = idx.search_stats()["phases"]["decisions"]
            assert decisions.get("host.hbm_budget", 0) >= 1, decisions
            assert acct.budget_denials_total > 0
            # budget restored: the mesh plane restages (probe event) and
            # serves identical hits again
            acct.set_budget(0)
            recovered = idx.search(dict(body))
            assert recovered["_plane"] == "mesh_pallas"
            self._same_hits(recovered, baseline)
            assert any(e["reason"] == "probe"
                       for e in acct.stats("dmbudget")["staging_events"])
        finally:
            idx.close()

    def test_budget_never_errors_over_rest(self, budget_guard):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        client = Client(node)
        try:
            for i in range(20):
                client.index("bidx", str(i), {"body": f"w{i % 4} common"})
            client.perform("POST", "/bidx/_refresh")
            status, _ = client.perform(
                "PUT", "/_cluster/settings",
                body={"persistent":
                      {"search.memory.hbm_budget_bytes": "1b"}})
            assert status == 200
            status, payload = client.perform(
                "POST", "/bidx/_search",
                body={"query": {"match": {"body": "common"}}, "size": 5})
            assert status == 200, payload  # degrade, never 5xx
            assert payload["hits"]["total"] == 20
            # the budget shows as the accounting breaker's limit
            status, stats = client.perform("GET", "/_nodes/stats")
            assert status == 200
            node_block = next(iter(stats["nodes"].values()))
            acc = node_block["breakers"]["accounting"]
            assert acc["limit_size_in_bytes"] == 1
            # clearing the cluster override reverts to the node file
            status, _ = client.perform(
                "PUT", "/_cluster/settings",
                body={"persistent":
                      {"search.memory.hbm_budget_bytes": None}})
            assert status == 200
            assert memory_accountant().budget_bytes == 0
        finally:
            node.close()


class TestConcurrency:
    """The satellite contract: a concurrent stage/evict/query burst
    keeps the incrementally-tracked ledger total exactly equal to the
    recomputed per-kind entry sum."""

    def test_unit_ledger_consistent_under_hammer(self, acct):
        stop = threading.Event()
        errors = []

        def stager(tid):
            try:
                i = 0
                while not stop.is_set():
                    scope = f"s{tid}_{i % 5}"
                    acct.register("i", scope, KINDS[i % len(KINDS)],
                                  f"t{i % 3}", (i % 7 + 1) * 64,
                                  evict=lambda: None)
                    if i % 4 == 3:
                        acct.release_scope("i", scope)
                    if i % 11 == 10:
                        acct.try_reserve("i", 128)
                    i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def toggler():
            try:
                while not stop.is_set():
                    acct.set_budget(512)
                    acct.set_budget(0)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=stager, args=(t,))
                   for t in range(6)] + [threading.Thread(target=toggler)]
        breaker = acct._accounting_breaker()
        prev_limit = breaker.limit_bytes
        for t in threads:
            t.start()
        try:
            import time

            time.sleep(0.5)
        finally:
            stop.set()
            for t in threads:
                t.join()
            breaker.limit_bytes = prev_limit
        assert not errors, errors
        assert acct.staged_bytes() == _entry_sum(acct)
        st = acct.stats()
        assert (st["staged_bytes_total"]
                == sum(st["staged_bytes"].values()))

    def test_service_queries_under_budget_churn(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        acct = memory_accountant()
        base = acct.staged_bytes()
        idx = _mk_index("dmconc", {"index.search.mesh": True}, docs=60,
                        shards=3)
        host = _mk_index("dmconchost", {"index.search.mesh": False},
                         docs=60, shards=3)
        body = {"query": {"match": {"body": "w1 w2"}}, "size": 6}
        stop = threading.Event()
        errors = []

        def querier():
            try:
                while not stop.is_set():
                    got = idx.search(dict(body))
                    assert got["hits"]["hits"], got
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def churner():
            try:
                while not stop.is_set():
                    acct.set_budget(1)  # evict + deny
                    acct.set_budget(0)  # restage allowed again
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=querier) for _ in range(4)]
        threads.append(threading.Thread(target=churner))
        for t in threads:
            t.start()
        try:
            import time

            time.sleep(1.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            acct.set_budget(0)
        try:
            assert not errors, errors
            assert not any(t.is_alive() for t in threads), (
                "stage/evict/query burst deadlocked")
            # the storm is over: ledger total == per-kind entry sum, and
            # a fresh query still returns correct hits on the fast plane
            assert acct.staged_bytes() == sum(
                memory_accountant().staged_bytes_by_kind().values())
            got = idx.search(dict(body))
            want = host.search(dict(body))
            assert got["hits"]["total"] == want["hits"]["total"]
            gs = [h["_score"] for h in got["hits"]["hits"]]
            ws = [h["_score"] for h in want["hits"]["hits"]]
            assert all(abs(a - b) < 1e-5 for a, b in zip(gs, ws))
        finally:
            idx.close()
            host.close()
        assert acct.staged_bytes() == base


class TestCatStaging:
    def test_cat_staging_renders_ledger(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        client = Client(node)
        try:
            for i in range(12):
                client.index("catstg", str(i), {"body": f"w{i % 3}"})
            client.perform("POST", "/catstg/_refresh")
            client.perform("POST", "/catstg/_search",
                           body={"query": {"match": {"body": "w1"}}})
            status, text = client.perform("GET", "/_cat/staging",
                                          params={"v": "true"})
            assert status == 200
            lines = text.strip().splitlines()
            assert lines[0].split()[:4] == ["index", "segment", "kind",
                                            "bytes"]
            assert any("catstg" in line for line in lines[1:]), text
            # every rendered byte count is a real ledger row
            status, plain = client.perform("GET", "/_cat/staging")
            assert status == 200
            assert "index" not in plain.splitlines()[0]
        finally:
            node.close()
