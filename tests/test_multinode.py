"""Multi-node cluster tests: membership, replication, failover, recovery.

Mirrors the reference's InternalTestCluster + disruption-scheme tests
(test/framework/.../InternalTestCluster.java, disruption/) — several real
nodes in one process over an in-process transport with programmable
network faults (SURVEY §4.3, §4.6.3).
"""

import pytest

from elasticsearch_tpu.cluster.multinode import ClusterClient, ClusterNode
from elasticsearch_tpu.cluster.state import ShardRoutingState
from elasticsearch_tpu.transport.local import TransportHub


def start_cluster(n_nodes=3, strict=True):
    hub = TransportHub(strict_serialization=strict)
    nodes = [ClusterNode(f"node-{i}", hub) for i in range(n_nodes)]
    nodes[0].bootstrap_cluster()
    for node in nodes[1:]:
        node.join("node-0")
    return hub, nodes


@pytest.fixture()
def cluster():
    hub, nodes = start_cluster(3)
    yield hub, nodes
    for n in nodes:
        n.close()


def seed_docs(client, index, n=20):
    for i in range(n):
        client.index(index, str(i), {"n": i, "body": f"doc number {i}"})
    client.refresh(index)


class TestMembership:
    def test_join_elects_first_master(self, cluster):
        hub, nodes = cluster
        assert nodes[0].is_master
        for n in nodes:
            assert n.master_id == "node-0"
            assert set(n.known_nodes) == {"node-0", "node-1", "node-2"}

    def test_join_via_non_master_redirects(self, cluster):
        hub, nodes = cluster
        late = ClusterNode("node-9", hub)
        late.join("node-2")  # seed is not the master
        assert "node-9" in nodes[0].known_nodes
        assert late.master_id == "node-0"
        late.close()


class TestAllocationAndReplication:
    def test_shards_spread_and_replicated(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 3,
                                                "number_of_replicas": 1}})
        # 3 primaries + 3 replicas over 3 nodes = 2 shards each
        counts = [len(n.shards) for n in nodes]
        assert sum(counts) == 6
        assert max(counts) - min(counts) <= 1
        # replica never on the primary's node
        for sid, copies in nodes[0].routing["idx"].items():
            nodes_used = [c.node_id for c in copies]
            assert len(nodes_used) == len(set(nodes_used))

    def test_write_replicates_with_same_seqno(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 2}})
        client = ClusterClient(nodes[1])
        r = client.index("idx", "1", {"v": 1})
        assert r["_shards"]["successful"] == 3
        client.refresh("idx")
        # every copy holds the doc with the primary-assigned seqno
        seqnos = []
        for node in nodes:
            shard = node.shards.get(("idx", 0))
            if shard is not None:
                g = shard.get_doc("1")
                assert g.found and g.source == {"v": 1}
                seqnos.append(g.seqno)
        assert len(seqnos) == 3 and len(set(seqnos)) == 1

    def test_get_served_from_replica(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        client.index("idx", "1", {"v": 7})
        g = client.get("idx", "1", prefer_replica=True)
        assert g["found"] and g["_source"] == {"v": 7}

    def test_search_across_nodes(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 4,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[2])
        seed_docs(client, "idx", 30)
        r = client.search("idx", {"query": {"match": {"body": "doc"}}, "size": 30})
        assert r["hits"]["total"] == 30
        assert r["_shards"]["total"] == 4 and r["_shards"]["failed"] == 0
        r2 = client.search("idx", {"query": {"term": {"n": 5}}})
        assert [h["_id"] for h in r2["hits"]["hits"]] == ["5"]

    def test_sorted_search_merges_across_nodes(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 3,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[0])
        seed_docs(client, "idx", 25)
        r = client.search("idx", {"query": {"match_all": {}},
                                  "sort": [{"n": "asc"}], "size": 5})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["0", "1", "2", "3", "4"]


class TestReplicaRecovery:
    def test_new_replica_recovers_from_primary(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
        client = ClusterClient(nodes[0])
        seed_docs(client, "idx", 10)
        # raise replica count -> allocation creates an INITIALIZING replica
        # that peer-recovers from the primary
        nodes[0].indices_meta["idx"].settings = nodes[0].indices_meta[
            "idx"].settings.merged_with(
            __import__("elasticsearch_tpu.common.settings",
                       fromlist=["Settings"]).Settings(
                {"index.number_of_replicas": 1})
        )
        nodes[0]._master_reroute_and_publish()
        copies = nodes[0].routing["idx"][0]
        assert len(copies) == 2
        assert all(c.state == ShardRoutingState.STARTED for c in copies)
        replica = next(c for c in copies if not c.primary)
        replica_node = next(n for n in nodes if n.node_id == replica.node_id)
        shard = replica_node.shards[("idx", 0)]
        assert shard.num_docs == 10

    def test_late_joining_node_gets_replicas(self):
        hub, nodes = start_cluster(1)
        nodes[0].create_index("idx", {"index": {"number_of_shards": 2,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        seed_docs(client, "idx", 8)
        # single node: replicas unassigned (yellow)
        assert all(len(c) == 1 for c in nodes[0].routing["idx"].values())
        n1 = ClusterNode("node-1", hub)
        n1.join("node-0")
        assert all(len(c) == 2 for c in nodes[0].routing["idx"].values())
        # recovered replicas carry the data
        total = sum(s.num_docs for s in n1.shards.values())
        assert total == 8
        for n in nodes + [n1]:
            n.close()


class TestFailover:
    def test_primary_promotion_on_node_loss(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 3,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        seed_docs(client, "idx", 12)
        # pick a shard whose primary is NOT the master (exists: 3 primaries
        # over 3 nodes) so the master survives to run fault detection
        sid, primary_node_id = next(
            (sid, nodes[0]._primary_node("idx", sid))
            for sid in nodes[0].routing["idx"]
            if nodes[0]._primary_node("idx", sid) != "node-0"
        )
        victim = next(n for n in nodes if n.node_id == primary_node_id)
        old_term = victim.shards[("idx", sid)].primary_term
        # partition the primary away and run fault detection
        hub.disconnect(primary_node_id)
        departed = nodes[0].check_nodes()
        assert primary_node_id in departed
        # replica promoted, term bumped
        new_primary_id = nodes[0]._primary_node("idx", sid)
        assert new_primary_id is not None and new_primary_id != primary_node_id
        new_primary = next(n for n in nodes if n.node_id == new_primary_id)
        shard = new_primary.shards[("idx", sid)]
        assert shard.primary
        assert shard.primary_term == old_term + 1
        # data survived; writes + reads work against the surviving nodes
        client2 = ClusterClient(nodes[0])
        client2.index("idx", "new-doc", {"after": "failover"})
        client2.refresh("idx")
        r = client2.search("idx", {"size": 0})
        assert r["hits"]["total"] == 13

    def test_replica_failure_during_write_drops_copy(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 2}})
        client = ClusterClient(nodes[0])
        client.index("idx", "1", {"v": 1})
        primary_id = nodes[0]._primary_node("idx", 0)
        replica_ids = [c.node_id for c in nodes[0].routing["idx"][0]
                       if not c.primary]
        # break primary -> first replica link only
        hub.disconnect(primary_id, replica_ids[0])
        r = client.index("idx", "2", {"v": 2})
        # write succeeded on primary + surviving replica; failed copy was
        # reported to the master and dropped, then re-allocated
        assert r["_shards"]["successful"] >= 2
        hub.heal()

    def test_search_fails_over_to_replica(self, cluster):
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 1,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[0])
        seed_docs(client, "idx", 5)
        primary_id = nodes[0]._primary_node("idx", 0)
        # coordinator (node-0) loses the primary's node; replica serves
        if primary_id != "node-0":
            hub.disconnect("node-0", primary_id)
        r = client.search("idx", {"size": 0})
        assert r["hits"]["total"] == 5
        hub.heal()


class TestTransportFaults:
    def test_disconnect_raises(self, cluster):
        hub, nodes = cluster
        hub.disconnect("node-0", "node-1")
        from elasticsearch_tpu.common.errors import NodeNotConnectedException

        with pytest.raises(NodeNotConnectedException):
            nodes[0].transport.send_request("node-1", "internal:cluster/coordination/publish_state", None)
        hub.heal()
        assert nodes[0].transport.send_request(
            "node-1", "internal:cluster/coordination/publish_state", None
        )["ok"]

    def test_requests_are_json_serializable(self, cluster):
        # strict_serialization mode round-trips every payload through JSON:
        # the handler contract stays wire-clean for the future DCN transport
        hub, nodes = cluster
        nodes[0].create_index("idx", {"index": {"number_of_shards": 2,
                                                "number_of_replicas": 1}})
        client = ClusterClient(nodes[1])
        seed_docs(client, "idx", 6)
        r = client.search("idx", {"query": {"match_all": {}}})
        assert r["hits"]["total"] == 6
