"""Tests for the Pallas tile-scoring kernel (ops/pallas_scoring.py).

Run on the CPU backend in interpreter mode (interpret=True): the kernel
semantics are identical to the compiled TPU path; mosaic-specific layout
constraints are exercised separately on hardware by bench.py.

Oracle: reference_scores — a host scatter-add over the same block-packed
postings, i.e. exactly what ops/scoring.score_term_blocks computes and
what Lucene's BulkScorer loop (search/query/QueryPhase.java:272) produces
for a weighted disjunction.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elasticsearch_tpu.ops.pallas_scoring import (
    CB_MAX,
    LANE,
    QueryLane,
    block_min_max,
    build_live_t,
    build_tile_tables,
    compute_block_frac,
    dense_to_flat,
    merge_tile_topk,
    next_pow2,
    pad_segment_blocks,
    reference_scores,
    score_tiles,
    tile_geometry,
)


def assert_topk_valid(top_s, top_d, ref, k):
    """Tie-robust top-k check: returned scores must equal the reference's
    sorted top-k values, and every returned doc's own reference score must
    equal its returned score (so any tie-breaking choice is accepted)."""
    top_s = np.asarray(top_s)
    top_d = np.asarray(top_d)
    expect = np.sort(ref[ref > 0])[::-1][:k]
    got = top_s[top_s > -np.inf]
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    for s, d in zip(top_s, top_d):
        if s > -np.inf:
            np.testing.assert_allclose(ref[d], s, rtol=1e-5)
    assert len(set(top_d[top_s > -np.inf].tolist())) == len(got)


def build_corpus(rng, nd, vocab, max_df=300):
    """Block-packed synthetic postings like SegmentBuilder.seal() emits."""
    nd_pad = next_pow2(nd)
    blocks_docs, blocks_tfs = [], []
    term_start, term_count = [], []
    for _ in range(vocab):
        df = rng.randint(1, max_df)
        docs = np.sort(rng.choice(nd, size=min(df, nd),
                                  replace=False)).astype(np.int32)
        tfs = rng.randint(1, 5, size=len(docs)).astype(np.float32)
        nb = -(-len(docs) // LANE)
        term_start.append(len(blocks_docs))
        term_count.append(nb)
        for i in range(nb):
            d = np.full(LANE, nd_pad, np.int32)
            f = np.zeros(LANE, np.float32)
            chunk = docs[i * LANE:(i + 1) * LANE]
            d[: len(chunk)] = chunk
            f[: len(chunk)] = tfs[i * LANE:(i + 1) * LANE]
            blocks_docs.append(d)
            blocks_tfs.append(f)
    return (np.stack(blocks_docs), np.stack(blocks_tfs),
            term_start, term_count, nd_pad)


def run_kernel(block_docs, frac, live, lanes, nd_pad, k=10, tile_sub=4,
               dense=False, with_counts=False):
    geom = tile_geometry(nd_pad, tile_sub=tile_sub)
    bmin, bmax = block_min_max(block_docs, frac, nd_pad)
    row_lo, row_hi, weights, cb = build_tile_tables(lanes, bmin, bmax, geom)
    dp, fp = pad_segment_blocks(block_docs, frac, nd_pad)
    live_t = build_live_t(live, geom)
    out = score_tiles(
        jnp.asarray(dp), jnp.asarray(fp), jnp.asarray(live_t),
        jnp.asarray(row_lo), jnp.asarray(row_hi), jnp.asarray(weights),
        t_pad=weights.shape[1], cb=cb, sub=geom.tile_sub, k=k,
        dense=dense, with_counts=with_counts, interpret=True)
    return out, geom


class TestTopkKernel:
    def test_matches_scatter_reference(self):
        rng = np.random.RandomState(1)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 3000, 80)
        doc_len = np.full(nd_pad + 1, 40.0, np.float32)
        frac = compute_block_frac(bd, bt, doc_len, avgdl=40.0)
        live = np.zeros(nd_pad, np.float32)
        live[:3000] = 1.0
        lanes = [QueryLane(ts_[3], tc[3], 1.4),
                 QueryLane(ts_[10], tc[10], 0.9),
                 QueryLane(ts_[55], tc[55], 2.0)]
        (tile_s, tile_d, tile_h), geom = run_kernel(
            bd, frac, live, lanes, nd_pad)
        top_s, top_d, hits = merge_tile_topk(tile_s, tile_d, tile_h, 10)
        ref = reference_scores(bd, frac, lanes, nd_pad)
        ref[live == 0] = 0.0
        assert int(hits) == int((ref > 0).sum())
        assert_topk_valid(top_s, top_d, ref, 10)

    def test_deleted_docs_excluded(self):
        rng = np.random.RandomState(2)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 1000, 20)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 10.0, np.float32),
                                  avgdl=10.0)
        live = np.zeros(nd_pad, np.float32)
        live[:1000] = 1.0
        dead = rng.choice(1000, 200, replace=False)
        live[dead] = 0.0
        lanes = [QueryLane(ts_[0], tc[0], 1.0)]
        (tile_s, tile_d, tile_h), _ = run_kernel(bd, frac, live, lanes, nd_pad)
        top_s, top_d, hits = merge_tile_topk(tile_s, tile_d, tile_h, 10)
        docs = np.asarray(top_d)
        assert not set(docs[np.asarray(top_s) > -np.inf].tolist()) & set(
            dead.tolist())
        ref = reference_scores(bd, frac, lanes, nd_pad)
        ref[live == 0] = 0.0
        assert int(hits) == int((ref > 0).sum())

    def test_fewer_matches_than_k(self):
        rng = np.random.RandomState(3)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 600, 10, max_df=5)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 10.0, np.float32),
                                  avgdl=10.0)
        live = np.zeros(nd_pad, np.float32)
        live[:600] = 1.0
        lanes = [QueryLane(ts_[2], tc[2], 1.0)]
        (tile_s, tile_d, tile_h), _ = run_kernel(bd, frac, live, lanes, nd_pad,
                                                 k=10)
        top_s, top_d, hits = merge_tile_topk(tile_s, tile_d, tile_h, 10)
        ref = reference_scores(bd, frac, lanes, nd_pad)
        n = int((ref > 0).sum())
        assert int(hits) == n < 10
        top_s = np.asarray(top_s)
        top_d = np.asarray(top_d)
        assert (top_d[top_s == -np.inf] == -1).all()
        assert (top_s > -np.inf).sum() == n

    def test_padded_lanes_ignored(self):
        """t_pad > len(lanes): zero-weight padding lanes contribute nothing."""
        rng = np.random.RandomState(4)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 1500, 30)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 20.0, np.float32),
                                  avgdl=20.0)
        live = np.zeros(nd_pad, np.float32)
        live[:1500] = 1.0
        lanes3 = [QueryLane(ts_[i], tc[i], 1.0) for i in (1, 5, 9)]
        geom = tile_geometry(nd_pad, tile_sub=4)
        bmin, bmax = block_min_max(bd, frac, nd_pad)
        row_lo, row_hi, weights, cb = build_tile_tables(
            lanes3, bmin, bmax, geom, t_pad=8)
        dp, fp = pad_segment_blocks(bd, frac, nd_pad)
        live_t = build_live_t(live, geom)
        tile_s, tile_d, tile_h = score_tiles(
            jnp.asarray(dp), jnp.asarray(fp), jnp.asarray(live_t),
            jnp.asarray(row_lo), jnp.asarray(row_hi), jnp.asarray(weights),
            t_pad=8, cb=cb, sub=geom.tile_sub, k=10, interpret=True)
        top_s, top_d, hits = merge_tile_topk(tile_s, tile_d, tile_h, 10)
        ref = reference_scores(bd, frac, lanes3, nd_pad)
        ref[live == 0] = 0.0
        assert_topk_valid(top_s, top_d, ref, 10)

    def test_single_tile_segment(self):
        """Segments smaller than one tile (n_tiles == 1) still work."""
        rng = np.random.RandomState(5)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 200, 8, max_df=60)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 15.0, np.float32),
                                  avgdl=15.0)
        live = np.zeros(nd_pad, np.float32)
        live[:200] = 1.0
        lanes = [QueryLane(ts_[0], tc[0], 1.0), QueryLane(ts_[4], tc[4], 3.0)]
        (tile_s, tile_d, tile_h), geom = run_kernel(bd, frac, live, lanes,
                                                    nd_pad, tile_sub=4)
        assert geom.n_tiles == 1
        top_s, top_d, hits = merge_tile_topk(tile_s, tile_d, tile_h, 10)
        ref = reference_scores(bd, frac, lanes, nd_pad)
        ref[live == 0] = 0.0
        assert_topk_valid(top_s, top_d, ref, 10)


class TestDenseKernel:
    def test_dense_scores_and_counts(self):
        rng = np.random.RandomState(6)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 2500, 40)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 30.0, np.float32),
                                  avgdl=30.0)
        live = np.zeros(nd_pad, np.float32)
        live[:2500] = 1.0
        lanes = [QueryLane(ts_[i], tc[i], w)
                 for i, w in [(0, 1.0), (7, 2.5), (13, 0.5)]]
        (dense, counts), geom = run_kernel(bd, frac, live, lanes, nd_pad,
                                           dense=True, with_counts=True)
        flat = np.asarray(dense_to_flat(dense, geom.tile_sub))
        cflat = np.asarray(dense_to_flat(counts, geom.tile_sub))
        ref = reference_scores(bd, frac, lanes, nd_pad)
        ref[live == 0] = 0.0
        np.testing.assert_allclose(flat, ref, rtol=1e-5)
        # counts: distinct matching lanes per doc
        cref = np.zeros(nd_pad, np.float32)
        for lane in lanes:
            rows = slice(lane.block_start, lane.block_start + lane.block_count)
            docs = bd[rows].ravel()
            f = frac[rows].ravel()
            sel = (f > 0) & (docs < nd_pad)
            np.add.at(cref, docs[sel], 1.0)
        cref[live == 0] = 0.0
        np.testing.assert_allclose(cflat, cref, rtol=1e-6)


class TestWindowAlignment:
    def test_misaligned_window_not_truncated(self):
        """Regression: a lane whose covering window starts at a block row
        with a high offset modulo CB (e.g. row 6 with cb=8) must still see
        all its blocks — the kernel fetches two aligned windows, so rows
        past the first aligned block are not dropped."""
        rng = np.random.RandomState(8)
        nd = 512
        nd_pad = next_pow2(nd)
        blocks_docs, blocks_tfs = [], []
        # 6 filler one-block terms so the dense term starts at row 6
        for i in range(6):
            d = np.full(LANE, nd_pad, np.int32)
            f = np.zeros(LANE, np.float32)
            d[0] = i
            f[0] = 1.0
            blocks_docs.append(d)
            blocks_tfs.append(f)
        # dense term: every doc -> 4 full blocks at rows [6, 10)
        docs = np.arange(nd, dtype=np.int32)
        for i in range(4):
            blocks_docs.append(docs[i * LANE:(i + 1) * LANE])
            blocks_tfs.append(np.ones(LANE, np.float32))
        bd = np.stack(blocks_docs)
        bt = np.stack(blocks_tfs)
        frac = compute_block_frac(bd, bt, np.full(nd_pad + 1, 10.0, np.float32),
                                  avgdl=10.0)
        live = np.zeros(next_pow2(max(nd_pad, LANE)), np.float32)
        live[:nd] = 1.0
        lanes = [QueryLane(6, 4, 1.0)]
        (dense, ), geom = run_kernel(bd, frac, live, lanes, nd_pad,
                                     tile_sub=4, dense=True)
        flat = np.asarray(dense_to_flat(dense, geom.tile_sub))
        ref = reference_scores(bd, frac, lanes, geom.nd_pad)
        ref[live[: geom.nd_pad] == 0] = 0.0
        np.testing.assert_allclose(flat, ref, rtol=1e-5)
        assert (flat[:nd] > 0).all()  # every doc scored — nothing dropped


class TestHostGeometry:
    def test_tile_tables_cover_all_postings(self):
        """Every real posting must fall inside its tile's [row_lo, row_hi)
        window — the correctness contract of the searchsorted coverage."""
        rng = np.random.RandomState(7)
        bd, bt, ts_, tc, nd_pad = build_corpus(rng, 4000, 50)
        geom = tile_geometry(nd_pad, tile_sub=4)
        w = geom.tile_w
        bmin, bmax = block_min_max(bd, bt, nd_pad)
        lanes = [QueryLane(ts_[i], tc[i], 1.0) for i in range(12)]
        row_lo, row_hi, weights, cb = build_tile_tables(lanes, bmin, bmax, geom)
        assert cb <= CB_MAX
        for j, lane in enumerate(lanes):
            for b in range(lane.block_start, lane.block_start + lane.block_count):
                docs = bd[b][bt[b] > 0]
                for t in np.unique(docs // w):
                    assert row_lo[t, j] <= b < row_hi[t, j], (
                        f"block {b} with docs in tile {t} not covered")

    def test_geometry_small_segments(self):
        assert tile_geometry(64).n_tiles == 1
        g = tile_geometry(1 << 20)
        assert g.n_tiles * g.tile_w == 1 << 20

    def test_dense_term_needs_smaller_tile(self):
        """A clustered dense term overflows the covering-window bound at
        big tiles; the planner's geometry ladder must find a tile_sub
        where it fits (sub=32 always does: need <= sub + 2), and the
        kernel at that geometry must still match the oracle."""
        nd = 1 << 16  # 64k docs so tile_sub=128 tiles exist
        nd_pad = nd
        # one term matching every doc: 512 maximally-dense blocks
        docs = np.arange(nd, dtype=np.int32).reshape(-1, LANE)
        tfs = np.ones_like(docs, np.float32)
        frac = compute_block_frac(docs, tfs, np.full(nd_pad + 1, 10.0,
                                                     np.float32), 10.0)
        bmin, bmax = block_min_max(docs, tfs, nd_pad)
        lanes = [QueryLane(0, docs.shape[0], 1.5)]
        with pytest.raises(ValueError):
            build_tile_tables(lanes, bmin, bmax,
                              tile_geometry(nd_pad, tile_sub=128))
        # the ladder's floor geometry fits and scores correctly
        geom = tile_geometry(nd_pad, tile_sub=32)
        row_lo, row_hi, weights, cb = build_tile_tables(
            lanes, bmin, bmax, geom)
        assert cb <= CB_MAX // 2
        dp, fp = pad_segment_blocks(docs, frac, nd_pad)
        live = np.ones(nd_pad, np.float32)
        out = score_tiles(
            jnp.asarray(dp), jnp.asarray(fp),
            jnp.asarray(build_live_t(live, geom)),
            jnp.asarray(row_lo), jnp.asarray(row_hi), jnp.asarray(weights),
            t_pad=weights.shape[1], cb=cb, sub=geom.tile_sub, k=10,
            interpret=True)
        top_s, top_d, hits = merge_tile_topk(*out, 10)
        ref = reference_scores(docs, frac, lanes, nd_pad)
        assert int(hits) == nd
        assert_topk_valid(top_s, top_d, ref, 10)
