"""Delta device staging (ISSUE 20, docs/MESH.md "Slot allocator").

The mesh plane keeps its collective geometry across refreshes: an
appended segment stages ONLY its own tables into a free slot
(lifecycle reason ``delta_append`` — restage_amplification ~1 for a
pure append), a delete updates ONLY the affected slot's live-mask
column in place (reason ``tombstone``), and a background pass compacts
sparse slots into a fresh generation (reason ``compaction``) off the
query path. The parity contract is absolute: a delta-staged index must
return byte-identical hits (ids + scores), fused aggs, and kNN results
to a freshly full-restaged oracle on every rung, and the ledger must
return to baseline exactly across append → tombstone → compact — a
mid-delta staging fault restores the exact pre-attempt ledger.
Runs the kernel in interpret mode on the CPU backend.
"""

import numpy as np
import pytest

from elasticsearch_tpu.common.memory import memory_accountant
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.testing.disruption import (
    StagingFailScheme,
    clear_search_disruptions,
)

MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "n": {"type": "integer"},
    "tag": {"type": "keyword"},
}}

DIMS = 8

KNN_MAPPING = {"properties": {
    "emb": {"type": "dense_vector", "dims": DIMS,
            "similarity": "cosine"},
    "body": {"type": "text", "analyzer": "whitespace"},
}}


@pytest.fixture(autouse=True)
def _interpret_kernel(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
    yield
    clear_search_disruptions()


def _doc(d):
    return {"body": f"w{d % 5} common", "n": d % 17,
            "tag": ["red", "green", "blue"][d % 3]}


def build_index(name, mesh=True, delta=True, compact=0.0, shards=3,
                mapping=None, **extra):
    """compact=0 disables background compaction so the staging tests
    observe the delta generations themselves, not the compactor
    rewriting them from under the assertions."""
    settings = {"index.number_of_shards": shards,
                "index.refresh_interval": -1,
                "index.search.mesh": mesh,
                "index.staging.delta.enabled": delta,
                "index.staging.compact.threshold": compact}
    if mesh:
        # one CPU device: raise the packing bound so multi-refresh
        # sequences keep fitting (a real mesh spreads over n_dev)
        settings.setdefault("index.search.mesh.max_slots_per_device", 16)
    settings.update(extra)
    return IndexService(name, Settings(settings),
                        mapping=mapping or MAPPING)


def _fill(idx, lo, hi):
    for d in range(lo, hi):
        idx.index_doc(str(d), _doc(d))
    idx.refresh()


def assert_parity(got, want):
    assert got["hits"]["total"] == want["hits"]["total"]
    assert ([h["_id"] for h in got["hits"]["hits"]]
            == [h["_id"] for h in want["hits"]["hits"]])
    for g, w in zip(got["hits"]["hits"], want["hits"]["hits"]):
        assert g["_score"] == w["_score"], (g, w)  # byte-identical
    assert got.get("aggregations") == want.get("aggregations"), (
        got.get("aggregations"), want.get("aggregations"))


class TestDeltaAppend:
    def test_pure_append_keeps_generation_and_amp_1(self):
        idx = build_index("da-amp")
        try:
            _fill(idx, 0, 48)
            assert idx.search({"query": {"match": {"body": "common"}},
                               "size": 5})["_plane"] == "mesh_pallas"
            ms = idx._mesh_search
            acc = memory_accountant()
            st0 = acc.stats("da-amp")
            scope0 = ms._executor.scope
            free0 = ms._executor.free_slots()
            assert free0 >= idx.num_shards  # headroom for one refresh

            _fill(idx, 48, 64)
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 5})
            assert r["_plane"] == "mesh_pallas"
            assert r["hits"]["total"] == 64
            # served by a delta append, not a rebuild: the successor
            # generation carries the old arrays (fresh scope, but the
            # delta counter — not a full-restage reason — moved)
            assert ms.delta_restage_total == 1
            assert ms._executor.scope != scope0
            assert ms._executor.free_slots() == free0 - idx.num_shards
            st1 = acc.stats("da-amp")
            d_rest = (st1["restaged_bytes_total"]
                      - st0["restaged_bytes_total"])
            d_log = (st1["bytes_logically_changed_total"]
                     - st0["bytes_logically_changed_total"])
            # the headline number this PR exists for: a pure-append
            # refresh restages only the appended segments' bytes
            assert d_log > 0
            assert d_rest / d_log <= 1.5, (d_rest, d_log)
            reasons = {e["reason"] for e in st1["staging_events"]
                       if e not in st0["staging_events"]}
            assert "delta_append" in reasons
        finally:
            idx.close()

    def test_append_slots_exhausted_falls_back_to_rebuild(self):
        # packing allows 2 slots total: the second refresh cannot fit a
        # delta append — the classifier must fall back to the full
        # rebuild (and the index keeps serving correctly)
        idx = build_index("da-fallback", shards=1,
                          **{"index.search.mesh.max_slots_per_device": 2})
        try:
            _fill(idx, 0, 24)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search
            _fill(idx, 24, 36)
            _fill(idx, 36, 48)  # 3 segments > 2 slots
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 5})
            assert r["hits"]["total"] == 48
            assert ms.delta_restage_total <= 1  # the 3rd seg rebuilt
        finally:
            idx.close()

    def test_delta_disabled_setting_forces_rebuild(self):
        idx = build_index("da-off", delta=False)
        try:
            _fill(idx, 0, 48)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search
            scope0 = ms._executor.scope if ms._executor else None
            _fill(idx, 48, 64)
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 5})
            assert r["hits"]["total"] == 64
            assert ms.delta_restage_total == 0
            assert ms._executor.scope != scope0  # full new generation
        finally:
            idx.close()


class TestTombstone:
    def test_delete_updates_only_live_mask_in_place(self):
        idx = build_index("ts-mask")
        try:
            _fill(idx, 0, 48)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search
            scope0 = ms._executor.scope
            acc = memory_accountant()
            n_before = len(acc.stats("ts-mask")["staging_events"])

            idx.delete_doc("7")
            idx.refresh()
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 48})
            assert r["hits"]["total"] == 47
            assert "7" not in [h["_id"] for h in r["hits"]["hits"]]
            # in place: SAME generation, only mask bytes restaged
            assert ms._executor.scope == scope0
            assert ms.tombstone_update_total == 1
            new_events = acc.stats("ts-mask")["staging_events"][n_before:]
            mesh_events = [e for e in new_events
                           if e["reason"] == "tombstone"]
            assert mesh_events, new_events
            assert all(e["kind"] in ("live_mask", "mesh_slot_tables")
                       for e in mesh_events), mesh_events
        finally:
            idx.close()

    def test_tombstone_density_visible_in_slot_stats(self):
        idx = build_index("ts-density", shards=2)
        try:
            _fill(idx, 0, 20)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search
            for d in range(5):
                idx.delete_doc(str(d))
            idx.refresh()
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            stats = ms.staging_slot_stats()
            assert stats["free_slots"] >= 1
            assert stats["free_slots_per_device"] >= 1
            # 5 of 20 docs tombstoned, visible per slot
            assert sum(s["docs"] - s["live"]
                       for s in stats["slots"]) == 5
            assert any(s["tombstone_density"] > 0
                       for s in stats["slots"]), stats
        finally:
            idx.close()


class TestDeltaVsFullParity:
    def _run_interleaved(self, idx):
        """Interleaved index/delete/refresh/search sequence, identical
        on every index it is applied to (the searches between steps
        keep a generation staged so the delta index actually exercises
        append + tombstone paths rather than one cold staging)."""
        probe = {"query": {"match": {"body": "common"}}, "size": 3}
        _fill(idx, 0, 48)
        idx.search(dict(probe))
        for d in (3, 17, 30):
            idx.delete_doc(str(d))
        idx.refresh()
        idx.search(dict(probe))
        _fill(idx, 48, 60)
        idx.search(dict(probe))
        for d in (48, 5):
            idx.delete_doc(str(d))
        idx.refresh()
        idx.search(dict(probe))
        _fill(idx, 60, 72)

    def test_hits_scores_and_aggs_byte_identical_every_rung(self):
        delta = build_index("par-delta")
        full = build_index("par-full", delta=False)
        host = build_index("par-host", mesh=False)
        try:
            for idx in (delta, full, host):
                self._run_interleaved(idx)
            bodies = [
                {"query": {"match": {"body": "common"}}, "size": 30},
                {"query": {"match": {"body": "w1 w2"}}, "size": 20,
                 "aggs": {"tags": {"terms": {"field": "tag"}},
                          "hist": {"histogram": {"field": "n",
                                                 "interval": 5}},
                          "st": {"stats": {"field": "n"}}}},
            ]
            for body in bodies:
                got = delta.search(dict(body))
                oracle = full.search(dict(body))
                want_host = host.search(dict(body))
                assert got["_plane"] == "mesh_pallas", got["_plane"]
                # delta index actually served deltas, oracle rebuilt
                assert_parity(got, oracle)
                assert_parity(got, want_host)
            assert delta._mesh_search.delta_restage_total >= 1
            assert delta._mesh_search.tombstone_update_total >= 1
            assert full._mesh_search.delta_restage_total == 0
        finally:
            delta.close()
            full.close()
            host.close()

    def test_knn_byte_identical_after_append_and_delete(self):
        rng = np.random.RandomState(7)
        vecs = rng.randn(72, DIMS).astype(np.float32)

        def fill(idx, lo, hi):
            for d in range(lo, hi):
                idx.index_doc(str(d), {"emb": vecs[d].tolist(),
                                       "body": f"t{d % 3}"})
            idx.refresh()

        delta = build_index("knnpar-delta", mapping=KNN_MAPPING)
        full = build_index("knnpar-full", delta=False,
                           mapping=KNN_MAPPING)
        try:
            body = {"knn": {"field": "emb",
                            "query_vector": vecs[0].tolist(), "k": 10,
                            "num_candidates": 50}, "size": 10}
            for idx in (delta, full):
                fill(idx, 0, 48)
                idx.search(dict(body))  # stage the kNN plane
                fill(idx, 48, 64)
                idx.delete_doc("9")
                idx.refresh()
                fill(idx, 64, 72)
            got = delta.search(dict(body))
            want = full.search(dict(body))
            assert got["hits"]["total"] == want["hits"]["total"]
            assert ([h["_id"] for h in got["hits"]["hits"]]
                    == [h["_id"] for h in want["hits"]["hits"]])
            for g, w in zip(got["hits"]["hits"], want["hits"]["hits"]):
                assert g["_score"] == w["_score"], (g, w)
            assert "9" not in [h["_id"] for h in got["hits"]["hits"]]
        finally:
            delta.close()
            full.close()


class TestCompaction:
    def test_compact_merges_sparse_slots_and_releases_old_generation(self):
        # threshold 0 suppresses the post-delta auto-trigger so the
        # pass runs exactly once, here, deterministically
        idx = build_index("cp-run", compact=0.0)
        try:
            _fill(idx, 0, 48)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search
            scope0 = ms._executor.scope
            # delete enough to cross the density threshold
            for d in range(0, 12):
                idx.delete_doc(str(d))
            idx.refresh()
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            # any shard with ≥1 tombstone is "dense" at this threshold,
            # so the pass expunges every delete (hash routing spreads
            # the 12 deletes unevenly across the 3 shards)
            idx.staging_compact_threshold_override = 0.01
            out = idx.compact_now()
            assert out["ran"] is True, out
            assert out["merged_shards"], out  # deletes expunged
            assert out["restaged"] is True
            assert ms.compaction_runs_total == 1
            assert ms._executor.scope != scope0  # fresh generation
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 48})
            assert r["hits"]["total"] == 36
            stats = ms.staging_slot_stats()
            assert all(s["tombstone_density"] == 0.0
                       for s in stats["slots"]), stats
        finally:
            idx.close()

    def test_compaction_single_flight_and_drain_abort(self):
        idx = build_index("cp-drain", compact=0.2)
        try:
            _fill(idx, 0, 24)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            idx.admission.begin_drain()
            out = idx.compact_now()
            assert out == {"ran": False, "reason": "draining"}
            assert idx.maybe_compact_async() is False  # drain wins
            # single-flight: a held lock means "already running"
            with idx._compact_lock:
                assert idx.compact_now() == {
                    "ran": False, "reason": "already_running"}
        finally:
            idx.close()

    def test_compact_noop_below_threshold(self):
        idx = build_index("cp-noop", compact=0.9)
        try:
            _fill(idx, 0, 24)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            assert idx.maybe_compact_async() is False
        finally:
            idx.close()


class TestLedgerExactness:
    def test_leak_free_across_append_tombstone_compact_cycle(self):
        acc = memory_accountant()
        base = acc.stats()["staged_bytes_total"]
        idx = build_index("lg-cycle", compact=0.2)
        try:
            _fill(idx, 0, 48)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            _fill(idx, 48, 60)  # delta append
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            for d in range(20):
                idx.delete_doc(str(d))  # tombstone, then compaction
            idx.refresh()
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            idx.compact_now()
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            assert acc.stats("lg-cycle")["staged_bytes_total"] > 0
        finally:
            idx.close()
        # every generation the cycle created was released: the node
        # ledger is byte-exactly back at its pre-index baseline
        assert acc.stats()["staged_bytes_total"] == base
        assert acc.stats("lg-cycle")["staged_bytes_total"] == 0

    def test_mid_delta_fault_restores_exact_pre_attempt_ledger(self):
        acc = memory_accountant()
        idx = build_index("lg-fault")
        try:
            _fill(idx, 0, 48)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search
            scope0 = ms._executor.scope

            def mesh_rows():
                # the mesh generations' ledger rows only: the host rung
                # legitimately stages per-segment tables while the mesh
                # staging is benched — those are NOT attempt residue
                return sorted(
                    (r["segment"], r["kind"], r["bytes"], r["tables"])
                    for r in acc.table()
                    if r["index"] == "lg-fault"
                    and r["segment"].startswith("mesh#"))

            snapshot = mesh_rows()
            # deterministic fault at the delta-append staging boundary:
            # the attempt must register NOTHING (register-then-commit)
            StagingFailScheme(kinds=["mesh_slot_tables"],
                              transient=False, times=1,
                              indices=["lg-fault"]).install()
            _fill(idx, 48, 60)
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 5})
            # served from the host rung (staging benched), still correct
            assert r["hits"]["total"] == 60
            assert r["_plane"] != "mesh_pallas"
            assert mesh_rows() == snapshot
            # the OLD generation survived the failed attempt untouched
            assert ms._executor is not None
            assert ms._executor.scope == scope0
        finally:
            idx.close()

    def test_mid_tombstone_fault_restores_exact_pre_attempt_ledger(self):
        acc = memory_accountant()
        idx = build_index("lg-tfault")
        try:
            _fill(idx, 0, 48)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            ms = idx._mesh_search

            def mesh_rows():
                return sorted(
                    (r["segment"], r["kind"], r["bytes"], r["tables"])
                    for r in acc.table()
                    if r["index"] == "lg-tfault"
                    and r["segment"].startswith("mesh#"))

            snapshot = mesh_rows()
            StagingFailScheme(kinds=["live_mask"],
                              transient=False, times=1,
                              indices=["lg-tfault"]).install()
            idx.delete_doc("3")
            idx.refresh()
            r = idx.search({"query": {"match": {"body": "common"}},
                            "size": 5})
            assert r["hits"]["total"] == 47  # host rung serves truth
            assert mesh_rows() == snapshot
            assert ms.tombstone_update_total == 0
        finally:
            idx.close()


class TestSettingsPlumbing:
    def test_counters_exported_in_search_stats(self):
        idx = build_index("st-exp")
        try:
            _fill(idx, 0, 24)
            idx.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            planes = idx.search_stats()["planes"]
            for key in ("delta_restage_total", "tombstone_update_total",
                        "compaction_runs_total"):
                assert key in planes, planes.keys()
        finally:
            idx.close()

    def test_cluster_override_and_create_seeding(self):
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        try:
            node.create_index("ovr-a", {"settings": {
                "index": {"number_of_shards": 1}}})
            svc_a = node.indices["ovr-a"]
            assert svc_a.staging_delta_enabled_override is None
            node.put_cluster_settings({"persistent": {
                "index.staging.delta.enabled": False,
                "index.staging.compact.threshold": 0.5}})
            assert svc_a.staging_delta_enabled_override is False
            assert svc_a.staging_compact_threshold_override == 0.5
            assert svc_a._compact_threshold() == 0.5
            # an index created AFTER the commit honors the live value
            node.create_index("ovr-b", {"settings": {
                "index": {"number_of_shards": 1}}})
            svc_b = node.indices["ovr-b"]
            assert svc_b.staging_delta_enabled_override is False
            assert svc_b.staging_compact_threshold_override == 0.5
            # clearing hands control back to each index's own setting
            node.put_cluster_settings({"persistent": {
                "index.staging.delta.enabled": None,
                "index.staging.compact.threshold": None}})
            assert svc_a.staging_delta_enabled_override is None
            assert svc_a._compact_threshold() == 0.25  # default
        finally:
            node.close()

    def test_cat_staging_shows_slot_columns(self):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        client = Client(node)
        try:
            node.create_index("cat-d", {"settings": {"index": {
                "number_of_shards": 2, "refresh_interval": -1,
                "search": {"mesh": True}}},
                "mappings": MAPPING})
            svc = node.indices["cat-d"]
            for d in range(24):
                svc.index_doc(str(d), _doc(d))
            svc.refresh()
            svc.search({"query": {"match": {"body": "common"}},
                        "size": 5})
            status, out = client.perform("GET", "/_cat/staging",
                                         params={"v": "true"})
            assert status == 200
            header = out.splitlines()[0]
            assert "free_slots_per_dev" in header
            assert "tombstone_density" in header
            ms = svc._mesh_search
            if ms is not None and ms._executor is not None:
                assert "/slot0" in out  # per-slot summary rows
        finally:
            node.close()
