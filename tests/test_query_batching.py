"""Cross-query micro-batching on the Pallas scoring plane (ISSUE 5).

Covers the three layers:
- kernel: ``score_tiles(q_batch=Q)`` over union tables scores every
  member of a heterogeneous batch exactly like Q serial launches
  (dense + fused-top-k variants, minimum_should_match counts);
- service: ``IndexService.search_batch`` parity with serial execution
  for mixed term counts / k / min_score / aggs, per-member deadline
  expiry and ``_tasks/_cancel`` isolation, PlaneFailScheme quarantining
  the mesh_pallas plane exactly once per batch;
- scheduler: ``MicroBatcher`` groups only under real concurrency (a
  lone query takes the unbatched path with no window wait), seals at
  max_queries, and delivers per-member exceptions.

Everything runs the kernel in interpret mode on the CPU backend — the
same semantics the compiled TPU path executes (tests/test_pallas_scoring
idiom).
"""

import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import TaskCancelledException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search.batching import (
    BatchStats,
    MicroBatcher,
    batchable_body,
)
from elasticsearch_tpu.search.cancellation import SearchDeadline
from elasticsearch_tpu.testing.disruption import (
    PlaneFailScheme,
    clear_search_disruptions,
)

MAPPING = {
    "properties": {
        "body": {"type": "text", "analyzer": "whitespace"},
        "n": {"type": "integer"},
        "tag": {"type": "keyword"},
    }
}


@pytest.fixture(autouse=True)
def _interpret_kernel(monkeypatch):
    monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
    yield
    clear_search_disruptions()


def build_index(n_shards=3, n_docs=120, seed=0, **extra_settings):
    idx = IndexService(
        f"batching-{n_shards}s", Settings({
            "index.number_of_shards": n_shards,
            "index.refresh_interval": -1, **extra_settings}),
        mapping=MAPPING)
    rng = np.random.RandomState(seed)
    vocab = [f"t{i}" for i in range(15)]
    tags = ["red", "green", "blue"]
    for d in range(n_docs):
        toks = [vocab[rng.randint(len(vocab))]
                for _ in range(rng.randint(3, 9))]
        idx.index_doc(str(d), {"body": " ".join(toks), "n": d,
                               "tag": tags[d % 3]})
    idx.refresh()
    return idx


# heterogeneous member mix: different term counts, k, min_score, aggs,
# minimum_should_match — the batch must reproduce each serially
HETERO_BODIES = [
    {"query": {"match": {"body": "t0 t1"}}, "size": 5},
    {"query": {"match": {"body": "t1"}}, "size": 3},
    {"query": {"match": {"body": "t2 t3 t4"}}, "size": 7,
     "min_score": 0.1},
    {"query": {"match": {"body": "t0 t5"}}, "size": 4,
     "aggs": {"tags": {"terms": {"field": "tag"}}}},
    {"query": {"match": {"body": {"query": "t0 t1 t2",
                                  "minimum_should_match": 2}}},
     "size": 5},
]


def assert_member_parity(idx, body, got):
    want = idx._search_uncached(dict(body), skip_mesh=True)
    assert got["hits"]["total"] == want["hits"]["total"], body
    assert ([h["_id"] for h in got["hits"]["hits"]]
            == [h["_id"] for h in want["hits"]["hits"]]), body
    for g, w in zip(got["hits"]["hits"], want["hits"]["hits"]):
        if g["_score"] is not None:
            assert abs(g["_score"] - w["_score"]) < 1e-5, (g, w)
    if "aggs" in body:
        assert got["aggregations"] == want["aggregations"], body


class TestKernelBatch:
    """Direct q_batch kernel parity against the scatter oracle."""

    def _corpus(self, rng, nd=1500, vocab=20):
        from elasticsearch_tpu.ops import pallas_scoring as psc

        nd_pad = psc.next_pow2(nd)
        bd, bt, starts, counts = [], [], [], []
        for _ in range(vocab):
            df = rng.randint(1, 300)
            docs = np.sort(rng.choice(nd, size=min(df, nd),
                                      replace=False)).astype(np.int32)
            tfs = rng.randint(1, 5, size=len(docs)).astype(np.float32)
            nb = -(-len(docs) // psc.LANE)
            starts.append(len(bd))
            counts.append(nb)
            for i in range(nb):
                d = np.full(psc.LANE, nd_pad, np.int32)
                f = np.zeros(psc.LANE, np.float32)
                chunk = docs[i * psc.LANE:(i + 1) * psc.LANE]
                d[: len(chunk)] = chunk
                f[: len(chunk)] = tfs[i * psc.LANE:(i + 1) * psc.LANE]
                bd.append(d)
                bt.append(f)
        return np.stack(bd), np.stack(bt), starts, counts, nd_pad

    def test_batched_dense_and_topk_match_serial(self):
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import pallas_scoring as psc

        rng = np.random.RandomState(3)
        block_docs, block_tfs, starts, counts, nd_pad = self._corpus(rng)
        doc_len = np.full(nd_pad + 1, 10.0, np.float32)
        frac = psc.compute_block_frac(block_docs, block_tfs, doc_len, 10.0)
        bmin, bmax = psc.block_min_max(block_docs, block_tfs, nd_pad)
        dp, fp = psc.pad_segment_blocks(block_docs, frac, nd_pad)
        live = np.ones(nd_pad, np.float32)
        live[1400:] = 0.0
        geom = psc.tile_geometry(nd_pad, tile_sub=4)
        live_t = psc.build_live_t(live, geom)
        # heterogeneous lane sets, incl. a shared term (lane dedup) and
        # different term counts
        lane_sets = [
            [psc.QueryLane(starts[0], counts[0], 1.3),
             psc.QueryLane(starts[3], counts[3], 0.7)],
            [psc.QueryLane(starts[3], counts[3], 2.0)],
            [psc.QueryLane(starts[5], counts[5], 0.4),
             psc.QueryLane(starts[7], counts[7], 1.1),
             psc.QueryLane(starts[9], counts[9], 0.9)],
        ]
        q_n = len(lane_sets)
        rl, rh, weights, cb = psc.build_tile_tables_batched(
            lane_sets, bmin, bmax, geom)
        args = (jnp.asarray(dp), jnp.asarray(fp), jnp.asarray(live_t),
                jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(weights))
        kw = dict(t_pad=rl.shape[1], cb=cb, sub=geom.tile_sub,
                  interpret=True, q_batch=q_n)
        dense, counts_out = psc.score_tiles(*args, dense=True,
                                            with_counts=True, **kw)
        ts_, td_, th_ = psc.score_tiles(*args, k=10, **kw)
        top_s, top_d, hits = psc.merge_tile_topk_batched(ts_, td_, th_, 10)
        for q, lanes in enumerate(lane_sets):
            ref = psc.reference_scores(block_docs, frac, lanes, nd_pad)
            ref = np.where(live[:nd_pad] > 0, ref[:nd_pad], 0.0)
            got = np.asarray(psc.dense_to_flat(dense[q], geom.tile_sub))
            got = got[:nd_pad] * (live[:nd_pad] > 0)
            np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)
            expect = np.sort(ref[ref > 0])[::-1][:10]
            got_s = np.asarray(top_s[q])
            got_s = got_s[got_s > -np.inf]
            np.testing.assert_allclose(got_s, expect[: len(got_s)],
                                       rtol=2e-5)
            assert int(hits[q]) == int((ref > 0).sum())
            # per-query live-lane mask: counts only count the member's
            # own lanes (never another query's)
            cnt = np.asarray(psc.dense_to_flat(counts_out[q],
                                               geom.tile_sub))[:nd_pad]
            assert cnt.max() <= len(lanes) + 1e-6

    def test_union_lanes_dedup_and_masks(self):
        from elasticsearch_tpu.ops import pallas_scoring as psc

        a = psc.QueryLane(0, 2, 1.5)
        b = psc.QueryLane(4, 1, 0.5)
        union, weights = psc.union_query_lanes([[a, b], [a], []])
        assert len(union) == 2
        assert weights.shape[0] == 3
        np.testing.assert_allclose(weights[0, :2], [1.5, 0.5])
        np.testing.assert_allclose(weights[1, :2], [1.5, 0.0])
        assert (weights[2] == 0).all()


class TestSearchBatchParity:
    def test_heterogeneous_batch_matches_serial(self):
        idx = build_index(n_shards=2)
        try:
            out = idx.search_batch([dict(b) for b in HETERO_BODIES])
            for body, got in zip(HETERO_BODIES, out):
                assert isinstance(got, dict), got
                assert_member_parity(idx, body, got)
            stats = idx.batch_stats.as_dict()
            assert stats["batched_query_total"] == len(HETERO_BODIES)
            assert stats["batch_size_histogram"] == {
                str(len(HETERO_BODIES)): 1}
        finally:
            idx.close()

    def test_mesh_pallas_batched_rung(self):
        idx = build_index(n_shards=3)
        try:
            bodies = [
                {"query": {"match": {"body": "t0 t1"}}, "size": 5},
                {"query": {"match": {"body": "t1 t2"}}, "size": 3},
                {"query": {"match": {"body": "t3"}}, "size": 6},
            ]
            out = idx.search_batch([dict(b) for b in bodies])
            for body, got in zip(bodies, out):
                assert isinstance(got, dict), got
                # _plane reports per-query truth: every member was
                # scored by the batched mesh_pallas launch
                assert got["_plane"] == "mesh_pallas", got
                assert_member_parity(idx, body, got)
            assert idx._mesh_search.batched_launch_total == 1
            assert idx._mesh_search.batched_query_total == 3
            assert idx.batch_stats.as_dict()["batched_query_total"] == 3
        finally:
            idx.close()

    def test_single_shard_uses_host_rung(self):
        idx = build_index(n_shards=1)
        try:
            bodies = [
                {"query": {"match": {"body": "t0 t1"}}, "size": 5},
                {"query": {"match": {"body": "t2"}}, "size": 5},
            ]
            out = idx.search_batch([dict(b) for b in bodies])
            for body, got in zip(bodies, out):
                assert isinstance(got, dict)
                assert got["_plane"] == "host"
                assert_member_parity(idx, body, got)
            assert idx.batch_stats.as_dict()["batched_query_total"] == 2
        finally:
            idx.close()

    def test_unbatchable_member_executes_serially_in_batch(self):
        idx = build_index(n_shards=2)
        try:
            bodies = [
                {"query": {"match": {"body": "t0 t1"}}, "size": 5},
                {"query": {"match": {"body": "t1"}}, "size": 5},
                # collapse is not batchable: still answered, serially
                {"query": {"match": {"body": "t2"}}, "size": 5,
                 "collapse": {"field": "tag"}},
                # profile IS batchable (ISSUE 8 plane-truthfulness): the
                # member joins the shared launch and reports its batch
                # shape in the profile annotations
                {"query": {"match": {"body": "t2"}}, "profile": True},
            ]
            out = idx.search_batch([dict(b) for b in bodies])
            assert all(isinstance(r, dict) for r in out)
            assert out[2]["_plane"] == "host"  # collapse: serial rung
            assert "profile" in out[3]
            assert out[3]["profile"]["annotations"].get("batch_size") == 3
            assert_member_parity(idx, bodies[0], out[0])
        finally:
            idx.close()


class TestBatchFaultTolerance:
    def test_expired_member_partial_while_peers_complete(self):
        idx = build_index(n_shards=2)
        try:
            expired = SearchDeadline(1e-9)
            time.sleep(0.01)
            out = idx.search_batch(
                [{"query": {"match": {"body": "t0 t1"}}, "size": 5},
                 {"query": {"match": {"body": "t1 t2"}}, "size": 5}],
                [expired, None])
            assert isinstance(out[0], dict)
            assert out[0]["timed_out"] is True
            assert out[0]["hits"]["hits"] == []  # partial: nothing ran
            assert isinstance(out[1], dict)
            assert out[1]["timed_out"] is False
            assert out[1]["hits"]["hits"]
        finally:
            idx.close()

    def test_cancelled_member_does_not_cancel_batch(self):
        idx = build_index(n_shards=2)
        try:
            class _CancelledTask:
                def ensure_not_cancelled(self):
                    raise TaskCancelledException("task cancelled")

            dl = SearchDeadline(None, task=_CancelledTask())
            out = idx.search_batch(
                [{"query": {"match": {"body": "t0"}}, "size": 5},
                 {"query": {"match": {"body": "t1 t2"}}, "size": 5}],
                [dl, None])
            assert isinstance(out[0], TaskCancelledException)
            assert isinstance(out[1], dict)
            assert out[1]["hits"]["hits"]
        finally:
            idx.close()

    def test_plane_fault_quarantines_once_per_batch(self):
        idx = build_index(n_shards=3)
        try:
            scheme = PlaneFailScheme(planes=["mesh_pallas"]).install()
            out = idx.search_batch(
                [{"query": {"match": {"body": "t0 t1"}}, "size": 5},
                 {"query": {"match": {"body": "t1 t2"}}, "size": 5},
                 {"query": {"match": {"body": "t3"}}, "size": 5}])
            # every member still answered (host rung), one quarantine
            for r in out:
                assert isinstance(r, dict), r
                assert r["_plane"] == "host"
                assert r["hits"]["total"] > 0
            ph = idx._mesh_search.plane_health
            assert ph.failures_total["mesh_pallas"] == 1  # not Q times
            assert scheme.hits == 1
            assert "mesh_pallas" in ph.quarantined()
        finally:
            idx.close()

    def test_duplicate_term_msm_member_matches_serial(self):
        """Review regression: a repeated term under operator:and counts
        each duplicate lane serially, but the union dedupes the posting
        run — such members must execute serially, not lose all hits."""
        idx = build_index(n_shards=1)
        try:
            dup = {"query": {"match": {"body": {
                "query": "t1 t1", "operator": "and"}}}, "size": 5}
            peer = {"query": {"match": {"body": "t2"}}, "size": 5}
            serial = idx._search_uncached(dict(dup), skip_mesh=True)
            out = idx.search_batch([dict(dup), dict(peer)])
            assert isinstance(out[0], dict)
            assert out[0]["hits"]["total"] == serial["hits"]["total"]
            assert out[0]["hits"]["total"] > 0
        finally:
            idx.close()

    def test_malformed_member_is_request_error_not_plane_fault(self):
        """Review regression: a malformed body in a batch is that
        member's 4xx, never a mesh_pallas quarantine."""
        idx = build_index(n_shards=3)
        try:
            out = idx.search_batch(
                [{"query": {"match": {"body": "t0 t1"}}, "size": 5},
                 {"query": {"nosuch_query": {}}, "size": 5}])
            assert isinstance(out[0], dict)
            assert isinstance(out[1], Exception)
            ph = idx._mesh_search.plane_health
            assert ph.failures_total["mesh_pallas"] == 0
            assert ph.available("mesh_pallas")
        finally:
            idx.close()

    def test_batch_settings_dynamic_via_cluster_settings(self):
        """Review regression: search.batch.* are dynamic — a cluster
        settings update must reach existing indices' live batchers."""
        from elasticsearch_tpu.node import Node

        node = Node(Settings())
        node.create_index("dyn", {"settings": {"number_of_shards": 1}})
        batcher = node.indices["dyn"]._batcher
        assert batcher.enabled is True
        node.put_cluster_settings({"transient": {
            "search.batch.enabled": False,
            "search.batch.max_queries": 5,
            "search.batch.window_ms": 1.5}})
        assert batcher.enabled is False
        assert batcher.max_queries == 5
        assert abs(batcher.window_s - 0.0015) < 1e-9

    def test_stats_block_exported(self):
        idx = build_index(n_shards=2)
        try:
            idx.search_batch(
                [{"query": {"match": {"body": "t0 t1"}}, "size": 5},
                 {"query": {"match": {"body": "t1"}}, "size": 5}])
            batch = idx.stats()["primaries"]["search"]["batch"]
            assert batch["batched_query_total"] == 2
            assert batch["batch_size_histogram"] == {"2": 1}
            assert "batch_window_waits_total" in batch
        finally:
            idx.close()


@pytest.mark.slow
class TestPackedMeshBatchedBurst:
    """The dryrun_multichip phase-3 assertion as a test: a PACKED mesh
    corpus (segments > devices, slot packing) serves a concurrent burst
    via ONE batched mesh_pallas launch."""

    def test_packed_corpus_burst_one_launch(self):
        idx = IndexService("packed-burst", Settings({
            "index.number_of_shards": 8,
            "index.refresh_interval": -1}), mapping=MAPPING)
        try:
            rng = np.random.RandomState(5)
            vocab = [f"t{i}" for i in range(15)]
            for batch in range(2):  # two refreshes: 2 segments/shard
                for d in range(batch * 64, (batch + 1) * 64):
                    toks = [vocab[rng.randint(len(vocab))]
                            for _ in range(rng.randint(3, 9))]
                    idx.index_doc(str(d), {"body": " ".join(toks),
                                           "n": d, "tag": "x"})
                idx.refresh()
            import jax

            n_pairs = sum(
                1 for sid in idx.shards
                for seg in idx.shards[sid].engine.searchable_segments()
                if seg.num_docs > 0)
            assert n_pairs > len(jax.devices()), "corpus must pack slots"
            burst = [
                {"query": {"match": {"body": "t0 t1"}}, "size": 5},
                {"query": {"match": {"body": "t2"}}, "size": 4},
                {"query": {"match": {"body": "t3 t4 t5"}}, "size": 6},
                {"query": {"match": {"body": "t1 t6"}}, "size": 5},
            ]
            out = idx.search_batch([dict(b) for b in burst])
            assert idx._mesh_search.batched_launch_total == 1
            assert (idx.batch_stats.as_dict()["batched_query_total"]
                    == len(burst))
            for body, got in zip(burst, out):
                assert isinstance(got, dict), got
                assert got["_plane"] == "mesh_pallas", got
                assert_member_parity(idx, body, got)
        finally:
            idx.close()


class TestMicroBatcher:
    def test_no_concurrency_goes_direct(self):
        stats = BatchStats()
        mb = MicroBatcher(window_s=0.5, max_queries=8, stats=stats)
        t0 = time.monotonic()
        out = mb.run("k", 1, single_fn=lambda x: x * 10,
                     batch_fn=lambda items: [x * 100 for x in items])
        assert out == 10  # unbatched path
        assert time.monotonic() - t0 < 0.25  # no window paid
        assert stats.as_dict()["batch_window_waits_total"] == 0

    def test_concurrent_submissions_batch(self):
        stats = BatchStats()
        mb = MicroBatcher(window_s=0.3, max_queries=8, stats=stats)
        start = threading.Barrier(3)
        results = {}

        def slow_single(x):
            # keep the inflight slot occupied long enough that the other
            # two submissions demonstrably overlap and form one group
            time.sleep(0.15)
            return ("single", x)

        def worker(i):
            start.wait()
            results[i] = mb.run(
                "k", i, single_fn=slow_single,
                batch_fn=lambda items: [("batch", x) for x in items])

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # one thread won the no-concurrency race and went direct; the
        # other two met in one batch
        kinds = sorted(kind for kind, _ in results.values())
        assert kinds.count("batch") >= 2
        for i in range(3):
            assert results[i][1] == i
        assert stats.as_dict()["batch_window_waits_total"] == 1

    def test_full_group_seals_at_max_queries(self):
        mb = MicroBatcher(window_s=5.0, max_queries=2)
        blocker = threading.Event()
        results = {}

        def occupy():
            mb.run("other", 0,
                   single_fn=lambda x: blocker.wait(5.0),
                   batch_fn=lambda items: [None for _ in items])

        def worker(i):
            results[i] = mb.run(
                "k", i, single_fn=lambda x: ("single", x),
                batch_fn=lambda items: [("batch", x) for x in items])

        t0 = threading.Thread(target=occupy)
        t0.start()
        time.sleep(0.05)  # occupy() holds the inflight slot
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(2)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        blocker.set()
        t0.join(10.0)
        # the full group dispatched WITHOUT waiting the 5s window
        assert time.monotonic() - t_start < 4.0
        assert results[0] == ("batch", 0)
        assert results[1] == ("batch", 1)

    def test_member_exception_isolated(self):
        mb = MicroBatcher(window_s=0.2, max_queries=4)
        start = threading.Barrier(2)
        outcomes = {}

        def batch_fn(items):
            return [ValueError(f"boom-{x}") if x == 1 else ("ok", x)
                    for x in items]

        def worker(i):
            start.wait()
            try:
                outcomes[i] = mb.run("k", i,
                                     single_fn=lambda x: ("ok", x),
                                     batch_fn=batch_fn)
            except ValueError as e:
                outcomes[i] = e

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # whichever member carried x == 1 got ITS error; the other
        # member's result is intact (one went direct if it won the race)
        vals = list(outcomes.values())
        assert any(v == ("ok", 0) for v in vals)
        assert any(isinstance(v, ValueError) or v == ("ok", 1)
                   for v in vals if v != ("ok", 0))

    def test_batchable_body_filter(self):
        assert batchable_body({"query": {"match": {"body": "x"}}})
        assert batchable_body({"query": {"term": {"tag": "a"}},
                               "size": 3, "min_score": 0.5,
                               "aggs": {"t": {"terms": {"field": "tag"}}}})
        assert not batchable_body({})  # no query
        # profile rides the batch (ISSUE 8): plane-truthful profiling
        # must not demote the member off the shared launch
        assert batchable_body({"query": {"match": {"b": "x"}},
                               "profile": True})
        assert not batchable_body({"query": {"match": {"b": "x"}},
                                   "collapse": {"field": "tag"}})
