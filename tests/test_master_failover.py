"""Master fault detection + re-election tests.

Role models: MasterFaultDetection.java:56 (nodes ping the master),
ZenDiscovery.handleMasterGone + ElectMasterService.electMaster (lowest-id
master-eligible node wins), and the term-fencing guarantee that a deposed
master's in-flight writes are rejected by promoted primaries."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.multinode import ClusterClient, ClusterNode
from elasticsearch_tpu.common.errors import NodeNotConnectedException
from elasticsearch_tpu.transport.local import TransportHub


def cluster(names=("n1", "n2", "n3"), eligibility=None):
    hub = TransportHub(strict_serialization=True)
    nodes = {}
    for name in names:
        eligible = True if eligibility is None else eligibility[name]
        nodes[name] = ClusterNode(name, hub, master_eligible=eligible)
    first = names[0]
    nodes[first].bootstrap_cluster()
    for name in names[1:]:
        nodes[name].join(first)
    return hub, nodes


def seed_index(nodes, master="n1", docs=12):
    nodes[master].create_index(
        "logs",
        {"index": {"number_of_shards": 2, "number_of_replicas": 1}},
        {"properties": {"msg": {"type": "text"}}})
    client = ClusterClient(nodes[master])
    for i in range(docs):
        client.index("logs", str(i), {"msg": f"event {i}"})
    for n in nodes.values():
        if n.node_id != master:
            ClusterClient(n).refresh("logs")
            break
    return client


class TestMasterFailover:
    def test_lowest_eligible_survivor_takes_over(self):
        hub, nodes = cluster()
        seed_index(nodes)
        v_before = nodes["n2"].state_version
        hub.disconnect("n1")  # master dies

        assert nodes["n2"].check_master() == "n2"
        assert nodes["n2"].is_master
        assert "n1" not in nodes["n2"].known_nodes
        assert nodes["n2"].state_version > v_before
        # publish reached n3
        assert nodes["n3"].master_id == "n2"
        assert "n1" not in nodes["n3"].known_nodes

        # no primary remains on the dead master; terms bumped where moved
        for index, shards in nodes["n2"].routing.items():
            for sid, copies in shards.items():
                primaries = [c for c in copies if c.primary]
                assert len(primaries) == 1
                assert primaries[0].node_id != "n1"

    def test_no_acked_write_lost_across_failover(self):
        hub, nodes = cluster()
        client = seed_index(nodes, docs=15)
        hub.disconnect("n1")
        nodes["n2"].check_master()
        survivor = ClusterClient(nodes["n3"])
        survivor.refresh("logs")
        res = survivor.search("logs", {"query": {"match": {"msg": "event"}},
                                       "size": 30})
        assert res["hits"]["total"] == 15

    def test_non_winner_adopts_winner_then_converges(self):
        hub, nodes = cluster()
        seed_index(nodes)
        hub.disconnect("n1")
        # the non-winner detects first: adopts n2 tentatively
        assert nodes["n3"].check_master() == "n2"
        assert not nodes["n3"].is_master
        # winner's own tick completes the election and publishes
        assert nodes["n2"].check_master() == "n2"
        assert nodes["n3"].master_id == "n2"
        assert nodes["n3"].state_version == nodes["n2"].state_version

    def test_ineligible_node_never_elected(self):
        hub, nodes = cluster(eligibility={"n1": True, "n2": False,
                                          "n3": True})
        seed_index(nodes)
        hub.disconnect("n1")
        assert nodes["n2"].check_master() == "n3"
        assert not nodes["n2"].is_master
        assert nodes["n3"].check_master() == "n3"
        assert nodes["n3"].is_master
        assert nodes["n2"].master_id == "n3"

    def test_deposed_master_writes_fenced_by_term(self):
        """Partition (not death): the old master keeps acting on its stale
        primaries; promoted primaries carry a bumped term, so its
        replica-path replication is rejected and its locally-acked writes
        never reach (or diverge) the true cluster."""
        from elasticsearch_tpu.cluster.multinode import ACTION_WRITE_REPLICA
        from elasticsearch_tpu.common.errors import (
            ElasticsearchTpuException,
        )
        from elasticsearch_tpu.utils.murmur3 import shard_id_for

        hub, nodes = cluster()
        client1 = seed_index(nodes)
        old_terms = dict(nodes["n1"].primary_terms)
        old_primaries = {
            (idx, sid): next(c.node_id for c in copies if c.primary)
            for idx, shards in nodes["n1"].routing.items()
            for sid, copies in shards.items()}
        hub.disconnect("n1")
        nodes["n2"].check_master()
        new_terms = nodes["n2"].primary_terms
        moved = {k for k, t in new_terms.items() if t > old_terms.get(k, 1)}
        assert moved, "expected at least one promoted primary"

        # direct fencing: a replica-path op at the stale term is rejected
        (idx, sid) = next(iter(moved))
        new_primary = next(
            c.node_id for c in nodes["n2"].routing[idx][sid] if c.primary)
        with pytest.raises(ElasticsearchTpuException,
                           match="primary term is too old"):
            nodes["n1"].transport.hub.heal()  # reconnect first
            nodes["n1"].transport.send_request(
                new_primary, ACTION_WRITE_REPLICA, {
                    "index": idx, "shard": sid, "op": "index",
                    "id": "fenced", "source": {"msg": "stale"},
                    "seq_no": 10_000, "version": 2,
                    "primary_term": old_terms[(idx, sid)],
                    "global_checkpoint": -1})

        # split brain: n1 still believes it is master and acks writes into
        # its stale local primaries — but none of those may surface on the
        # true cluster (no divergence)
        assert nodes["n1"].is_master  # stale belief
        n_shards = len(nodes["n2"].routing["logs"])
        for i in range(40):
            try:
                client1.index("logs", f"stale-{i}", {"msg": "stale write"})
            except Exception:
                pass
        survivor = ClusterClient(nodes["n3"])
        survivor.refresh("logs")
        res = survivor.search("logs", {"query": {"match": {"msg": "stale"}},
                                       "size": 100})
        visible = {h["_id"] for h in res["hits"]["hits"]}
        for doc_id in visible:
            sid = shard_id_for(doc_id, n_shards)
            # visible stale docs may only live on shards n1 legitimately
            # forwarded to the still-current primary — never on shards
            # whose primary moved away from n1
            assert ("logs", sid) not in moved or \
                old_primaries[("logs", sid)] != "n1"
        # the stale master's re-publishes carry the old epoch and must not
        # regress the followers' state
        assert nodes["n3"].master_id == "n2"
        assert nodes["n3"].cluster_epoch == nodes["n2"].cluster_epoch
        # the deposed master's own fault-detection tick sees the higher
        # epoch, steps down and rejoins the real cluster
        assert nodes["n1"].check_nodes() == []
        assert not nodes["n1"].is_master
        assert nodes["n1"].master_id == "n2"
        assert "n1" in nodes["n2"].known_nodes

    def test_double_failure_second_election(self):
        hub, nodes = cluster(names=("n1", "n2", "n3", "n4"))
        seed_index(nodes)
        hub.disconnect("n1")
        assert nodes["n2"].check_master() == "n2"
        hub.disconnect("n2")
        assert nodes["n3"].check_master() == "n3"
        assert nodes["n3"].is_master
        assert nodes["n4"].master_id == "n3"

    def test_dual_election_same_epoch_converges(self):
        """n1 dies while n2 and n3 are also partitioned from each other:
        both elect themselves at the same epoch. After healing, the
        lower-id master wins the tie-break and the other steps down —
        split brain must not be permanent."""
        hub, nodes = cluster()
        seed_index(nodes)
        hub.disconnect("n1")
        hub.disconnect("n2", "n3")
        assert nodes["n2"].check_master() == "n2"
        # n3: n2 unreachable too -> elects itself
        n3_view = nodes["n3"].check_master()
        if n3_view == "n2":  # first adopted the presumptive winner...
            n3_view = nodes["n3"].check_master()  # ...then finds it dead
        assert n3_view == "n3"
        assert nodes["n2"].is_master and nodes["n3"].is_master
        assert nodes["n2"].cluster_epoch >= 2
        # heal n2<->n3 (n1 stays dead): the higher-id master sees a
        # cluster with precedence and steps down
        hub.heal("n2")
        hub.disconnect("n1")
        assert nodes["n3"].check_nodes() == []
        assert not nodes["n3"].is_master
        assert nodes["n3"].master_id == "n2"
        assert nodes["n2"].check_nodes() == []  # n2 stays master
        assert nodes["n2"].is_master
        assert "n3" in nodes["n2"].known_nodes

    def test_headless_when_no_eligible_survivor(self):
        hub, nodes = cluster(eligibility={"n1": True, "n2": False,
                                          "n3": False})
        seed_index(nodes)
        hub.disconnect("n1")
        assert nodes["n2"].check_master() is None
        assert not nodes["n2"].is_master


def quorum_cluster(names=("n1", "n2", "n3"), mmn=2):
    hub = TransportHub(strict_serialization=True)
    nodes = {}
    for name in names:
        nodes[name] = ClusterNode(name, hub, min_master_nodes=mmn)
    nodes[names[0]].bootstrap_cluster()
    for name in names[1:]:
        nodes[name].join(names[0])
    return hub, nodes


class TestQuorum:
    """discovery.zen.minimum_master_nodes: split-brain guard on election
    AND publish commit (ElectMasterService.hasEnoughMasterNodes,
    PublishClusterStateAction commit quorum)."""

    def test_minority_partition_cannot_elect(self):
        hub, nodes = quorum_cluster()
        hub.disconnect("n3")  # n3 alone: 1 of 3 eligibles
        assert nodes["n3"].check_master() is None
        assert nodes["n3"].master_id in (None, "n1")  # never itself
        assert not nodes["n3"].is_master

    def test_majority_partition_elects(self):
        hub, nodes = quorum_cluster()
        hub.disconnect("n1")  # master isolated; n2+n3 = 2 >= quorum
        winner = nodes["n2"].check_master()
        assert winner == "n2"
        assert nodes["n2"].is_master
        # the new state committed on the majority side
        assert nodes["n3"].check_master() in ("n2", None)
        assert nodes["n3"].master_id == "n2"

    def test_isolated_master_steps_down(self):
        hub, nodes = quorum_cluster()
        hub.disconnect("n1")
        nodes["n1"].check_nodes()  # sees both peers gone -> quorum lost
        assert not nodes["n1"].is_master
        assert nodes["n1"].master_id is None

    def test_publish_without_quorum_steps_down(self):
        from elasticsearch_tpu.cluster.multinode import (
            FailedToCommitClusterStateException,
        )

        hub, nodes = quorum_cluster()
        hub.disconnect("n1")
        # n1 still believes it is master and tries to mutate state: the
        # commit quorum fails, the client SEES the failure (the reference
        # throws FailedToCommitClusterStateException), and n1 steps down
        with pytest.raises(FailedToCommitClusterStateException):
            nodes["n1"].create_index(
                "ghost", {"index": {"number_of_shards": 1,
                                    "number_of_replicas": 0}})
        assert not nodes["n1"].is_master
        assert "ghost" not in nodes["n2"].indices_meta
        assert "ghost" not in nodes["n3"].indices_meta
        # the minority master must NOT keep serving the uncommitted
        # change: the client was told the state did not commit, so the
        # index must not exist on n1 either (the reference master only
        # applies after the publish quorum acks) — local meta, routing,
        # and shard instances all roll back to the committed snapshot
        assert "ghost" not in nodes["n1"].indices_meta
        assert "ghost" not in nodes["n1"].routing
        assert not any(k[0] == "ghost" for k in nodes["n1"].shards)

    def test_delete_rollback_resurrects_shard_data(self):
        """A minority master rolling back an uncommitted delete_index
        must bring the LOCAL shard copies back with their data: the
        self-applied delete closed them, and recreating them empty
        (start_fresh) would lose the master's copy while telling the
        client the delete never happened."""
        from elasticsearch_tpu.cluster.multinode import (
            ClusterClient,
            FailedToCommitClusterStateException,
        )

        hub, nodes = quorum_cluster()
        nodes["n1"].create_index(
            "keep", {"index": {"number_of_shards": 2,
                               "number_of_replicas": 0}},
            {"properties": {"msg": {"type": "text"}}})
        client = ClusterClient(nodes["n1"])
        for i in range(8):
            client.index("keep", str(i), {"msg": f"event {i}"})
        client.refresh("keep")
        before = client.search("keep", {"query": {"match_all": {}}})
        assert before["hits"]["total"] == 8

        def local_docs():
            return sum(s.num_docs
                       for (idx, _), s in nodes["n1"].shards.items()
                       if idx == "keep")

        before_local = local_docs()
        assert before_local > 0  # n1 hosts at least one shard copy
        hub.disconnect("n1")
        with pytest.raises(FailedToCommitClusterStateException):
            nodes["n1"].delete_index("keep")
        # metadata rolled back AND the local shard data survived
        assert "keep" in nodes["n1"].indices_meta
        assert local_docs() == before_local

    def test_headless_node_recovers_via_fd_tick(self):
        hub, nodes = quorum_cluster()
        hub.disconnect("n1")
        nodes["n2"].check_master()   # majority elects n2
        nodes["n1"].check_nodes()    # minority master steps down
        assert nodes["n1"].master_id is None
        hub.heal()
        # the production FD tick path (check_master with no master) must
        # rejoin without manual intervention
        assert nodes["n1"].check_master() == "n2"
        assert nodes["n1"].master_id == "n2"

    def test_stale_epoch_publish_rejected_in_phase1(self):
        from elasticsearch_tpu.cluster.multinode import ACTION_PUBLISH

        hub, nodes = quorum_cluster()
        hub.disconnect("n1")
        nodes["n2"].check_master()  # epoch bumped on majority side
        hub.heal()
        stale = nodes["n1"]._state_dict()  # old epoch
        resp = nodes["n1"].transport.send_request("n2", ACTION_PUBLISH, stale)
        assert resp["ok"] is False and "stale" in resp["reason"]

    def test_two_phase_follower_applies_only_on_commit(self):
        from elasticsearch_tpu.cluster.multinode import (
            ACTION_COMMIT,
            ACTION_PUBLISH,
        )

        hub, nodes = quorum_cluster()
        n1, n2 = nodes["n1"], nodes["n2"]
        state = n1._state_dict()
        state["version"] += 1
        # phase 1: buffered, NOT applied
        n1.transport.send_request("n2", ACTION_PUBLISH, state)
        assert n2.state_version == state["version"] - 1
        assert n2._pending_publish is not None
        # phase 2: commit applies it
        n1.transport.send_request("n2", ACTION_COMMIT, {
            "epoch": state["epoch"], "version": state["version"]})
        assert n2.state_version == state["version"]
        assert n2._pending_publish is None

    def test_healed_partition_reconverges(self):
        hub, nodes = quorum_cluster()
        hub.disconnect("n1")
        nodes["n2"].check_master()   # majority elects n2
        nodes["n1"].check_nodes()    # minority master steps down
        hub.heal()
        # deposed n1 notices the higher-epoch cluster on its next tick
        nodes["n1"].join("n2")
        assert nodes["n1"].master_id == "n2"
        assert nodes["n1"].cluster_epoch == nodes["n2"].cluster_epoch
