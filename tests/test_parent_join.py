"""Parent-join module: join field, has_child/has_parent/parent_id queries,
children agg (ref: modules/parent-join — ParentJoinFieldMapper,
HasChildQueryBuilder:62, HasParentQueryBuilder, ChildrenAggregationBuilder)."""

import pytest

from elasticsearch_tpu.common.errors import MapperParsingException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def hit_ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


@pytest.fixture()
def qa():
    """question/answer corpus: q1 has 2 answers, q2 has 1, q3 has none."""
    idx = IndexService("qa", Settings({"index.number_of_shards": 1}))
    idx.put_mapping({"properties": {
        "my_join": {"type": "join", "relations": {"question": "answer"}},
        "title": {"type": "text"},
        "body": {"type": "text"},
        "votes": {"type": "long"},
    }})
    idx.index_doc("q1", {"my_join": "question", "title": "how to train a dog"})
    idx.index_doc("q2", {"my_join": "question", "title": "how to cook rice"})
    idx.index_doc("q3", {"my_join": "question", "title": "unanswered question"})
    idx.index_doc("a1", {"my_join": {"name": "answer", "parent": "q1"},
                         "body": "use positive reinforcement", "votes": 5})
    idx.index_doc("a2", {"my_join": {"name": "answer", "parent": "q1"},
                         "body": "daily training with treats", "votes": 2})
    idx.index_doc("a3", {"my_join": {"name": "answer", "parent": "q2"},
                         "body": "use a rice cooker", "votes": 9})
    idx.refresh()
    yield idx
    idx.close()


class TestJoinField:
    def test_term_query_on_relation(self, qa):
        resp = qa.search({"query": {"term": {"my_join": "question"}}})
        assert hit_ids(resp) == ["q1", "q2", "q3"]
        resp = qa.search({"query": {"term": {"my_join": "answer"}}})
        assert hit_ids(resp) == ["a1", "a2", "a3"]

    def test_child_requires_parent(self, qa):
        with pytest.raises(MapperParsingException):
            qa.index_doc("bad", {"my_join": "answer"})

    def test_unknown_relation_rejected(self, qa):
        with pytest.raises(MapperParsingException):
            qa.index_doc("bad", {"my_join": "comment"})

    def test_parent_with_parent_param_rejected(self, qa):
        with pytest.raises(MapperParsingException):
            qa.index_doc("bad", {"my_join": {"name": "question", "parent": "q1"}})


class TestHasChild:
    def test_basic(self, qa):
        resp = qa.search({"query": {"has_child": {
            "type": "answer", "query": {"match": {"body": "training"}}}}})
        assert hit_ids(resp) == ["q1"]

    def test_all_children(self, qa):
        resp = qa.search({"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}}}})
        assert hit_ids(resp) == ["q1", "q2"]  # q3 has no answers

    def test_min_children(self, qa):
        resp = qa.search({"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}, "min_children": 2}}})
        assert hit_ids(resp) == ["q1"]

    def test_max_children(self, qa):
        resp = qa.search({"query": {"has_child": {
            "type": "answer", "query": {"match_all": {}}, "max_children": 1}}})
        assert hit_ids(resp) == ["q2"]

    def test_score_mode_sum(self, qa):
        resp = qa.search({"query": {"has_child": {
            "type": "answer",
            "query": {"function_score": {
                "query": {"match_all": {}},
                "field_value_factor": {"field": "votes"},
                "boost_mode": "replace"}},
            "score_mode": "sum"}}})
        by_id = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
        assert by_id["q1"] == pytest.approx(7.0)  # 5 + 2
        assert by_id["q2"] == pytest.approx(9.0)
        assert resp["hits"]["hits"][0]["_id"] == "q2"

    def test_score_mode_max_min_avg(self, qa):
        for mode, expected_q1 in (("max", 5.0), ("min", 2.0), ("avg", 3.5)):
            resp = qa.search({"query": {"has_child": {
                "type": "answer",
                "query": {"function_score": {
                    "query": {"match_all": {}},
                    "field_value_factor": {"field": "votes"},
                    "boost_mode": "replace"}},
                "score_mode": mode}}})
            by_id = {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}
            assert by_id["q1"] == pytest.approx(expected_q1), mode


class TestHasParent:
    def test_basic(self, qa):
        resp = qa.search({"query": {"has_parent": {
            "parent_type": "question", "query": {"match": {"title": "dog"}}}}})
        assert hit_ids(resp) == ["a1", "a2"]

    def test_score_true(self, qa):
        resp = qa.search({"query": {"has_parent": {
            "parent_type": "question", "query": {"match": {"title": "dog"}},
            "score": True}}})
        scores = [h["_score"] for h in resp["hits"]["hits"]]
        assert all(s > 0 for s in scores)
        assert scores[0] == scores[1]  # both children get the parent's score


class TestParentId:
    def test_parent_id(self, qa):
        resp = qa.search({"query": {"parent_id": {"type": "answer", "id": "q1"}}})
        assert hit_ids(resp) == ["a1", "a2"]
        resp = qa.search({"query": {"parent_id": {"type": "answer", "id": "q3"}}})
        assert hit_ids(resp) == []


class TestChildrenAgg:
    def test_children_agg(self, qa):
        resp = qa.search({
            "size": 0,
            "query": {"match": {"title": "dog"}},
            "aggs": {"answers": {
                "children": {"type": "answer"},
                "aggs": {"total_votes": {"sum": {"field": "votes"}}},
            }},
        })
        agg = resp["aggregations"]["answers"]
        assert agg["doc_count"] == 2
        assert agg["total_votes"]["value"] == pytest.approx(7.0)

    def test_children_under_terms(self, qa):
        resp = qa.search({
            "size": 0,
            "aggs": {"questions": {
                "terms": {"field": "my_join"},
                "aggs": {"kids": {"children": {"type": "answer"}}},
            }},
        })
        buckets = {b["key"]: b for b in
                   resp["aggregations"]["questions"]["buckets"]}
        assert buckets["question"]["kids"]["doc_count"] == 3

    def test_multishard_child_requires_routing(self):
        """RoutingMissingException parity: on multi-shard indices a child
        without routing is rejected; with routing=parent it joins."""
        from elasticsearch_tpu.common.errors import IllegalArgumentException

        idx = IndexService("qa3", Settings({"index.number_of_shards": 3}))
        idx.put_mapping({"properties": {
            "j": {"type": "join", "relations": {"p": "c"}}}})
        idx.index_doc("p1", {"j": "p"})
        with pytest.raises(IllegalArgumentException):
            idx.index_doc("c1", {"j": {"name": "c", "parent": "p1"}})
        idx.index_doc("c1", {"j": {"name": "c", "parent": "p1"}}, routing="p1")
        idx.refresh()
        resp = idx.search({"query": {"has_child": {
            "type": "c", "query": {"match_all": {}}}}})
        assert hit_ids(resp) == ["p1"]
        idx.close()

    def test_cross_segment_join(self):
        """Parent and child in different segments (separate refreshes)."""
        idx = IndexService("qa2", Settings({"index.number_of_shards": 1}))
        idx.put_mapping({"properties": {
            "j": {"type": "join", "relations": {"p": "c"}}}})
        idx.index_doc("p1", {"j": "p"})
        idx.refresh()  # segment 1: parent
        idx.index_doc("c1", {"j": {"name": "c", "parent": "p1"}})
        idx.refresh()  # segment 2: child
        resp = idx.search({"query": {"has_child": {
            "type": "c", "query": {"match_all": {}}}}})
        assert hit_ids(resp) == ["p1"]
        resp = idx.search({"query": {"has_parent": {
            "parent_type": "p", "query": {"match_all": {}}}}})
        assert hit_ids(resp) == ["c1"]
        idx.close()
