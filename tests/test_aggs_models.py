"""scripted_metric, diversified_sampler, moving_avg models (ref:
search/aggregations/metrics/scripted/, bucket/sampler/DiversifiedAggregatorFactory,
pipeline/movavg/models/ — Simple/Linear/Ewma/HoltLinear/HoltWinters)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def agg(resp, name):
    return resp["aggregations"][name]


@pytest.fixture(scope="module")
def series():
    """Monthly histogram series with values 1..6 plus a diversity field."""
    idx = IndexService("series", Settings({"index.number_of_shards": 1}))
    for i in range(6):
        idx.index_doc(str(i), {
            "t": i * 10,
            "v": float(i + 1),
            "author": "a" if i < 4 else "b",
            "body": "common words here",
        })
    idx.refresh()
    yield idx
    idx.close()


class TestScriptedMetric:
    def test_sum_expression(self, series):
        r = series.search({"size": 0, "aggs": {"m": {"scripted_metric": {
            "map_script": "doc['v'].value * 2"}}}})
        assert agg(r, "m")["value"] == pytest.approx(2 * (1 + 2 + 3 + 4 + 5 + 6))

    def test_with_params_and_reduce(self, series):
        r = series.search({"size": 0, "aggs": {"m": {"scripted_metric": {
            "map_script": "doc['v'].value * params.factor",
            "reduce_script": "params._agg / 3",
            "params": {"factor": 3},
        }}}})
        assert agg(r, "m")["value"] == pytest.approx(21.0)

    def test_doc_length(self, series):
        r = series.search({"size": 0, "aggs": {"m": {"scripted_metric": {
            "map_script": "doc['v'].length"}}}})
        assert agg(r, "m")["value"] == pytest.approx(6.0)  # one value per doc

    def test_scalar_division_by_zero_not_nan(self, series):
        r = series.search({"size": 0, "aggs": {"m": {"scripted_metric": {
            "map_script": "params.a / params.b",
            "params": {"a": 1, "b": 0}}}}})
        v = agg(r, "m")["value"]
        assert v == 0.0  # skipped segments, never NaN in the response

    def test_respects_query(self, series):
        r = series.search({"size": 0,
                           "query": {"range": {"v": {"gte": 5}}},
                           "aggs": {"m": {"scripted_metric": {
                               "map_script": "doc['v'].value"}}}})
        assert agg(r, "m")["value"] == pytest.approx(11.0)


class TestDiversifiedSampler:
    def test_caps_per_value(self, series):
        r = series.search({"size": 0, "aggs": {"s": {
            "diversified_sampler": {"field": "author", "shard_size": 10,
                                    "max_docs_per_value": 1},
            "aggs": {"n": {"value_count": {"field": "v"}}},
        }}})
        # one doc per distinct author value
        assert agg(r, "s")["doc_count"] == 2
        assert agg(r, "s")["n"]["value"] == 2

    def test_max_two_per_value(self, series):
        r = series.search({"size": 0, "aggs": {"s": {
            "diversified_sampler": {"field": "author", "shard_size": 10,
                                    "max_docs_per_value": 2},
        }}})
        assert agg(r, "s")["doc_count"] == 4  # 2 of "a" + 2 of "b"

    def test_sampler_takes_top_scoring(self, series):
        r = series.search({"size": 0,
                           "query": {"match": {"body": "common"}},
                           "aggs": {"s": {"sampler": {"shard_size": 3}}}})
        assert agg(r, "s")["doc_count"] == 3


def _histo_with_movavg(series, model, settings=None, predict=0, window=3):
    body = {"buckets_path": "s", "window": window, "model": model}
    if settings:
        body["settings"] = settings
    if predict:
        body["predict"] = predict
    return series.search({"size": 0, "aggs": {"h": {
        "histogram": {"field": "t", "interval": 10},
        "aggs": {"s": {"sum": {"field": "v"}},
                 "ma": {"moving_avg": body}},
    }}})


class TestMovingAvgModels:
    def test_simple(self, series):
        r = _histo_with_movavg(series, "simple")
        buckets = agg(r, "h")["buckets"]
        # bucket i holds mean of the previous <=3 values
        assert buckets[1]["ma"]["value"] == pytest.approx(1.0)
        assert buckets[3]["ma"]["value"] == pytest.approx(2.0)
        assert buckets[5]["ma"]["value"] == pytest.approx(4.0)

    def test_linear_weights_recent_higher(self, series):
        r = _histo_with_movavg(series, "linear")
        buckets = agg(r, "h")["buckets"]
        # window [2,3,4] -> (2*1+3*2+4*3)/6 = 20/6
        assert buckets[4]["ma"]["value"] == pytest.approx(20 / 6)

    def test_ewma(self, series):
        r = _histo_with_movavg(series, "ewma", settings={"alpha": 0.5})
        buckets = agg(r, "h")["buckets"]
        # window [2,3,4]: s=2 -> 0.5*3+0.5*2=2.5 -> 0.5*4+0.5*2.5=3.25
        assert buckets[4]["ma"]["value"] == pytest.approx(3.25)

    def test_holt_tracks_trend(self, series):
        r = _histo_with_movavg(series, "holt",
                               settings={"alpha": 0.8, "beta": 0.5})
        buckets = agg(r, "h")["buckets"]
        # the series is a clean +1 trend: holt must beat simple at the end
        assert buckets[5]["ma"]["value"] > 4.0

    def test_holt_winters_seasonal(self):
        idx = IndexService("hw", Settings({"index.number_of_shards": 1}))
        # period-2 seasonal series: 10, 2, 10, 2, ...
        vals = [10.0, 2.0] * 4
        for i, v in enumerate(vals):
            idx.index_doc(str(i), {"t": i * 10, "v": v})
        idx.refresh()
        r = idx.search({"size": 0, "aggs": {"h": {
            "histogram": {"field": "t", "interval": 10},
            "aggs": {"s": {"sum": {"field": "v"}},
                     "ma": {"moving_avg": {
                         "buckets_path": "s", "window": 8,
                         "model": "holt_winters",
                         "settings": {"period": 2, "alpha": 0.3, "beta": 0.1,
                                      "gamma": 0.3}}}},
        }}})
        buckets = agg(r, "h")["buckets"]
        # the seasonal model locks onto the period-2 cycle exactly:
        # bucket 6 is the high phase (10), bucket 7 the low phase (2)
        assert buckets[6]["ma"]["value"] == pytest.approx(10.0, abs=0.1)
        assert buckets[7]["ma"]["value"] == pytest.approx(2.0, abs=0.1)
        idx.close()

    def test_predict_date_histogram_key_as_string(self):
        idx = IndexService("dh", Settings({"index.number_of_shards": 1}))
        for i, d in enumerate(["2017-01-01", "2017-02-01", "2017-03-01"]):
            idx.index_doc(str(i), {"sold": d, "v": float(i + 1)})
        idx.refresh()
        r = idx.search({"size": 0, "aggs": {"h": {
            "date_histogram": {"field": "sold", "interval": "month"},
            "aggs": {"s": {"sum": {"field": "v"}},
                     "ma": {"moving_avg": {"buckets_path": "s", "window": 3,
                                           "predict": 1}}},
        }}})
        buckets = agg(r, "h")["buckets"]
        assert all("key_as_string" in b for b in buckets)
        idx.close()

    def test_predict_appends_buckets(self, series):
        r = _histo_with_movavg(series, "holt",
                               settings={"alpha": 0.8, "beta": 0.5}, predict=2)
        buckets = agg(r, "h")["buckets"]
        assert len(buckets) == 8  # 6 real + 2 predicted
        assert buckets[6]["doc_count"] == 0
        assert buckets[6]["key"] == pytest.approx(60.0)
        assert buckets[7]["key"] == pytest.approx(70.0)
        # +1 trend continues upward
        assert buckets[7]["ma"]["value"] > buckets[6]["ma"]["value"]
